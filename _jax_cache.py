"""Shared persistent-compile-cache setup (stdlib-only, import before jax).

One place owns the cache-dir choice for every entry point that compiles
device programs (bench.py, tests/conftest.py, tools/*, __graft_entry__):
repo-local `.jax_cache/` by preference — /tmp is wiped between build
sessions while the repo workspace persists, so a repo-local cache carries
warm compiles (200-300 s each over the tunnel) across sessions and into
the driver's end-of-round bench — falling back to /tmp when the repo dir
is missing OR unwritable (read-only checkout, foreign-owner dir).
"""
import os

_REPO = os.path.dirname(os.path.abspath(__file__))


def setup() -> str:
    """Point JAX_COMPILATION_CACHE_DIR at a writable persistent dir."""
    cache = os.path.join(_REPO, ".jax_cache")
    try:
        os.makedirs(cache, exist_ok=True)
        if not os.access(cache, os.W_OK):
            raise OSError("unwritable")
    except OSError:
        cache = "/tmp/gubernator_jax_cache"
        try:
            os.makedirs(cache, exist_ok=True)
        except OSError:
            pass
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    return os.environ["JAX_COMPILATION_CACHE_DIR"]

# gubernator-tpu service container.
# For TPU nodes, base this on a jax[tpu] image instead; the code is
# identical (jax picks the TPU backend automatically).
FROM python:3.12-slim

RUN pip install --no-cache-dir "jax[cpu]" numpy grpcio protobuf \
    prometheus-client cryptography setuptools

WORKDIR /app
COPY gubernator_tpu/ gubernator_tpu/
COPY example.conf /etc/gubernator/gubernator.conf

# C++ fast lane (batch hashing + protobuf wire codec); the service
# falls back to the pure-Python paths if the build is unavailable
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && python gubernator_tpu/ops/setup_native.py build_ext --inplace \
    && apt-get purge -y g++ && apt-get autoremove -y \
    && rm -rf /var/lib/apt/lists/* \
    || echo "native build unavailable; using pure-Python fallback"

ENV GUBER_GRPC_ADDRESS=0.0.0.0:1051 \
    GUBER_HTTP_ADDRESS=0.0.0.0:1050

EXPOSE 1050 1051 1052/udp
HEALTHCHECK --interval=15s --timeout=5s \
    CMD python -m gubernator_tpu.cmd.healthcheck \
        --url http://localhost:1050/v1/HealthCheck || exit 1

CMD ["python", "-m", "gubernator_tpu.cmd.daemon", \
     "--config", "/etc/gubernator/gubernator.conf"]

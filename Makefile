# gubernator-tpu build/test targets (reference: Makefile).

PY ?= python

.PHONY: test proto bench bench-pallas bench-tiered bench-diff chaos \
        scenarios fleet-audit tpu-session b-sweep daemon cluster lint \
        native tsan asan racer check clean

test:
	$(PY) -m pytest tests/ -q

# whole-program correctness suite (tools/guberlint/, see
# CONCURRENCY.md): guarded-by, lock order, GUBER_* env registry,
# faultpoint catalog, thread inventory, clock-domain taint,
# traced-code purity, retrace stability, operator-doc consistency.
# Zero violations at HEAD is a tier-1 invariant and the full suite
# must finish inside the pinned 30 s wall-clock budget — both
# enforced by tests/test_lint_clean.py.
lint:
	$(PY) -m tools.guberlint

# ThreadSanitizer build of ops/_native.cpp + the multithreaded native
# soak under it (tools/native_soak.py; suppressions: tools/tsan.supp).
# The production in-place .so is untouched — the instrumented build
# lands in build/tsan/.
tsan:
	GUBER_NATIVE_SAN=tsan $(PY) gubernator_tpu/ops/setup_native.py \
	    build_ext --build-lib build/tsan
	$(PY) tools/native_soak.py --san tsan

# AddressSanitizer twin of `make tsan` (build/asan/).
asan:
	GUBER_NATIVE_SAN=asan $(PY) gubernator_tpu/ops/setup_native.py \
	    build_ext --build-lib build/asan
	$(PY) tools/native_soak.py --san asan

# seeded interleaving harness: adversarial preemptions at the
# dispatcher merge/carry/splice faultpoints, conservation as oracle
racer:
	JAX_PLATFORMS=cpu $(PY) tools/racer.py --seed 1 --runs 2

# CI-style gate: static analysis + sanitizer soaks + the concurrency
# test subset + the compile-ledger gate (steady-state zero recompiles
# on the service path); the full tier-1 battery stays `make test`
check: lint tsan asan scenarios fleet-audit
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_guberlint.py \
	    tests/test_lint_clean.py tests/test_compileledger.py \
	    tests/test_created_at.py \
	    tests/test_cold_conservation.py tests/test_native.py \
	    tests/test_interval.py tests/test_dispatcher.py \
	    tests/test_scenarios.py -q

# the scenario lab's seeded fast subset (ISSUE 16): every spec in
# scenarios/ with its fast-mode overrides, every oracle armed
scenarios:
	JAX_PLATFORMS=cpu $(PY) tools/scenario_lab.py --fast

# faultpoint × {error,delay} matrix against an in-proc cluster; exits
# nonzero if any injected fault hangs the daemon or breaks recovery
chaos:
	$(PY) tools/chaos_matrix.py

# 3-daemon fleet conservation smoke (ISSUE 19, fleet.py): drive GLOBAL
# traffic, then fold every daemon's OWN GET /debug/audit vector and
# prove fleet drift == 0 at steady state with a consistent ring
fleet-audit:
	JAX_PLATFORMS=cpu $(PY) tools/fleet_audit_smoke.py

proto:
	cd gubernator_tpu/proto && protoc -I. --python_out=. \
	    gubernator.proto peers.proto

bench:
	$(PY) bench.py

# the fused-serving A/B row (11_pallas_serving) standalone: fused
# engine vs classic XLA on identical seeded wire traffic, with the
# PhaseLedger phase_deleted evidence (ISSUE 8)
bench-pallas:
	GUBER_BENCH_SECTION=pallas $(PY) bench.py

# the tiered-store capacity row (13_tiered_store) standalone: 1M-key
# seeded skewed traffic vs a 4K-row device cap + host cold tier,
# A/B'd byte-for-byte against an uncapped oracle (ISSUE 10)
bench-tiered:
	GUBER_BENCH_SECTION=tiered $(PY) bench.py

# perf-regression gate (ISSUE 13): diff the newest BENCH_r*.json
# against the previous round with per-metric tolerance; rows the run
# flagged environment-dominated (context/skipped_*/error) are skipped,
# truncated artifacts are declared incomparable (exit 0), regressions
# beyond tolerance exit 1
bench-diff:
	$(PY) tools/bench_compare.py

# one-shot on-chip validation battery (run when a TPU is reachable)
tpu-session:
	$(PY) tools/tpu_session.py

# headline-only device-batch sweep, e.g. make b-sweep B="131072 262144"
B ?= 131072
b-sweep:
	$(PY) tools/b_sweep.py $(B)

daemon:
	$(PY) -m gubernator_tpu.cmd.daemon --config example.conf

cluster:
	$(PY) -m gubernator_tpu.cmd.cluster --count 4

native:
	$(PY) gubernator_tpu/ops/setup_native.py build_ext --inplace

clean:
	rm -rf build dist *.egg-info gubernator_tpu/ops/*.so
	find . -name __pycache__ -type d -exec rm -rf {} +

# gubernator-tpu build/test targets (reference: Makefile).

PY ?= python

.PHONY: test proto bench chaos tpu-session b-sweep daemon cluster lint native clean

test:
	$(PY) -m pytest tests/ -q

# faultpoint × {error,delay} matrix against an in-proc cluster; exits
# nonzero if any injected fault hangs the daemon or breaks recovery
chaos:
	$(PY) tools/chaos_matrix.py

proto:
	cd gubernator_tpu/proto && protoc -I. --python_out=. \
	    gubernator.proto peers.proto

bench:
	$(PY) bench.py

# one-shot on-chip validation battery (run when a TPU is reachable)
tpu-session:
	$(PY) tools/tpu_session.py

# headline-only device-batch sweep, e.g. make b-sweep B="131072 262144"
B ?= 131072
b-sweep:
	$(PY) tools/b_sweep.py $(B)

daemon:
	$(PY) -m gubernator_tpu.cmd.daemon --config example.conf

cluster:
	$(PY) -m gubernator_tpu.cmd.cluster --count 4

native:
	$(PY) gubernator_tpu/ops/setup_native.py build_ext --inplace

clean:
	rm -rf build dist *.egg-info gubernator_tpu/ops/*.so
	find . -name __pycache__ -type d -exec rm -rf {} +

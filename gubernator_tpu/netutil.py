"""Address resolution helpers.

reference: net.go › ResolveHostIP / advertise-address discovery
(reconstructed).
"""
from __future__ import annotations

import socket


def split_host_port(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    if not port.isdigit():
        raise ValueError(f"address must be host:port, got {addr!r}")
    return host, int(port)


def resolve_host_ip(addr: str) -> str:
    """Resolve "host:port" to "ip:port"; 0.0.0.0/empty host becomes the
    first non-loopback local IP (the reference's advertise-address
    behavior when binding a wildcard)."""
    host, port = split_host_port(addr)
    if host in ("", "0.0.0.0", "::"):
        ip = local_ip()
    else:
        try:
            ip = socket.getaddrinfo(host, None, socket.AF_INET)[0][4][0]
        except socket.gaierror:
            ip = host
    return f"{ip}:{port}"


def local_ip() -> str:
    """Best-effort non-loopback local IP (no packets are sent)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("192.0.2.1", 9))  # TEST-NET; connect() on UDP is local
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (test cluster harness helper)."""
    s = socket.socket()
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()

"""Daemon: listeners + lifecycle around one V1Instance.

reference: daemon.go › Daemon / SpawnDaemon — reconstructed, mount
empty.  Serves:

- gRPC V1 + PeersV1 on ``grpc_listen_address`` (TLS optional),
- an HTTP/JSON gateway on ``http_listen_address`` mirroring the
  reference's grpc-gateway mux: POST /v1/GetRateLimits,
  GET /v1/HealthCheck, plus GET /metrics (prometheus), GET /healthz
  (``?deep=1`` adds dispatcher queue/wave/stall state), and
  GET /debug/events (the flight-recorder ring as JSON — see
  OBSERVABILITY.md),
- the configured discovery source wired to instance.set_peers.
"""
from __future__ import annotations

import json
import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

import grpc

from .config import DaemonConfig
from .discovery import make_discovery
from .dispatcher import ResourceExhausted, request_deadline
from .grpc_api import (add_health_servicer, add_peers_servicer_raw,
                       add_v1_servicer_raw)
from .instance import V1Instance
from .netutil import resolve_host_ip, split_host_port
from .proto import gubernator_pb2 as pb
from .proto import peers_pb2 as peers_pb
from .store import FileLoader
from .telemetry import exc_text
from .tlsutil import setup_tls
from .tracing import grpc_request_context, request_context, span
from .types import Behavior, PeerInfo, RateLimitRequest
from .wire import health_to_pb, req_from_pb, resp_to_pb

log = logging.getLogger("gubernator_tpu.daemon")


class _V1Servicer:
    def __init__(self, instance: V1Instance):
        self.instance = instance

    def GetRateLimits(self, request: pb.GetRateLimitsReq, context):
        with grpc_request_context(
                context, recorder=self.instance.span_recorder), \
                span("grpc.GetRateLimits", metrics=self.instance.metrics), \
                request_deadline(context.time_remaining()):
            try:
                reqs = [req_from_pb(m) for m in request.requests]
                resps = self.instance.get_rate_limits(reqs)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, exc_text(e))
            except ResourceExhausted as e:
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              exc_text(e))
            out = pb.GetRateLimitsResp()
            out.responses.extend(resp_to_pb(r) for r in resps)
            return out

    def GetRateLimitsWire(self, request: bytes, context):
        """Raw-bytes twin of GetRateLimits (grpc_api.add_v1_servicer_raw):
        lets the instance's C++ wire lane run decode→decide→encode
        without pb2 when the batch qualifies.  The caller's remaining
        deadline scopes deadline-aware admission shedding (ISSUE 5)."""
        with grpc_request_context(
                context, recorder=self.instance.span_recorder), \
                span("grpc.GetRateLimits", metrics=self.instance.metrics), \
                request_deadline(context.time_remaining()):
            try:
                return self.instance.get_rate_limits_wire(request)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, exc_text(e))
            except ResourceExhausted as e:
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              exc_text(e))

    def HealthCheck(self, request: pb.HealthCheckReq, context):
        return health_to_pb(self.instance.health_check())


class _PeersServicer:
    def __init__(self, instance: V1Instance):
        self.instance = instance

    def GetPeerRateLimits(self, request: peers_pb.GetPeerRateLimitsReq,
                          context):
        with grpc_request_context(
                context, recorder=self.instance.span_recorder), \
                span("grpc.GetPeerRateLimits",
                     metrics=self.instance.metrics):
            try:
                reqs = [req_from_pb(m) for m in request.requests]
                resps = self.instance.get_peer_rate_limits(reqs)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, exc_text(e))
            out = peers_pb.GetPeerRateLimitsResp()
            out.rate_limits.extend(resp_to_pb(r) for r in resps)
            return out

    def GetPeerRateLimitsWire(self, request: bytes, context):
        """Raw-bytes twin of GetPeerRateLimits (C++ wire lane)."""
        with grpc_request_context(
                context, recorder=self.instance.span_recorder), \
                span("grpc.GetPeerRateLimits",
                     metrics=self.instance.metrics), \
                request_deadline(context.time_remaining()):
            try:
                return self.instance.get_peer_rate_limits_wire(request)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, exc_text(e))
            except ResourceExhausted as e:
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              exc_text(e))

    def UpdatePeerGlobals(self, request: peers_pb.UpdatePeerGlobalsReq,
                          context):
        with grpc_request_context(
                context, recorder=self.instance.span_recorder), \
                span("grpc.UpdatePeerGlobals",
                     metrics=self.instance.metrics):
            self.instance.update_peer_globals(list(request.globals))
            return peers_pb.UpdatePeerGlobalsResp()


def _json_to_req(o: dict) -> RateLimitRequest:
    """Accept both snake_case and grpc-gateway camelCase field names."""

    def g(*names, default=None):
        for n in names:
            if n in o:
                return o[n]
        return default

    return RateLimitRequest(
        name=g("name", default=""),
        unique_key=g("unique_key", "uniqueKey", default=""),
        hits=int(g("hits", default=1)),
        limit=int(g("limit", default=0)),
        duration=int(g("duration", default=0)),
        algorithm=int(g("algorithm", default=0)),
        behavior=Behavior(int(g("behavior", default=0))),
        burst=int(g("burst", default=0)),
        metadata=g("metadata", default={}) or {},
    )


def _resp_to_json(r) -> dict:
    # grpc-gateway emits proto JSON names (camelCase); keep snake_case
    # too so existing simple clients keep working
    return {"status": int(r.status), "limit": r.limit,
            "remaining": r.remaining,
            "reset_time": r.reset_time, "resetTime": r.reset_time,
            "error": r.error, "metadata": r.metadata}


class Daemon:
    """reference: daemon.go › Daemon.  Use spawn_daemon() to construct."""

    def __init__(self, cfg: DaemonConfig, mesh=None, engine=None):
        from .tracing import DeviceProfiler

        self.cfg = cfg
        self.tls = setup_tls(cfg.tls)
        self._closed = False
        #: drain-aware shutdown (ISSUE 5): True from the moment close()
        #: starts; /healthz answers 503 "draining" for the grace window
        #: before the listeners stop
        self._draining = False
        self.profiler = DeviceProfiler.from_env()
        #: on-demand device profiling (GET /debug/profile?seconds=N):
        #: at most ONE capture at a time — jax.profiler is process-
        #: global, so a second start_trace would corrupt the first
        self._prof_mu = threading.Lock()
        self._runtime_prof: Optional[dict] = None
        self.instance: Optional[V1Instance] = None
        self.discovery = None
        self.http_server: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self.client_server: Optional[grpc.Server] = None
        self.client_port: int = 0

        # --- gRPC listener FIRST: an ephemeral port (":0") must be
        # resolved to the real bound port before the advertise address
        # (and thus peer identity / discovery) is derived from it.
        self.grpc_server = grpc.server(
            ThreadPoolExecutor(max_workers=32),
            options=[("grpc.so_reuseport", 0)])
        if self.tls is not None:
            bound = self.grpc_server.add_secure_port(
                cfg.grpc_listen_address, self.tls.grpc_server_credentials())
        else:
            bound = self.grpc_server.add_insecure_port(cfg.grpc_listen_address)
        if bound == 0:
            raise OSError(f"failed to bind {cfg.grpc_listen_address}")
        self.grpc_port = bound

        try:
            icfg = cfg.instance_config()
            host, _ = split_host_port(cfg.grpc_listen_address)
            adv = icfg.advertise_address or f"{host}:{bound}"
            adv_host, adv_port = split_host_port(adv)
            if adv_port == 0:
                adv = f"{adv_host}:{bound}"
            icfg.advertise_address = resolve_host_ip(adv)
            self.advertise_address = icfg.advertise_address
            if cfg.snapshot_path:
                icfg.loader = FileLoader(cfg.snapshot_path)
            peer_creds = (self.tls.grpc_client_credentials()
                          if self.tls is not None else None)
            self.instance = V1Instance(icfg, mesh=mesh, engine=engine,
                                       peer_tls_creds=peer_creds)
            # Warm-up: compile the device step before serving (first
            # compile is tens of seconds; an RPC must not eat that).
            self.instance.get_rate_limits(
                [RateLimitRequest(name="_warmup", unique_key="w", hits=0,
                                  limit=1, duration=1000)])
            import jax

            if (hasattr(self.instance.engine, "warmup")
                    and jax.default_backend() == "tpu"):
                # every wave bucket, so a first coalesced burst never
                # eats a minutes-scale cold compile inside an RPC.  Off
                # TPU a bucket compiles in milliseconds on first use,
                # not worth taxing every (test) daemon startup.
                self.instance.engine.warmup()
            add_v1_servicer_raw(self.grpc_server,
                                _V1Servicer(self.instance))
            add_peers_servicer_raw(self.grpc_server,
                                   _PeersServicer(self.instance))
            add_health_servicer(self.grpc_server, self.instance)

            if cfg.client_listen_address:
                # Shared front door: V1 (+ health) on a SO_REUSEPORT
                # socket so sibling daemon processes on this host can
                # bind the same address and split inbound connections.
                # Peer traffic stays on the unique grpc_listen_address —
                # the ring needs per-process identities.  Bound BEFORE
                # the peer server starts: readiness probes watch the
                # peer port's health service, and SERVING there must
                # imply the front door is already accepting.
                self.client_server = grpc.server(
                    ThreadPoolExecutor(max_workers=32),
                    options=[("grpc.so_reuseport", 1)])
                add_v1_servicer_raw(self.client_server,
                                    _V1Servicer(self.instance))
                add_health_servicer(self.client_server, self.instance)
                if self.tls is not None:
                    cbound = self.client_server.add_secure_port(
                        cfg.client_listen_address,
                        self.tls.grpc_server_credentials())
                else:
                    cbound = self.client_server.add_insecure_port(
                        cfg.client_listen_address)
                if cbound == 0:
                    raise OSError(
                        f"failed to bind client address "
                        f"{cfg.client_listen_address} (SO_REUSEPORT)")
                self.client_port = cbound
                self.client_server.start()
            self.grpc_server.start()

            if cfg.http_listen_address:
                self._start_http(cfg.http_listen_address)

            self_info = PeerInfo(grpc_address=self.advertise_address,
                                 http_address=cfg.http_listen_address,
                                 datacenter=cfg.data_center)
            self.discovery = make_discovery(cfg, self_info,
                                            self.instance.set_peers)
        except BaseException:
            # Don't leak live listeners/threads from a half-built daemon.
            self._teardown()
            raise

    # ---- HTTP gateway ---------------------------------------------------

    def _start_http(self, addr: str) -> None:
        host, port = split_host_port(addr)
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                log.debug("http: " + fmt, *args)

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import parse_qs, urlsplit

                parts = urlsplit(self.path)
                path, q = parts.path, parse_qs(parts.query)
                if path == "/metrics":
                    ana = daemon.instance.analytics
                    if ana is not None:
                        # scrape-time top-K gauge refresh: the label
                        # churn (≤ K removes + sets) costs the scraper,
                        # never the serving loop or analytics worker
                        ana.republish()
                    led = getattr(daemon.instance, "memledger", None)
                    if led is not None:
                        # same scrape-time discipline for the ledger
                        # gauges: probes run on the scraper's dime
                        led.republish(daemon.instance.metrics)
                    self._send(200, daemon.instance.metrics.render(),
                               "text/plain; version=0.0.4")
                elif path in ("/v1/HealthCheck", "/healthz"):
                    if daemon._draining:
                        # drain-aware probe (ISSUE 5): load balancers
                        # must stop routing BEFORE the listener dies
                        self._send(503, json.dumps(
                            {"status": "draining",
                             "message": "daemon is shutting down",
                             "peer_count": len(
                                 daemon.instance.peers())}).encode())
                        return
                    h = daemon.instance.health_check()
                    code = 200 if h.status == "healthy" else 503
                    body = {"status": h.status, "message": h.message,
                            "peer_count": h.peer_count}
                    if q.get("deep", ["0"])[-1] not in ("", "0", "false"):
                        # deep mode: dispatcher queue depth, last-wave
                        # age, stalled state — the stall watchdog's
                        # view, for probes that want a diagnosis and
                        # not just liveness (cmd/healthcheck.py --deep)
                        body["dispatcher"] = \
                            daemon.instance.dispatcher.debug_stats()
                        # per-peer send-lane + circuit state (ISSUE 3):
                        # a backed-up buffer or an open circuit is the
                        # forward hop's stall signal
                        peers_blk = {}
                        for p in daemon.instance.peers():
                            if hasattr(p, "lane_stats"):
                                peers_blk[p.info.grpc_address] = \
                                    p.lane_stats()
                        body["peers"] = peers_blk
                        # SLO verdicts (ISSUE 11): breached / burning
                        # objectives — the --fail-on-burn readiness feed
                        if daemon.instance.slo is not None:
                            body["slo"] = daemon.instance.slo.health()
                        # device-memory ledger totals (ISSUE 13): the
                        # pressure fraction a capacity probe wants
                        led = getattr(daemon.instance, "memledger",
                                      None)
                        if led is not None:
                            snap = led.snapshot()
                            body["memory"] = {
                                "device_bytes": snap["device_bytes"],
                                "host_bytes": snap["host_bytes"],
                                "pressure": snap["pressure"],
                                "pressure_target":
                                    snap["pressure_target"]}
                    self._send(code, json.dumps(body).encode())
                elif path == "/debug/events":
                    # flight recorder ring (telemetry.py), newest-last;
                    # ?limit=N keeps only the newest N events; ?kind=K,
                    # ?since_seq=S, ?tenant=T and ?trace=ID filter
                    # SERVER-side so a polling CLI doesn't re-download
                    # the whole ring
                    try:
                        limit = int(q.get("limit", ["0"])[-1]) or None
                    except ValueError:
                        limit = None
                    kind = q.get("kind", [""])[-1] or None
                    try:
                        since = int(q.get("since_seq", ["0"])[-1]) or None
                    except ValueError:
                        since = None
                    tenant = q.get("tenant", [""])[-1] or None
                    trace = q.get("trace", [""])[-1] or None
                    self._send(200, json.dumps({
                        "events": daemon.instance.recorder.events(
                            limit=limit, kind=kind, since_seq=since,
                            tenant=tenant, trace=trace)}).encode())
                elif path == "/debug/traces":
                    # trace plane (ISSUE 12, tracing.py): the span
                    # recorder's committed ring as JSON — one daemon's
                    # SLICE of each trace; tools/trace_assemble.py (or
                    # guber-cli debug traces --waterfall) stitches N
                    # daemons' slices into the cluster-wide tree
                    rec = daemon.instance.span_recorder
                    if rec is None:
                        self._send(404, json.dumps(
                            {"error": "tracing disabled"}).encode())
                        return
                    try:
                        limit = int(q.get("limit", ["0"])[-1]) or None
                    except ValueError:
                        limit = None
                    tid = q.get("trace_id", [""])[-1] or None
                    st = rec.stats()
                    body = {"sample": st["sample"],
                            "capacity": st["capacity"],
                            "dropped": st["dropped"],
                            "spans": rec.spans(trace_id=tid,
                                               limit=limit)}
                    self._send(200, json.dumps(body).encode())
                elif path == "/debug/topkeys":
                    # heavy-hitter ledger (analytics.py): the current
                    # top-K keys with hits / over-limit / error bound /
                    # last-seen, plus each key's ring owner when
                    # hash-level routing is valid
                    ana = daemon.instance.analytics
                    if ana is None:
                        self._send(404, json.dumps(
                            {"error": "analytics disabled "
                                      "(GUBER_ANALYTICS=0)"}).encode())
                        return
                    try:
                        limit = int(q.get("limit", ["0"])[-1]) or None
                    except ValueError:
                        limit = None
                    ana.flush(timeout=2.0)  # fold queued taps first
                    snap = ana.topkeys_snapshot(limit)
                    for e in snap["keys"]:
                        e["owner"] = daemon.instance.owner_addr_by_khash(
                            int(e["khash"], 16))
                    self._send(200, json.dumps(snap).encode())
                elif path == "/debug/phases":
                    # per-phase latency attribution (analytics.py ›
                    # PhaseLedger) + the wave-duration reference the
                    # in-wave phases partition
                    ana = daemon.instance.analytics
                    if ana is None:
                        self._send(404, json.dumps(
                            {"error": "analytics disabled "
                                      "(GUBER_ANALYTICS=0)"}).encode())
                        return
                    body = ana.phases_snapshot()
                    tel = daemon.instance.dispatcher.telemetry_snapshot()
                    body["waves"] = {
                        k: tel.get(k) for k in
                        ("waves", "wave_duration_p50_ms",
                         "wave_duration_p99_ms", "queue_wait_p50_ms",
                         "queue_wait_p99_ms")}
                    self._send(200, json.dumps(body).encode())
                elif path == "/debug/tenants":
                    # per-tenant RED ledger (analytics.py ›
                    # TenantLedger): bounded-cardinality request /
                    # over-limit / error / degraded / shed attribution
                    ana = daemon.instance.analytics
                    if ana is None:
                        self._send(404, json.dumps(
                            {"error": "analytics disabled "
                                      "(GUBER_ANALYTICS=0)"}).encode())
                        return
                    ana.flush(timeout=2.0)  # fold queued taps first
                    self._send(200, json.dumps(
                        ana.tenants_snapshot()).encode())
                elif path == "/debug/audit":
                    # conservation audit vector (fleet.py): per-lane
                    # injected/applied/queued/in-flight counters, the
                    # drift they prove, and the ring view the fleet
                    # fold cross-checks.  Always served — the auditor
                    # rides the GLOBAL lanes' own accounting (a
                    # GUBER_FLEET_AUDIT=0 daemon reports enabled=false
                    # with zeroed lanes rather than 404ing, so a fleet
                    # fold over a mixed cluster still completes)
                    self._send(200, json.dumps(
                        daemon.instance.audit_doc()).encode())
                elif path == "/debug/slo":
                    # SLO registry + live burn rates (slo.py)
                    if daemon.instance.slo is None:
                        self._send(404, json.dumps(
                            {"error": "slo engine disabled "
                                      "(GUBER_SLO=0)"}).encode())
                        return
                    self._send(200, json.dumps(
                        daemon.instance.slo.snapshot()).encode())
                elif path == "/debug/memory":
                    # device-memory ledger (ISSUE 13, memledger.py):
                    # per-consumer bytes / capacity / occupancy /
                    # demand vector; ?advise=1 adds the water-filling
                    # split recommendation (advisory — nothing
                    # repartitions live)
                    led = getattr(daemon.instance, "memledger", None)
                    if led is None:
                        self._send(404, json.dumps(
                            {"error": "memory ledger disabled "
                                      "(GUBER_MEM_LEDGER=0)"}).encode())
                        return
                    body = led.snapshot()
                    if q.get("advise", ["0"])[-1] not in ("", "0",
                                                          "false"):
                        body["advise"] = led.advise()
                    self._send(200, json.dumps(body).encode())
                elif path == "/debug/costmodel":
                    # fitted collective cost model (analytics.py ›
                    # CostModel): per-(phase, ndev) alpha/beta
                    ana = daemon.instance.analytics
                    if ana is None:
                        self._send(404, json.dumps(
                            {"error": "analytics disabled "
                                      "(GUBER_ANALYTICS=0)"}).encode())
                        return
                    self._send(200, json.dumps(
                        ana.costmodel_snapshot()).encode())
                elif path == "/debug/profile":
                    code, body = daemon._handle_profile(q)
                    self._send(code, json.dumps(body).encode())
                elif path == "/debug/faults":
                    # fault-injection state (faults.py): armed spec,
                    # per-point check/fire counters, catalog
                    self._send(200, json.dumps(
                        daemon.instance.faults.describe()).encode())
                else:
                    self._send(404, b'{"error":"not found"}')

            def do_POST(self):
                if self.path == "/debug/faults":
                    # arm/clear faultpoints at runtime (chaos drills):
                    # {"spec": "peer_send:error:0.3", "seed": 7} or
                    # {"clear": true}
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                        payload = json.loads(
                            self.rfile.read(length) or b"{}")
                        if payload.get("clear"):
                            out = daemon.instance.faults.clear()
                        else:
                            out = daemon.instance.faults.arm(
                                payload.get("spec", ""),
                                seed=payload.get("seed"))
                    except (ValueError, TypeError) as e:
                        self._send(400, json.dumps(
                            {"error": exc_text(e)}).encode())
                        return
                    self._send(200, json.dumps(out).encode())
                    return
                if self.path not in ("/v1/GetRateLimits",
                                     "/v1/V1/GetRateLimits"):
                    self._send(404, b'{"error":"not found"}')
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    reqs = [_json_to_req(o)
                            for o in payload.get("requests", [])]
                    with request_context(
                            self.headers.get("traceparent"),
                            recorder=daemon.instance.span_recorder), \
                            span("http.GetRateLimits",
                                 metrics=daemon.instance.metrics):
                        resps = daemon.instance.get_rate_limits(reqs)
                except ValueError as e:
                    self._send(400, json.dumps(
                        {"error": exc_text(e)}).encode())
                    return
                except ResourceExhausted as e:
                    # admission shed / drain: 429, the HTTP analog of
                    # grpc RESOURCE_EXHAUSTED
                    self._send(429, json.dumps(
                        {"error": exc_text(e)}).encode())
                    return
                self._send(200, json.dumps({
                    "responses": [_resp_to_json(r) for r in resps]}).encode())

        self.http_server = ThreadingHTTPServer((host, port), Handler)
        if self.tls is not None:
            self.http_server.socket = self.tls.http_ssl_context().wrap_socket(
                self.http_server.socket, server_side=True)
        self.http_port = self.http_server.server_address[1]
        self._http_thread = threading.Thread(
            target=self.http_server.serve_forever, daemon=True,
            name=f"http-{addr}")
        self._http_thread.start()

    # ---- on-demand device profiling (GET /debug/profile) ----------------

    #: hard cap on a runtime capture: profiling taxes the serving loop
    #: and the trace grows with time — an unbounded capture left running
    #: would eventually wedge the daemon's disk
    PROFILE_MAX_SECONDS = 300.0

    def _handle_profile(self, q: dict):
        """``?seconds=N`` starts a DeviceProfiler capture for N seconds
        into a fresh directory (409 while any capture — runtime or the
        GUBER_PROFILE_DIR startup one — is active); without ``seconds``
        it reports capture status.  Returns (http_code, json_body)."""
        from .tracing import DeviceProfiler

        raw = q.get("seconds", [""])[-1]
        with self._prof_mu:
            active = (self._runtime_prof is not None
                      and not self._runtime_prof["done"].is_set())
            if not raw:
                body = {"active": active}
                if self._runtime_prof is not None:
                    body.update({
                        "dir": self._runtime_prof["dir"],
                        "seconds": self._runtime_prof["seconds"]})
                elif self.profiler is not None:
                    body.update({"active": True,
                                 "dir": self.profiler.log_dir,
                                 "startup_env": True})
                return 200, body
            try:
                seconds = float(raw)
            except ValueError:
                return 400, {"error": f"invalid seconds={raw!r}"}
            if not (0 < seconds <= self.PROFILE_MAX_SECONDS):
                return 400, {"error": f"seconds must be in (0, "
                                      f"{self.PROFILE_MAX_SECONDS:.0f}]"}
            if active or self.profiler is not None:
                # one capture at a time: jax.profiler is process-global
                return 409, {"error": "a profile capture is already "
                                      "active"}
            import tempfile

            log_dir = tempfile.mkdtemp(prefix="guber_profile_")
            try:
                prof = DeviceProfiler(log_dir)
            except Exception as e:  # noqa: BLE001 - surfaced to caller
                return 500, {"error": f"profiler start failed: "
                                      f"{exc_text(e)}"}
            done = threading.Event()
            state = {"profiler": prof, "dir": log_dir,
                     "seconds": seconds, "done": done}
            self._runtime_prof = state
        self.instance.recorder.record("profile_start", dir=log_dir,
                                      seconds=seconds)

        def _stop_later():
            done.wait(seconds)  # close() can cut the capture short
            try:
                prof.stop()
            finally:
                done.set()
                self.instance.recorder.record("profile_stop",
                                              dir=log_dir)

        t = threading.Thread(target=_stop_later, daemon=True,
                             name="debug-profile-stop")
        state["thread"] = t
        t.start()
        return 200, {"profiling": True, "dir": log_dir,
                     "seconds": seconds}

    # ---- lifecycle ------------------------------------------------------

    def set_peers(self, infos: List[PeerInfo]) -> None:
        self.instance.set_peers(infos)

    def peer_info(self) -> PeerInfo:
        return PeerInfo(grpc_address=self.advertise_address,
                        http_address=self.cfg.http_listen_address,
                        datacenter=self.cfg.data_center)

    def close(self) -> None:
        """Graceful shutdown (daemon.go › Daemon.Close, SURVEY.md §3.5).

        Drain FIRST (ISSUE 5): /healthz flips to 503 "draining", the
        dispatcher sheds new ingress with RESOURCE_EXHAUSTED, and the
        listeners stay up for ``drain_grace_ms`` so load balancers stop
        routing before connections die.  Then listeners stop, so no
        request lands after the instance has flushed its async managers
        and written the Loader snapshot — mutations during the shutdown
        window would be lost on restart."""
        if self._closed:
            return
        self._closed = True
        import time as _time

        self._draining = True
        if self.instance is not None:
            self.instance.recorder.record(
                "drain_started", grace_ms=self.cfg.drain_grace_ms)
            self.instance.metrics.draining.set(1)
            # during the grace window requests still SERVE (the point
            # is to let load balancers notice the 503 probe first);
            # only after it does the dispatcher shed new ingress
            grace = max(int(getattr(self.cfg, "drain_grace_ms", 0)), 0)
            if grace > 0:
                _time.sleep(grace / 1000.0)
            self.instance.dispatcher.drain()
        self._teardown()
        if self.instance is not None:
            self.instance.recorder.record("drain_completed")

    def _teardown(self) -> None:
        if self.discovery is not None:
            self.discovery.close()
        if self.client_server is not None:
            self.client_server.stop(grace=2).wait(timeout=5)
        self.grpc_server.stop(grace=2).wait(timeout=5)
        if self.http_server is not None:
            self.http_server.shutdown()
            self.http_server.server_close()
        if self.instance is not None:
            self.instance.close()
        if self.profiler is not None:
            self.profiler.stop()
        with self._prof_mu:
            rp = self._runtime_prof
        if rp is not None and not rp["done"].is_set():
            # cut a running on-demand capture short; its stop thread
            # owns the actual profiler.stop() (single stop path)
            rp["done"].set()
            t = rp.get("thread")
            if t is not None:
                t.join(timeout=5)


def spawn_daemon(cfg: DaemonConfig, mesh=None, engine=None) -> Daemon:
    """reference: daemon.go › SpawnDaemon."""
    d = Daemon(cfg, mesh=mesh, engine=engine)
    log.info("gubernator-tpu daemon up: grpc=%s http=%s advertise=%s",
             cfg.grpc_listen_address, cfg.http_listen_address,
             d.advertise_address)
    return d

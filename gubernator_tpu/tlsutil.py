"""TLS setup: file-based certs or in-memory self-signed AutoTLS.

reference: tls.go › SetupTLS / TLSConfig — reconstructed, mount empty.
AutoTLS generates a throwaway CA + server cert (SAN: localhost,
127.0.0.1, hostname) exactly for the reference's "just encrypt my lab
cluster" use case; client-auth modes mirror crypto/tls.ClientAuthType.
"""
from __future__ import annotations

import datetime
import os
import ssl
import tempfile
from dataclasses import dataclass
from typing import Optional

from .config import TLSSettings

try:
    import grpc
except ImportError:  # pragma: no cover - grpc is present in this image
    grpc = None


@dataclass
class TLSContext:
    """Materialized TLS state shared by the gRPC and HTTP listeners."""

    settings: TLSSettings
    ca_pem: bytes = b""
    cert_pem: bytes = b""
    key_pem: bytes = b""
    client_ca_pem: bytes = b""

    def grpc_server_credentials(self):
        require = self.settings.client_auth in ("require-any", "verify")
        root = self.client_ca_pem or self.ca_pem
        return grpc.ssl_server_credentials(
            [(self.key_pem, self.cert_pem)],
            root_certificates=root if require else None,
            require_client_auth=require)

    def grpc_client_credentials(self):
        """Credentials peers/clients use to dial a TLS daemon.  With
        client-auth enabled the server cert doubles as the client cert
        (peers authenticate with their own daemon cert, as AutoTLS
        deployments of the reference do)."""
        require = self.settings.client_auth in ("require-any", "verify")
        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_pem or None,
            private_key=self.key_pem if require else None,
            certificate_chain=self.cert_pem if require else None)

    def http_ssl_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        # ssl.load_cert_chain requires file paths; stage the PEMs in a
        # temp dir and remove it immediately after loading (the private
        # key must not outlive this call on disk).
        with tempfile.TemporaryDirectory(prefix="gubtls-") as d:
            cert, key = os.path.join(d, "c.pem"), os.path.join(d, "k.pem")
            with open(cert, "wb") as f:
                f.write(self.cert_pem)
            with open(key, "wb") as f:
                os.fchmod(f.fileno(), 0o600)
                f.write(self.key_pem)
            ctx.load_cert_chain(cert, key)
            if self.settings.client_auth in ("require-any", "verify"):
                ctx.verify_mode = ssl.CERT_REQUIRED
                ca = os.path.join(d, "ca.pem")
                with open(ca, "wb") as f:
                    f.write(self.client_ca_pem or self.ca_pem)
                ctx.load_verify_locations(ca)
        return ctx


def setup_tls(settings: Optional[TLSSettings]) -> Optional[TLSContext]:
    """reference: tls.go › SetupTLS."""
    if settings is None:
        return None
    ctx = TLSContext(settings=settings)
    if settings.auto_tls and not settings.cert_file:
        _generate_auto_tls(ctx)
    else:
        with open(settings.cert_file, "rb") as f:
            ctx.cert_pem = f.read()
        with open(settings.key_file, "rb") as f:
            ctx.key_pem = f.read()
        if settings.ca_file:
            with open(settings.ca_file, "rb") as f:
                ctx.ca_pem = f.read()
    if settings.client_auth_ca_file:
        with open(settings.client_auth_ca_file, "rb") as f:
            ctx.client_ca_pem = f.read()
    return ctx


def _generate_auto_tls(ctx: TLSContext) -> None:
    """Self-signed CA + server cert (tls.go AutoTLS analog)."""
    import socket

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    def make_key():
        return ec.generate_private_key(ec.SECP256R1())

    now = datetime.datetime.now(datetime.timezone.utc)
    ca_key = make_key()
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                            "gubernator-tpu-auto-ca")])
    ca_cert = (x509.CertificateBuilder()
               .subject_name(ca_name).issuer_name(ca_name)
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(minutes=5))
               .not_valid_after(now + datetime.timedelta(days=365))
               .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))

    key = make_key()
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                         "gubernator-tpu")])
    san = x509.SubjectAlternativeName([
        x509.DNSName("localhost"),
        x509.DNSName(socket.gethostname()),
        x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1")),
    ])
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(ca_name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(san, critical=False)
            .sign(ca_key, hashes.SHA256()))

    pem = serialization.Encoding.PEM
    ctx.ca_pem = ca_cert.public_bytes(pem)
    ctx.cert_pem = cert.public_bytes(pem) + ctx.ca_pem
    ctx.key_pem = key.private_bytes(
        pem, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())

"""SLO plane: declarative objectives evaluated in-process with
multi-window burn rates (ISSUE 11).

The repo has carried every raw signal an operator needs — the phase
ledger's latency percentiles, the mesh-GLOBAL staleness gauge, the
degraded/shed counters, the per-tenant RED ledger — without a layer
that turns them into VERDICTS.  This module is that layer: a registry
of SLOs, each a cheap source callable, evaluated on a fixed tick with
the multi-window burn-rate discipline from the SRE workbook:

- an SLO's **error budget** is ``1 - objective`` (objective 0.999 →
  budget 0.1%);
- the **burn rate** over a window is the bad-event fraction in that
  window divided by the budget (burn 1.0 = spending exactly the
  budget; burn 10 = ten times too fast);
- a **breach** fires only when BOTH the fast and the slow window burn
  past the threshold (the fast window gives reaction speed, the slow
  window rides out blips), and **recovery** fires when the fast
  window's burn drops back under it.

Two source shapes:

- ``ratio`` sources return cumulative ``(bad, total)`` counters (e.g.
  shed rows vs admitted rows): the window burn is the delta-ratio
  over the window.
- ``threshold`` sources return an instantaneous ``(value, target)``
  pair (e.g. staleness seconds vs the reconcile interval): each tick
  contributes one bad event when ``value > target``, so the burn is
  the fraction of recent ticks spent out of bounds over the budget.

Per-tenant SLOs register as a **group**: one source returning
``{tenant: (bad, total)}`` snapshots (bounded cardinality — the
tenant ledger folds overflow into ``__other__``), evaluated per
tenant with the same windows.

Verdicts surface as ``gubernator_slo_burn{slo,tenant}`` gauges,
``slo_breach``/``slo_recovered`` flight-recorder events,
``GET /debug/slo``, the ``?deep=1`` healthz block, and the
``healthcheck --fail-on-burn`` readiness hook.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

#: declarative catalog of the SLOs every instance registers
#: (instance.py › _build_slos).  OBSERVABILITY.md's "SLO catalog &
#: burn windows" table mirrors this dict EXACTLY — tools/
#: check_metrics.py lints the two against each other both ways.
SLO_CATALOG: Dict[str, str] = {
    "decision_p99": "device-phase p99 latency vs GUBER_SLO_P99_MS "
                    "(the decision kernel's tail)",
    "global_staleness": "mesh-GLOBAL coherence staleness vs 2× the "
                        "reconcile interval (grpc mode reports 0)",
    "error_ratio": "error + degraded rows / total attributed rows",
    "shed_ratio": "admission-shed rows / (attributed + shed) rows",
    "tenant_error_ratio": "per-tenant error + degraded rows / that "
                          "tenant's rows",
    "tenant_shed_ratio": "per-tenant shed rows / that tenant's "
                         "(attributed + shed) rows",
    "hbm_pressure": "byte-weighted device occupancy fraction vs "
                    "GUBER_MEM_PRESSURE (fires before table-full / "
                    "cap-overflow starts demoting)",
    "fleet_conservation": "seconds the GLOBAL audit drift (injected "
                          "minus applied, both backends) has been "
                          "nonzero vs the one-flush-window bound "
                          "(2x GUBER_GLOBAL_SYNC_WAIT or "
                          "GUBER_FLEET_DRIFT_BOUND)",
}

DEFAULT_FAST_S = 60.0
DEFAULT_SLOW_S = 300.0
DEFAULT_BURN_THRESHOLD = 2.0


class SLO:
    """One declarative objective.  ``source`` is a cheap callable:
    ratio kind → cumulative ``(bad, total)``; threshold kind →
    instantaneous ``(value, target)``."""

    __slots__ = ("name", "kind", "objective", "source", "description",
                 "budget")

    def __init__(self, name: str, kind: str, objective: float,
                 source: Callable[[], Tuple[float, float]],
                 description: str = ""):
        if kind not in ("ratio", "threshold"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        self.name = name
        self.kind = kind
        self.objective = float(objective)
        self.source = source
        self.description = description
        #: error budget: the tolerated bad fraction
        self.budget = max(1.0 - self.objective, 1e-9)


class _Track:
    """Window state for one (slo, tenant) series: a deque of
    ``(t, bad_cum, total_cum)`` samples plus the breach latch."""

    __slots__ = ("samples", "breached", "since", "last_value",
                 "last_target")

    def __init__(self):
        self.samples: deque = deque()
        self.breached = False
        self.since: Optional[float] = None
        self.last_value: Optional[float] = None
        self.last_target: Optional[float] = None

    def append(self, t: float, bad: float, total: float,
               keep_s: float) -> None:
        self.samples.append((t, float(bad), float(total)))
        cutoff = t - keep_s
        s = self.samples
        # keep one sample OLDER than the slow window so the window
        # delta has a baseline even exactly at the horizon
        while len(s) > 2 and s[1][0] <= cutoff:
            s.popleft()

    def burn(self, now: float, window_s: float, budget: float) -> float:
        """Bad-fraction over the trailing window / budget.  The
        baseline is the newest sample at or older than the window
        start (falling back to the oldest sample while uptime is
        shorter than the window — standard early-life behavior)."""
        s = self.samples
        if len(s) < 2:
            return 0.0
        cut = now - window_s
        base = s[0]
        for smp in s:
            if smp[0] <= cut:
                base = smp
            else:
                break
        t1, b1, n1 = s[-1]
        _, b0, n0 = base
        dn = n1 - n0
        if dn <= 0:
            return 0.0
        frac = max(b1 - b0, 0.0) / dn
        return frac / budget


class SLOEngine:
    """The in-process evaluator: ``tick()`` samples every registered
    source, updates burn gauges, and latches breach/recovery events
    into the flight recorder.  Thread-safe: tick runs on its
    IntervalLoop; ``snapshot``/``health`` serve HTTP threads."""

    def __init__(self, metrics=None, recorder=None,
                 fast_s: float = DEFAULT_FAST_S,
                 slow_s: float = DEFAULT_SLOW_S,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 clock=time.monotonic, exemplar=None):
        self.metrics = metrics
        self.recorder = recorder
        #: optional zero-arg callable → recent sampled trace id
        #: (ISSUE 12): breach events carry it as ``exemplar_trace`` so
        #: a burning SLO links to one concrete trace
        self.exemplar = exemplar
        self.fast_s = max(float(fast_s), 1e-3)
        self.slow_s = max(float(slow_s), self.fast_s)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._mu = threading.Lock()
        self._slos: List[SLO] = []  # guarded-by: self._mu
        #: per-tenant groups: name → (objective, kind, source, desc)
        self._groups: Dict[str, tuple] = {}  # guarded-by: self._mu
        #: (slo_name, tenant-or-None) → _Track
        self._tracks: Dict[tuple, _Track] = {}  # guarded-by: self._mu
        self._ticks = 0  # guarded-by: self._mu
        #: (slo, tenant) label pairs currently exported (bounded-label
        #: gauge discipline: departed series are removed first)
        self._published: set = set()  # guarded-by: self._mu

    # ---- registration ---------------------------------------------------

    def register(self, slo: SLO) -> None:
        with self._mu:
            self._slos.append(slo)

    def register_group(self, name: str, objective: float,
                       source: Callable[[], Dict[str, tuple]],
                       description: str = "") -> None:
        """Per-tenant family: ``source`` returns ``{tenant: (bad,
        total)}`` cumulative snapshots (bounded cardinality — the
        caller's ledger caps the tenant set)."""
        with self._mu:
            self._groups[name] = (float(objective), source, description)

    def names(self) -> List[str]:
        with self._mu:
            return ([s.name for s in self._slos]
                    + list(self._groups))

    # ---- evaluation -----------------------------------------------------

    def _eval_one(self, name: str, kind: str, budget: float,
                  tenant: Optional[str], bad: float, total: float,
                  now: float, events: list, value=None, target=None
                  ) -> dict:
        tr = self._tracks.get((name, tenant))  # lock-free: caller holds self._mu (tick)
        if tr is None:
            tr = self._tracks[(name, tenant)] = _Track()  # lock-free: caller holds self._mu (tick)
        if kind == "threshold":
            # synthesize cumulative counters: one event per tick,
            # bad when out of bounds
            prev = tr.samples[-1] if tr.samples else (now, 0.0, 0.0)
            tr.last_value, tr.last_target = value, target
            bad = prev[1] + (1.0 if bad else 0.0)
            total = prev[2] + 1.0
        tr.append(now, bad, total, self.slow_s * 1.5)
        fast = tr.burn(now, self.fast_s, budget)
        slow = tr.burn(now, self.slow_s, budget)
        thr = self.burn_threshold
        if not tr.breached and fast > thr and slow > thr:
            tr.breached = True
            tr.since = now
            events.append(("slo_breach", name, tenant, fast, slow))
        elif tr.breached and fast < thr:
            tr.breached = False
            tr.since = now
            events.append(("slo_recovered", name, tenant, fast, slow))
        return {"slo": name, "tenant": tenant, "fast_burn": fast,
                "slow_burn": slow, "breached": tr.breached,
                "value": tr.last_value, "target": tr.last_target}

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass; returns the per-series verdicts (the
        snapshot cache) — IntervalLoop-driven in the daemon, called
        directly by tests/chaos with a fake clock."""
        now = self._clock() if now is None else now
        events: list = []
        rows: List[dict] = []
        with self._mu:
            self._ticks += 1
            for slo in self._slos:
                try:
                    a, b = slo.source()
                except Exception:  # pragma: no cover - source must
                    continue  # never kill the tick
                if slo.kind == "threshold":
                    rows.append(self._eval_one(
                        slo.name, "threshold", slo.budget, None,
                        float(a) > float(b), 0.0, now, events,
                        value=float(a), target=float(b)))
                else:
                    rows.append(self._eval_one(
                        slo.name, "ratio", slo.budget, None,
                        float(a), float(b), now, events))
            for name, (objective, source, _d) in self._groups.items():
                budget = max(1.0 - objective, 1e-9)
                try:
                    per_tenant = source()
                except Exception:  # pragma: no cover
                    continue
                for tenant, (a, b) in per_tenant.items():
                    rows.append(self._eval_one(
                        name, "ratio", budget, tenant,
                        float(a), float(b), now, events))
            self._publish_locked(rows)
        rec = self.recorder
        if rec is not None:
            for kind, name, tenant, fast, slow in events:
                ev = {"slo": name, "fast_burn": round(fast, 3),
                      "slow_burn": round(slow, 3),
                      "threshold": self.burn_threshold}
                if tenant is not None:
                    ev["tenant"] = tenant
                if kind == "slo_breach":
                    if self.exemplar is not None:
                        try:
                            tid = self.exemplar()
                        except Exception:  # pragma: no cover - link only
                            tid = None
                        if tid:
                            # the breach→trace link (ISSUE 12)
                            ev["exemplar_trace"] = tid
                    rec.record("slo_breach", **ev)
                else:
                    rec.record("slo_recovered", **ev)
        return rows

    def _publish_locked(self, rows: List[dict]) -> None:
        """gubernator_slo_burn{slo,tenant} refresh under _mu: departed
        series (a tenant that left the bounded ledger) are removed
        before the current set is written, so cardinality stays
        bounded by #SLOs + #SLO-groups × (GUBER_TENANT_MAX + 1)."""
        m = self.metrics
        if m is None:
            return
        fresh = {(r["slo"], r["tenant"] or ""): r["fast_burn"]
                 for r in rows}
        for pair in self._published - set(fresh):  # lock-free: caller holds self._mu (tick)
            try:
                m.slo_burn.remove(*pair)
            except KeyError:  # pragma: no cover - already gone
                pass
        for (slo, tenant), val in fresh.items():
            m.slo_burn.labels(slo=slo, tenant=tenant).set(val)
        self._published = set(fresh)  # lock-free: caller holds self._mu (tick)

    # ---- reporting ------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /debug/slo`` document: windows + every series'
        current burn/breach state (re-evaluated fresh so a probe
        between ticks still sees live numbers)."""
        rows = self.tick()
        with self._mu:
            descs = {s.name: (s.kind, s.objective, s.description)
                     for s in self._slos}
            for name, (objective, _s, desc) in self._groups.items():
                descs[name] = ("ratio", objective, desc)
            ticks = self._ticks
        out_rows = []
        for r in rows:
            kind, objective, desc = descs.get(
                r["slo"], ("ratio", 0.0, ""))
            row = {"slo": r["slo"], "kind": kind,
                   "objective": objective,
                   "fast_burn": round(r["fast_burn"], 4),
                   "slow_burn": round(r["slow_burn"], 4),
                   "breached": r["breached"],
                   "description": desc}
            if r["tenant"] is not None:
                row["tenant"] = r["tenant"]
            if r["value"] is not None:
                row["value"] = round(r["value"], 6)
                row["target"] = round(r["target"], 6)
            out_rows.append(row)
        return {"fast_window_s": self.fast_s,
                "slow_window_s": self.slow_s,
                "burn_threshold": self.burn_threshold,
                "ticks": ticks, "slos": out_rows}

    def health(self) -> dict:
        """The healthz ``?deep=1`` block + the ``--fail-on-burn``
        readiness feed: which SLOs are breached, which fast windows
        are burning past the threshold right now."""
        rows = self.tick()
        breached = sorted({r["slo"] for r in rows if r["breached"]})
        burning = sorted({r["slo"] for r in rows
                          if r["fast_burn"] > self.burn_threshold})
        max_burn = max((r["fast_burn"] for r in rows), default=0.0)
        return {"breached": breached, "burning": burning,
                "max_fast_burn": round(max_burn, 4),
                "burn_threshold": self.burn_threshold}

    def verdicts(self) -> List[dict]:
        """Final per-series verdicts for the crash-forensics dump
        (telemetry.write_debug_dump) — no re-evaluation, just the
        latched state, so a dying process can't wedge on a source."""
        with self._mu:
            out = []
            for (name, tenant), tr in self._tracks.items():
                v = {"slo": name, "breached": tr.breached}
                if tenant is not None:
                    v["tenant"] = tenant
                if tr.since is not None:
                    v["since_mono_s"] = round(tr.since, 3)
                out.append(v)
            return out

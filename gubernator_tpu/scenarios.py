"""Scenario lab (ISSUE 16): a seeded, replayable workload DSL with
exact oracles.

Every acceptance story the actuator arc needs (autopilot, leasing, QoS,
live repartition) reduces to the same sentence: *describe* an
adversarial workload, *replay* it deterministically, *judge* it with an
exact oracle.  ``bench.py`` hard-codes a handful of Zipf shapes; this
module makes the workload a first-class, JSON-serializable object:

- :class:`ScenarioSpec` — a pure-data description (sources, faults,
  clients, clock skew, oracles) that round-trips losslessly through
  JSON and compiles to a byte-deterministic schedule under its seed.
- source primitives — ``zipf_drift`` (skew exponent drifts a0→a1 over
  the run), ``diurnal`` (sinusoidal volume), ``flash_crowd`` (a single
  celebrity key erupts for a tick window), ``tenant_mix`` (weighted
  tenant populations, e.g. 90/10 abuse), ``uniform``, and ``replay``
  (a recorded trace-plane JSONL capture re-emitted as traffic).
- :class:`ScenarioRunner` — drives the compiled schedule against a real
  stack (``object``, ``wire``, ``clustered``, ``mesh``, ``tiered``) on
  a **virtual clock** (NOW0 + tick·tick_ms, plus per-client skew), arms
  faults from the ``faults.py`` catalog on cue, then judges with exact
  oracles: decision-stream parity vs the pure-python ``Oracle`` on a
  reference lane, exact hit conservation after reconcile, Jain's
  fairness index + tenant-ledger conservation, SLO-verdict snapshots,
  and end-to-end trace assembly.

Determinism contract: the issue loop is single-threaded and
synchronous, all randomness flows from ``np.random.default_rng(seed)``
consumed in (tick, source) order, and the clock is virtual — the same
spec + seed replays a byte-identical decision stream (the sha256 over
``status|remaining|error`` per response, in issue order; ``reset_time``
is a clock artifact and deliberately excluded so clock-skew scenarios
can assert byte-identity against an unskewed twin).

The spec library lives in ``scenarios/`` (GUBER_SCENARIO_DIR);
``tools/scenario_lab.py`` is the CLI, ``bench.py`` section 15 the
recorded block, ``tools/chaos_matrix.py`` grows generated cells.
"""
from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from dataclasses import asdict, dataclass, field, replace
from functools import lru_cache
from typing import Dict, List, Optional

import numpy as np

from .oracle import Oracle
from .types import MAX_BATCH_SIZE, Algorithm, Behavior, RateLimitRequest

#: schema version stamped into every serialized spec and result row
SCENARIO_SCHEMA = 1

#: virtual-clock epoch — pinned far from the wall clock so any lane
#: substituting its own clock for the caller's time base breaks the
#: parity/conservation oracles VISIBLY (same discipline as the PR-6
#: cold-conservation tests)
NOW0 = 1_790_000_000_000

#: stacks a scenario can target (ScenarioRunner._build dispatches)
STACKS = ("object", "wire", "clustered", "mesh", "tiered")

#: source primitive catalog (kind -> one-line contract); SCENARIOS.md
#: documents the full per-kind parameter grammar
SOURCE_KINDS = {
    "zipf_drift": "Zipf keys, exponent drifts a0->a1 across the run",
    "diurnal": "uniform keys, volume modulated by a sinusoidal wave",
    "flash_crowd": "uniform background + a celebrity key eruption",
    "tenant_mix": "weighted tenant populations (e.g. 90/10 abuse)",
    "uniform": "uniform keys at constant volume",
    "replay": "re-emit a recorded trace-plane JSONL capture",
}

#: oracle catalog (name -> one-line contract)
ORACLE_KINDS = {
    "parity": "decision digest == the same schedule on an "
              "Oracle-backed reference lane",
    "conservation": "sum(admitted hits) == limit - remaining, exactly, "
                    "for every token key after reconcile",
    "fairness": "Jain's index over per-tenant admitted hits + exact "
                "tenant-ledger conservation vs the analytics plane",
    "slo": "SLO burn-engine verdict snapshot (breaches recorded; "
           "expect.slo_clean makes breaches a failure)",
    "trace_assembly": "force-sampled spans assemble into >=1 "
                      "multi-span trace with a wave child",
    "fleet_audit": "the live conservation auditors' folded drift "
                   "(fleet.fold_audits over every daemon's own "
                   "/debug/audit vector) drains to zero post-heal",
}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_scenario_dir() -> str:
    """The spec library directory (GUBER_SCENARIO_DIR overrides)."""
    return os.environ.get("GUBER_SCENARIO_DIR") \
        or os.path.join(_REPO_ROOT, "scenarios")


def env_fast() -> bool:
    """GUBER_SCENARIO_FAST=1 forces fast mode in every lab entry."""
    return os.environ.get("GUBER_SCENARIO_FAST", "0") == "1"


def env_seed() -> Optional[int]:
    """GUBER_SCENARIO_SEED overrides every spec's seed (sweep knob)."""
    v = os.environ.get("GUBER_SCENARIO_SEED", "")
    return int(v) if v else None


# ---------------------------------------------------------------------------
# spec


@dataclass
class ScenarioSpec:
    """A pure-data scenario description.  Everything JSON-native —
    sources/faults stay plain dicts so ``to_dict``/``from_dict`` is a
    lossless round trip by construction."""

    name: str
    description: str = ""
    stack: str = "object"            # one of STACKS
    seed: int = 1
    ticks: int = 12
    tick_ms: int = 500               # virtual ms per tick
    clients: int = 2                 # round-robin request issuers
    daemons: int = 3                 # clustered stack size
    skew_ms: List[int] = field(default_factory=list)  # per-client offset
    sources: List[dict] = field(default_factory=list)
    faults: List[dict] = field(default_factory=list)  # timeline entries
    oracles: List[str] = field(default_factory=list)
    expect: dict = field(default_factory=dict)   # oracle thresholds
    fast: dict = field(default_factory=dict)     # fast-mode overrides

    def to_dict(self) -> dict:
        d = {"schema": SCENARIO_SCHEMA}
        d.update(asdict(self))
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        d = dict(d)
        schema = d.pop("schema", SCENARIO_SCHEMA)
        if schema != SCENARIO_SCHEMA:
            raise ValueError(f"scenario schema {schema} != "
                             f"{SCENARIO_SCHEMA}")
        known = set(cls.__dataclass_fields__)
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown scenario keys: {sorted(unknown)}")
        spec = cls(**d)
        spec.validate()
        return spec

    def validate(self) -> None:
        if self.stack not in STACKS:
            raise ValueError(f"unknown stack {self.stack!r} "
                             f"(one of {STACKS})")
        for src in self.sources:
            if src.get("kind") not in SOURCE_KINDS:
                raise ValueError(f"unknown source kind "
                                 f"{src.get('kind')!r}")
        for o in self.oracles:
            if o not in ORACLE_KINDS:
                raise ValueError(f"unknown oracle {o!r}")
        if self.skew_ms and len(self.skew_ms) != self.clients:
            raise ValueError("skew_ms must list one offset per client")
        for f in self.faults:
            if "arm" not in f and not f.get("clear"):
                raise ValueError(f"fault entry needs arm or clear: {f}")

    def with_fast(self) -> "ScenarioSpec":
        """Apply the spec's ``fast`` overrides (ticks/clients/daemons
        plus a ``rows_scale`` multiplier on every source's volume) —
        the CI-speed twin of the full scenario, same grammar."""
        if not self.fast:
            return self
        over = {k: v for k, v in self.fast.items()
                if k in ("ticks", "tick_ms", "clients", "daemons")}
        spec = replace(self, **over, fast={})
        scale = float(self.fast.get("rows_scale", 1.0))
        if scale != 1.0:
            srcs = []
            for src in spec.sources:
                s = dict(src)
                for k in ("rows", "crowd_rows"):
                    if k in s:
                        s[k] = max(1, int(round(s[k] * scale)))
                srcs.append(s)
            spec = replace(spec, sources=srcs)
        if spec.skew_ms and len(spec.skew_ms) != spec.clients:
            spec = replace(
                spec, skew_ms=(list(spec.skew_ms)
                               * spec.clients)[:spec.clients])
        return spec


def load_spec(path: str) -> ScenarioSpec:
    with open(path) as f:
        return ScenarioSpec.from_dict(json.load(f))


def save_spec(spec: ScenarioSpec, path: str) -> None:
    with open(path, "w") as f:
        json.dump(spec.to_dict(), f, indent=2, sort_keys=True)
        f.write("\n")


def load_library(directory: Optional[str] = None) -> List[ScenarioSpec]:
    """Every ``*.json`` spec in the library directory, name-sorted."""
    d = directory or default_scenario_dir()
    specs = []
    for fn in sorted(os.listdir(d)):
        if fn.endswith(".json"):
            specs.append(load_spec(os.path.join(d, fn)))
    return specs


# ---------------------------------------------------------------------------
# source primitives -> request rows


def _tenant_delim() -> str:
    return os.environ.get("GUBER_TENANT_DELIM", "/") or "/"


def _src_req(src: dict, unique_key: str, hits: int,
             name: Optional[str] = None) -> RateLimitRequest:
    algo = (Algorithm.LEAKY_BUCKET if src.get("algorithm") == "leaky"
            else Algorithm.TOKEN_BUCKET)
    beh = (Behavior.GLOBAL if src.get("behavior") == "global"
           else Behavior.BATCHING)
    return RateLimitRequest(
        name=name if name is not None else str(src.get("name", "scn")),
        unique_key=unique_key, hits=int(hits),
        limit=int(src.get("limit", 1_000_000)),
        duration=int(src.get("duration", 3_600_000)),
        algorithm=algo, behavior=beh, burst=int(src.get("burst", 0)))


def _rows_uniform(src, rng, tick, spec):
    rows = int(src.get("rows", 32))
    nk = int(src.get("n_keys", 16))
    ks = rng.integers(0, nk, size=rows)
    h = int(src.get("hits", 1))
    return [_src_req(src, f"u{int(k)}", h) for k in ks]


def _rows_zipf_drift(src, rng, tick, spec):
    rows = int(src.get("rows", 32))
    nk = int(src.get("n_keys", 64))
    a0 = float(src.get("a0", 1.3))
    a1 = float(src.get("a1", a0))
    frac = tick / max(spec.ticks - 1, 1)
    a = max(a0 + (a1 - a0) * frac, 1.01)
    ks = (rng.zipf(a, size=rows) - 1) % nk
    h = int(src.get("hits", 1))
    return [_src_req(src, f"z{int(k)}", h) for k in ks]


def _rows_diurnal(src, rng, tick, spec):
    base = int(src.get("rows", 32))
    period = max(int(src.get("period_ticks", max(spec.ticks, 1))), 1)
    amp = float(src.get("amplitude", 0.5))
    rows = max(int(round(
        base * (1.0 + amp * math.sin(2 * math.pi * tick / period)))), 0)
    nk = int(src.get("n_keys", 16))
    ks = rng.integers(0, nk, size=rows)
    h = int(src.get("hits", 1))
    return [_src_req(src, f"d{int(k)}", h) for k in ks]


def _rows_flash_crowd(src, rng, tick, spec):
    out = _rows_uniform(
        {**src, "rows": src.get("rows", 16)}, rng, tick, spec)
    start = int(src.get("start_tick", spec.ticks // 3))
    stop = int(src.get("stop_tick", 2 * spec.ticks // 3))
    if start <= tick < stop:
        celeb = str(src.get("celebrity", "celebrity"))
        crowd = int(src.get("crowd_rows", 64))
        h = int(src.get("hits", 1))
        out.extend(_src_req(src, celeb, h) for _ in range(crowd))
    return out


def _rows_tenant_mix(src, rng, tick, spec):
    rows = int(src.get("rows", 32))
    tenants = src.get("tenants") or []
    if not tenants:
        return []
    w = np.array([float(t.get("weight", 1)) for t in tenants])
    picks = rng.choice(len(tenants), size=rows, p=w / w.sum())
    delim = _tenant_delim()
    suffix = str(src.get("name", "api"))
    out = []
    for p in picks:
        t = tenants[int(p)]
        nk = int(t.get("n_keys", 4))
        k = int(rng.integers(0, nk))
        out.append(_src_req(
            src, f"k{k}", int(t.get("hits", src.get("hits", 1))),
            name=f"{t['tenant']}{delim}{suffix}"))
    return out


@lru_cache(maxsize=8)
def _load_capture(path: str) -> tuple:
    """Wave spans of a trace-plane JSONL capture (telemetry.
    write_trace_dump format): skip the ``trace_header`` line and
    non-span lines, keep ``(start, size, trace_id)`` per wave span,
    normalized so ``start`` spreads over [0, 1)."""
    waves = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if not isinstance(d, dict) or d.get("kind") == "trace_header":
                continue
            if d.get("name") == "wave" and "start" in d:
                attrs = d.get("attrs") or {}
                waves.append((float(d["start"]),
                              int(attrs.get("size", 1)),
                              str(d.get("trace_id", ""))))
    if not waves:
        raise ValueError(f"capture {path} holds no wave spans")
    waves.sort()
    t0 = waves[0][0]
    span = max(waves[-1][0] - t0, 1e-9)
    return tuple((min((s - t0) / span, 1.0 - 1e-9), size, tid)
                 for s, size, tid in waves)


def _rows_replay(src, rng, tick, spec):
    path = str(src["capture"])
    if not os.path.isabs(path):
        path = os.path.join(_REPO_ROOT, path)
    waves = _load_capture(path)
    nk = int(src.get("n_keys", 16))
    cap = int(src.get("rows_cap", 64))
    scale = float(src.get("rows_scale", 1.0))
    out = []
    for frac, size, tid in waves:
        if int(frac * spec.ticks) != tick:
            continue
        rows = max(1, min(int(round(size * scale)), cap))
        base = int(tid[:8] or "0", 16) if tid else 0
        out.extend(_src_req(src, f"r{(base + i) % nk}", 1)
                   for i in range(rows))
    return out


_SOURCE_FNS = {
    "uniform": _rows_uniform,
    "zipf_drift": _rows_zipf_drift,
    "diurnal": _rows_diurnal,
    "flash_crowd": _rows_flash_crowd,
    "tenant_mix": _rows_tenant_mix,
    "replay": _rows_replay,
}


def compile_schedule(spec: ScenarioSpec) -> List[List[List[RateLimitRequest]]]:
    """ticks x clients request batches, byte-deterministic under the
    spec's seed: one rng, consumed in (tick, source) order, rows dealt
    round-robin to clients, each client call clamped to the wire's
    MAX_BATCH_SIZE."""
    rng = np.random.default_rng(int(spec.seed))
    sched = []
    for tick in range(spec.ticks):
        rows: List[RateLimitRequest] = []
        for src in spec.sources:
            rows.extend(_SOURCE_FNS[src["kind"]](src, rng, tick, spec))
        per_client: List[List[RateLimitRequest]] = \
            [[] for _ in range(max(spec.clients, 1))]
        for i, r in enumerate(rows):
            per_client[i % len(per_client)].append(r)
        sched.append([c[:MAX_BATCH_SIZE] for c in per_client])
    return sched


# ---------------------------------------------------------------------------
# judge tap


class DecisionDigest:
    """sha256 over ``status|remaining|error`` per response, in issue
    order — the canonical decision stream.  ``reset_time`` is a clock
    artifact (it moves with the caller's time base) and is excluded,
    which is exactly what lets clock-skew scenarios assert
    byte-identity against an unskewed twin."""

    def __init__(self) -> None:
        self._h = hashlib.sha256()
        self.rows = 0

    def update(self, status: int, remaining: int, error: str) -> None:
        self._h.update(f"{status}|{remaining}|{error}\n".encode())
        self.rows += 1

    def update_lines(self, lines: List[str]) -> None:
        """Batch form: one hash update per call, not per row."""
        self._h.update("".join(lines).encode())
        self.rows += len(lines)

    def hex(self) -> str:
        return self._h.hexdigest()


class JudgeTap:
    """Per-run bookkeeping for every (request, response) pair — the
    exact state the oracles judge from.  The service-path half,
    ``observe()``, only RETAINS the pair (one list append under an
    uncontended lock): all per-row work — digest, per-key ledgers,
    tenant attribution — happens in ``finalize()`` at settle time,
    off the measured path.  ``bench.py``'s ``runner_ab`` pins the
    observe() overhead < 3%; keeping it O(1) per call is what makes
    the lab's measurements trustworthy, the same discipline as the
    analytics tap (taps copy cheap, attribute later)."""

    def __init__(self, delim: Optional[str] = None) -> None:
        self._mu = threading.Lock()
        self._pending: List[tuple] = []  # guarded-by: self._mu
        self.digest = DecisionDigest()  # guarded-by: self._mu
        self.templates: Dict[str, RateLimitRequest] = {}  # guarded-by: self._mu
        self.admitted: Dict[str, int] = {}  # guarded-by: self._mu
        self.attempted: Dict[str, int] = {}  # guarded-by: self._mu
        #: tenant -> [requests, hits, admitted_hits, over_limit]
        self._tenant_rows: Dict[str, list] = {}  # guarded-by: self._mu
        self.errors: List[str] = []  # guarded-by: self._mu
        self.total = 0  # guarded-by: self._mu
        self.over_limit = 0  # guarded-by: self._mu
        self._delim = delim or _tenant_delim()

    def tenant_of(self, name: str) -> str:
        i = name.find(self._delim)
        return name if i < 0 else name[:i]

    @property
    def tenants(self) -> Dict[str, dict]:
        self.finalize()
        with self._mu:
            return {name: {"requests": r[0], "hits": r[1],
                           "admitted_hits": r[2], "over_limit": r[3]}
                    for name, r in self._tenant_rows.items()}

    def observe(self, reqs, resps, now_ms: int) -> None:
        """Service-path tap: retain and return.  O(1) per call."""
        with self._mu:
            self._pending.append((reqs, resps))

    def finalize(self) -> None:
        """Settle-time attribution of every retained pair, in issue
        order.  Idempotent; every oracle accessor calls it first."""
        with self._mu:
            pending, self._pending = self._pending, []
            if not pending:
                return
            lines: List[str] = []
            line = lines.append
            templates = self.templates
            admitted = self.admitted
            attempted = self.attempted
            tenant_rows = self._tenant_rows
            tcache: Dict[str, list] = {}
            for reqs, resps in pending:
                for req, resp in zip(reqs, resps):
                    st = resp.status
                    err = resp.error
                    line(f"{int(st)}|{int(resp.remaining)}|"
                         f"{err or ''}\n")
                    key = req.key
                    h = req.hits
                    if key not in templates:
                        templates[key] = req
                    attempted[key] = attempted.get(key, 0) + h
                    t = tcache.get(req.name)
                    if t is None:
                        t = tenant_rows.setdefault(
                            self.tenant_of(req.name), [0, 0, 0, 0])
                        tcache[req.name] = t
                    t[0] += 1
                    t[1] += h
                    if err:
                        if len(self.errors) < 32:
                            self.errors.append(f"{key}: {err}")
                    elif st == 0:
                        if h:
                            admitted[key] = admitted.get(key, 0) + h
                            t[2] += h
                    else:
                        self.over_limit += 1
                        t[3] += 1
            self.total += len(lines)
            self.digest.update_lines(lines)


def jain_index(xs: List[float]) -> float:
    """Jain's fairness index (sum x)^2 / (n * sum x^2) — 1.0 is
    perfectly fair, 1/n is one tenant taking everything."""
    xs = [float(x) for x in xs if x > 0]
    if not xs:
        return 1.0
    s = sum(xs)
    return (s * s) / (len(xs) * sum(x * x for x in xs))


# ---------------------------------------------------------------------------
# stack handles


class _StackHandle:
    """A built stack: entry points to issue batches against, the
    underlying instances for judge taps, and teardown."""

    def __init__(self, instances, issue, close, cluster=None) -> None:
        self.instances = instances
        self._issue = issue
        self._close = close
        self.cluster = cluster

    def issue(self, client: int, reqs, now_ms: int):
        return self._issue(client, reqs, now_ms)

    def close(self) -> None:
        self._close()


def _wire_codec():
    from .proto import gubernator_pb2 as pb

    def ser(reqs) -> bytes:
        msg = pb.GetRateLimitsReq()
        for r in reqs:
            m = msg.requests.add()
            m.name = r.name
            m.unique_key = r.unique_key
            m.hits = int(r.hits)
            m.limit = int(r.limit)
            m.duration = int(r.duration)
            m.algorithm = int(r.algorithm)
            m.behavior = int(r.behavior)
            m.burst = int(r.burst)
            if r.created_at:
                m.created_at = int(r.created_at)
        return msg.SerializeToString()

    def de(data: bytes):
        return pb.GetRateLimitsResp.FromString(data).responses

    return ser, de


class ScenarioRunner:
    """Compile a spec, build its stack, drive the schedule on the
    virtual clock, then judge.  Single-threaded by design — determinism
    is the contract, concurrency chaos belongs to the soak tests."""

    #: settle budget for reconcile convergence (wall seconds)
    SETTLE_TIMEOUT_S = 30.0

    def __init__(self, spec: ScenarioSpec, fast: bool = False,
                 engine=None) -> None:
        if fast or env_fast():
            spec = spec.with_fast()
        seed = env_seed()
        if seed is not None:
            spec = replace(spec, seed=seed)
        spec.validate()
        self.spec = spec
        self._engine = engine
        self._armed: List[object] = []  # guarded-by: self._fault_mu
        self._fault_mu = threading.Lock()

    # -- stack builders ---------------------------------------------------

    def _build(self) -> _StackHandle:
        return getattr(self, f"_build_{self.spec.stack}")()

    def _solo(self, **cfg_kwargs) -> "object":
        from .config import Config
        from .instance import V1Instance
        cfg = Config(cache_size=cfg_kwargs.pop("cache_size", 1 << 12),
                     sweep_interval_ms=0, **cfg_kwargs)
        return V1Instance(cfg, engine=self._engine)

    def _build_object(self) -> _StackHandle:
        inst = self._solo()
        return _StackHandle(
            [inst],
            lambda c, reqs, now: inst.get_rate_limits(reqs, now_ms=now),
            inst.close)

    def _build_wire(self) -> _StackHandle:
        inst = self._solo()
        ser, de = _wire_codec()

        def issue(c, reqs, now):
            return de(inst.get_rate_limits_wire(ser(reqs), now_ms=now))

        return _StackHandle([inst], issue, inst.close)

    def _build_clustered(self) -> _StackHandle:
        from . import cluster as cluster_mod
        from .config import BehaviorConfig
        cluster = cluster_mod.start(
            self.spec.daemons,
            behaviors=BehaviorConfig(
                batch_wait_ms=5, batch_timeout_ms=400,
                peer_retry_limit=1, peer_retry_backoff_ms=5,
                peer_circuit_threshold=2, peer_circuit_cooldown_ms=250,
                peer_eject_after_ms=300, peer_readmit_after_ms=250,
                global_sync_wait_ms=100))
        insts = [cluster.instance_at(i)
                 for i in range(self.spec.daemons)]

        def issue(c, reqs, now):
            return insts[c % len(insts)].get_rate_limits(reqs,
                                                         now_ms=now)

        return _StackHandle(insts, issue, cluster.stop, cluster=cluster)

    def _build_mesh(self) -> _StackHandle:
        from .parallel import make_mesh
        had = os.environ.get("GUBER_MESH_GLOBAL_CAP")
        if had is None:
            os.environ["GUBER_MESH_GLOBAL_CAP"] = "256"
        from .config import BehaviorConfig, Config
        from .instance import V1Instance
        inst = V1Instance(
            Config(cache_size=1 << 12, sweep_interval_ms=0,
                   global_mode="mesh", batch_rows=64,
                   behaviors=BehaviorConfig(global_sync_wait_ms=100)),
            mesh=make_mesh(n=2))

        def close():
            inst.close()
            if had is None:
                os.environ.pop("GUBER_MESH_GLOBAL_CAP", None)

        return _StackHandle(
            [inst],
            lambda c, reqs, now: inst.get_rate_limits(reqs, now_ms=now),
            close)

    def _build_tiered(self) -> _StackHandle:
        inst = self._solo(cache_size=256, tier_cold=True)
        return _StackHandle(
            [inst],
            lambda c, reqs, now: inst.get_rate_limits(reqs, now_ms=now),
            inst.close)

    # -- fault timeline ---------------------------------------------------

    def _fault_spec(self, raw: str, handle: _StackHandle) -> str:
        """Substitute ``{addr:N}`` placeholders with daemon N's gRPC
        address (clustered stacks only)."""
        out = raw
        while "{addr:" in out:
            i = out.index("{addr:")
            j = out.index("}", i)
            n = int(out[i + 6:j])
            if handle.cluster is None:
                raise ValueError(f"{raw!r} needs a clustered stack")
            out = out[:i] + handle.cluster.grpc_address(n) + out[j + 1:]
        return out

    def _faults_at(self, tick: int, handle: _StackHandle) -> None:
        for f in self.spec.faults:
            if int(f.get("at_tick", 0)) != tick:
                continue
            on = f.get("on", "all")
            targets = (handle.instances if on == "all"
                       else [handle.instances[i] for i in on])
            if f.get("clear"):
                for inst in targets:
                    inst.faults.clear()
                with self._fault_mu:
                    self._armed = [i for i in self._armed
                                   if i not in targets]
            else:
                spec = self._fault_spec(f["arm"], handle)
                seed = int(f.get("seed", self.spec.seed))
                for inst in targets:
                    inst.faults.arm(spec, seed=seed)
                with self._fault_mu:
                    self._armed.extend(targets)

    def _clear_faults(self, handle: _StackHandle) -> None:
        with self._fault_mu:
            armed, self._armed = self._armed, []
        for inst in armed:
            inst.faults.clear()

    # -- oracles ----------------------------------------------------------

    def _oracle_parity(self, judge: JudgeTap) -> dict:
        """Replay the identical schedule on a reference lane — a solo
        object instance whose engine is the pure-python exact-integer
        Oracle — and byte-compare decision digests."""
        from .oracle import OracleEngine
        ref = ScenarioRunner(replace(self.spec, stack="object",
                                     faults=[], oracles=[]),
                             engine=OracleEngine())
        handle = ref._build()
        try:
            rj = JudgeTap(delim=judge._delim)
            ref._drive(handle, rj)
            rj.finalize()
        finally:
            handle.close()
        ok = rj.digest.hex() == judge.digest.hex()
        return {"ok": ok, "reference_digest": rj.digest.hex(),
                "rows": rj.digest.rows}

    def _probe(self, handle: _StackHandle, tmpl: RateLimitRequest,
               now_ms: int, entry: int = 0):
        """A hits=0 status query for one key (debits nothing)."""
        q = replace(tmpl, hits=0, created_at=0)
        return handle.issue(entry, [q], now_ms)[0]

    def _oracle_conservation(self, handle: _StackHandle,
                             judge: JudgeTap, end_now: int,
                             fast: bool) -> dict:
        """Exact hit conservation for every non-GLOBAL token key:
        ``limit - remaining`` at a hits=0 probe must equal the judge's
        admitted-hit ledger, after degraded reconcile converges.  The
        virtual probe time sits inside every bucket's window (durations
        dwarf the scenario span), so remaining reflects debits only.
        Probes rotate through EVERY entry point and must agree — the
        cross-daemon observability from the resilience suite, and the
        light traffic each caller's routing gate needs to readmit a
        healed peer before its queued degraded hits can flush."""
        keys = [k for k, t in judge.templates.items()
                if int(t.algorithm) == int(Algorithm.TOKEN_BUCKET)
                and not (int(t.behavior) & int(Behavior.GLOBAL))]
        entries = max(len(handle.instances), 1)

        def audit():
            bad = []
            for k in keys:
                t = judge.templates[k]
                want = judge.admitted.get(k, 0)
                for e in range(entries):
                    r = self._probe(handle, t, end_now, entry=e)
                    debited = int(t.limit) - int(r.remaining)
                    if debited != want or r.error:
                        bad.append({"key": k, "entry": e,
                                    "debited": debited,
                                    "admitted": want,
                                    "error": r.error or ""})
            return bad

        # the budget is a deadline, not a sleep: a settled run exits on
        # the first audit, so fast mode only pays this under real load
        deadline = time.perf_counter() + \
            (15.0 if fast else self.SETTLE_TIMEOUT_S)
        bad = audit()
        while bad and time.perf_counter() < deadline:
            for inst in handle.instances:
                gm = getattr(inst, "global_manager", None)
                loop = getattr(gm, "_hits_loop", None)
                if loop is not None:
                    loop.poke()
            time.sleep(0.2)
            bad = audit()
        return {"ok": not bad, "keys": len(keys),
                "mismatches": bad[:5]}

    def _oracle_fairness(self, handle: _StackHandle,
                         judge: JudgeTap) -> dict:
        """Jain's index over per-tenant admitted hits, plus exact
        tenant-ledger conservation: the analytics plane's per-tenant
        (requests, hits) must equal the judge's own counts.  Solo
        stacks only for the exact cross-check — forwarding counts rows
        on both sides."""
        jain = jain_index([t["admitted_hits"]
                           for t in judge.tenants.values()])
        out = {"jain_index": round(jain, 6),
               "tenants": len(judge.tenants)}
        floor = float(self.spec.expect.get("jain_min", 0.0))
        ceil = float(self.spec.expect.get("jain_max", 1.0))
        out["ok"] = floor <= jain <= ceil
        ana = getattr(handle.instances[0], "analytics", None)
        if ana is not None and len(handle.instances) == 1:
            ana.flush(timeout=10)
            snap = ana.tenants_snapshot()
            mism = []
            if snap.get("enabled"):
                led = snap.get("tenants", {})
                for name, mine in judge.tenants.items():
                    got = led.get(name)
                    if (got is None
                            or got["requests"] != mine["requests"]
                            or got["hits"] != mine["hits"]):
                        mism.append({"tenant": name, "judge": mine,
                                     "ledger": got})
                out["ledger_requests"] = \
                    snap.get("totals", {}).get("requests")
                out["ledger_conserved"] = not mism
                out["ledger_mismatches"] = mism[:5]
                out["ok"] = out["ok"] and not mism
        return out

    def _oracle_slo(self, handle: _StackHandle) -> dict:
        """SLO burn-engine verdict snapshot: tick every engine once,
        record breached series.  Breaches are telemetry, not failure —
        unless the spec sets ``expect.slo_clean``."""
        breached = []
        present = False
        for inst in handle.instances:
            eng = getattr(inst, "slo", None)
            if eng is None:
                continue
            present = True
            eng.tick()
            breached.extend(v["slo"] for v in eng.verdicts()
                            if v.get("breached"))
        ok = present and (not breached
                          if self.spec.expect.get("slo_clean") else True)
        return {"ok": ok, "engines": present,
                "breached": sorted(set(breached))}

    def _oracle_fleet_audit(self, handle: _StackHandle,
                            fast: bool) -> dict:
        """The live auditors' verdict (ISSUE 19): fold every daemon's
        OWN audit vector (instance.audit_doc — the same document
        GET /debug/audit serves) with fleet.fold_audits and require
        fleet drift == 0 once reconcile settles.  No test-harness
        walking: the daemons prove conservation themselves.  Stacks
        without a GLOBAL backend trivially conserve (all-zero
        vectors), so the oracle is armed per-spec on clustered/mesh
        scenarios where the flush discipline actually runs."""
        from . import fleet

        def fold():
            return fleet.fold_audits(
                [inst.audit_doc() for inst in handle.instances])

        deadline = time.perf_counter() + \
            (15.0 if fast else self.SETTLE_TIMEOUT_S)
        f = fold()
        while not f["conserved"] and time.perf_counter() < deadline:
            for inst in handle.instances:
                gm = getattr(inst, "global_manager", None)
                loop = getattr(gm, "_hits_loop", None)
                if loop is not None:
                    loop.poke()
            time.sleep(0.2)
            f = fold()
        ring = fleet.ring_verdict(
            [inst.audit_doc() for inst in handle.instances])
        return {"ok": f["conserved"] and ring["consistent"],
                "drift": f["drift"],
                "injected": f["totals"]["injected"],
                "applied": f["totals"]["applied"],
                "lost": f["totals"]["lost"],
                "max_drain_age_s": f["max_drain_age_s"],
                "ring_consistent": ring["consistent"]}

    def _oracle_trace_assembly(self, handle: _StackHandle) -> dict:
        """Force-sampled spans from every instance must assemble into
        at least one multi-span trace carrying a wave child — the
        end-to-end proof that the PR-12 trace plane stitched the run."""
        from .tracing import assemble
        spans = []
        for inst in handle.instances:
            spans.extend(inst.span_recorder.spans())
        traces = assemble(spans)

        def _stitched_wave(nodes, depth=0):
            # a wave span BELOW a root proves parent/child stitching
            for n in nodes:
                if depth > 0 and str(n.get("name", "")).startswith(
                        "wave"):
                    return True
                if _stitched_wave(n.get("children") or [], depth + 1):
                    return True
            return False

        good = [t for t in traces
                if t["spans"] >= 2 and _stitched_wave(t["roots"])]
        return {"ok": bool(good), "spans": len(spans),
                "traces": len(traces), "assembled": len(good)}

    # -- drive ------------------------------------------------------------

    def _skew(self, client: int) -> int:
        return int(self.spec.skew_ms[client]) if self.spec.skew_ms else 0

    def _drive(self, handle: _StackHandle, judge: JudgeTap) -> None:
        sched = compile_schedule(self.spec)
        for tick, per_client in enumerate(sched):
            self._faults_at(tick, handle)
            now = NOW0 + tick * self.spec.tick_ms
            for ci, reqs in enumerate(per_client):
                if not reqs:
                    continue
                c_now = now + self._skew(ci)
                resps = handle.issue(ci, reqs, c_now)
                judge.observe(reqs, resps, c_now)

    def run(self, fast: bool = False) -> dict:
        spec = self.spec
        t0 = time.perf_counter()
        handle = self._build()
        try:
            if "trace_assembly" in spec.oracles:
                for inst in handle.instances:
                    inst.span_recorder.sample = 1.0
            rec = handle.instances[0].recorder
            rec.record("scenario_started", name=spec.name,
                       stack=spec.stack, seed=spec.seed,
                       ticks=spec.ticks)
            judge = JudgeTap()
            self._drive(handle, judge)
            judge.finalize()
            # settle: faults off first, then judge in an order that
            # keeps the exact cross-checks exact — the fairness ledger
            # snapshot must land BEFORE conservation's hits=0 probes
            # add rows to it
            self._clear_faults(handle)
            end_now = NOW0 + spec.ticks * spec.tick_ms
            oracles: Dict[str, dict] = {}
            if "fairness" in spec.oracles:
                oracles["fairness"] = self._oracle_fairness(handle,
                                                            judge)
            if "parity" in spec.oracles:
                oracles["parity"] = self._oracle_parity(judge)
            if "conservation" in spec.oracles:
                oracles["conservation"] = self._oracle_conservation(
                    handle, judge, end_now, fast)
            if "slo" in spec.oracles:
                oracles["slo"] = self._oracle_slo(handle)
            if "fleet_audit" in spec.oracles:
                oracles["fleet_audit"] = self._oracle_fleet_audit(
                    handle, fast)
            if "trace_assembly" in spec.oracles:
                oracles["trace_assembly"] = \
                    self._oracle_trace_assembly(handle)
            ok = (not judge.errors
                  and all(o["ok"] for o in oracles.values()))
            row = {
                "schema": SCENARIO_SCHEMA,
                "name": spec.name, "stack": spec.stack,
                "seed": spec.seed, "ticks": spec.ticks,
                "requests": judge.total,
                "admitted_hits": sum(judge.admitted.values()),
                "over_limit": judge.over_limit,
                "error_rows": len(judge.errors),
                "errors": judge.errors[:5],
                "keys": len(judge.templates),
                "decision_digest": judge.digest.hex(),
                "oracles": oracles, "ok": ok,
                "wall_ms": round(
                    (time.perf_counter() - t0) * 1e3, 3),
            }
            if "fairness" in oracles:
                row["jain_index"] = oracles["fairness"]["jain_index"]
            rec.record("scenario_finished", name=spec.name, ok=ok,
                       requests=judge.total,
                       digest=row["decision_digest"][:16])
            m = handle.instances[0].metrics
            m.scenario_runs.labels(
                verdict="ok" if ok else "failed").inc()
            return row
        finally:
            self._clear_faults(handle)
            handle.close()


def run_scenarios(specs: List[ScenarioSpec], fast: bool = False,
                  progress=None) -> dict:
    """Run a spec list; the aggregate document ``bench.py`` records as
    the ``15_scenarios`` row and ``tools/scenario_lab.py`` prints."""
    rows: Dict[str, dict] = {}
    for spec in specs:
        if progress is not None:
            progress(spec)
        rows[spec.name] = ScenarioRunner(spec, fast=fast).run(fast=fast)
    return {"schema": SCENARIO_SCHEMA,
            "scenarios": rows,
            "count": len(rows),
            "all_ok": all(r["ok"] for r in rows.values())}

"""Dataclass ↔ protobuf converters.

The core framework speaks plain Python types (types.py); the gRPC front
door and peer transport speak the generated pb2 classes (proto/).  These
converters are the only place the two meet.
"""
from __future__ import annotations

from typing import List

from .proto import gubernator_pb2 as pb
from .types import (
    Algorithm,
    Behavior,
    HealthCheckResponse,
    RateLimitRequest,
    RateLimitResponse,
    Status,
)


def req_to_pb(r: RateLimitRequest) -> pb.RateLimitReq:
    m = pb.RateLimitReq(
        name=r.name, unique_key=r.unique_key, hits=int(r.hits),
        limit=int(r.limit), duration=int(r.duration),
        algorithm=int(r.algorithm), behavior=int(r.behavior),
        burst=int(r.burst))
    for k, v in r.metadata.items():
        m.metadata[k] = v
    return m


def req_from_pb(m: pb.RateLimitReq) -> RateLimitRequest:
    # plain ints, not enum construction: Behavior(...)/Algorithm(...)
    # cost ~2µs each and this runs per request on the ingest hot path
    # (every consumer does int(req.behavior) anyway, and bit-combos
    # aren't valid single Behavior members)
    return RateLimitRequest(
        name=m.name, unique_key=m.unique_key, hits=m.hits, limit=m.limit,
        duration=m.duration, algorithm=m.algorithm, behavior=m.behavior,
        burst=m.burst, metadata=dict(m.metadata) if m.metadata else {})


def req_from_tlv(tlv: bytes) -> RateLimitRequest:
    """Deferred request prototype: a verbatim `requests` TLV slice
    (tag byte 0x0a + varint length + RateLimitReq payload) → object.

    The columnar wire lanes queue raw TLV slices for async reconcile
    (GLOBAL) and cross-region replication (MULTI_REGION) instead of
    building per-request objects on the hot path; the managers call
    this at flush cadence."""
    i, shift, ln = 1, 0, 0
    while True:
        b = tlv[i]
        ln |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            break
        shift += 7
    payload = tlv[i:i + ln]
    req = req_from_pb(pb.RateLimitReq.FromString(payload))
    # created_at (field 10) is wire-only until `make proto` regenerates
    # the pb2 classes: pb2 parses it into the unknown-field set, so the
    # hand scan below is what keeps the caller's clock attached to
    # requests materialized from raw TLVs
    created = tlv_created_at_payload(payload)
    if created:
        req.created_at = created
    return req


def tlv_created_at_payload(payload: bytes) -> int:
    """Scan a RateLimitReq payload for ``created_at`` (field 10 varint;
    proto3 last-value-wins).  Returns 0 when absent or on any framing
    this scanner doesn't model (the caller treats 0 as unset)."""
    i, n = 0, len(payload)
    created = 0
    while i < n:
        tag, shift = 0, 0
        while i < n:
            b = payload[i]
            tag |= (b & 0x7F) << shift
            i += 1
            if not b & 0x80:
                break
            shift += 7
        field_no, wt = tag >> 3, tag & 7
        if wt == 0:
            v, shift = 0, 0
            while i < n:
                b = payload[i]
                v |= (b & 0x7F) << shift
                i += 1
                if not b & 0x80:
                    break
                shift += 7
            if field_no == 10:
                created = v
        elif wt == 2:
            ln, shift = 0, 0
            while i < n:
                b = payload[i]
                ln |= (b & 0x7F) << shift
                i += 1
                if not b & 0x80:
                    break
                shift += 7
            i += ln
        elif wt == 1:
            i += 8
        elif wt == 5:
            i += 4
        else:
            return 0  # unmodeled wire type: treat as unset
    return created


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def req_to_tlv(r: RateLimitRequest) -> bytes:
    """Request → one `requests` TLV slice (tag 0x0a + varint length +
    RateLimitReq payload) — the columnar peer send lanes' entry unit
    (GetRateLimitsReq.requests and GetPeerRateLimitsReq.requests share
    field 1, so the slice is valid in either frame).  ``created_at``
    rides as a hand-appended field-10 varint until `make proto`
    regenerates the pb2 classes with the field."""
    payload = req_to_pb(r).SerializeToString()
    if r.created_at:
        payload += b"\x50" + _varint(int(r.created_at))
    return b"\x0a" + _varint(len(payload)) + payload


def tlv_with_hits(tlv: bytes, hits: int) -> bytes:
    """A request TLV slice with its ``hits`` replaced by the aggregated
    value — WITHOUT parsing the payload: a fresh field-3 varint is
    appended (proto3 last-value-wins for scalar fields; both pb2 and the
    C++ lane honor it) and the outer length is rebuilt.  This is how the
    GLOBAL hit flush sends per-key aggregates from raw queued TLVs with
    zero request materialization."""
    i, shift, ln = 1, 0, 0
    while True:
        b = tlv[i]
        ln |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            break
        shift += 7
    payload = tlv[i:i + ln] + b"\x18" + _varint(int(hits))
    return b"\x0a" + _varint(len(payload)) + payload


def tlv_with_created(tlv: bytes, created_ms: int) -> bytes:
    """A request TLV slice with ``created_at`` (field 10) appended —
    the forward hop stamps the CALLER's accepted-at clock onto each
    raw slice it ships to the owner, so the owner applies the request
    at the caller's time base instead of its own wall clock (see
    types.RateLimitRequest.created_at for why mixing bases loses
    debits).  Same rebuild-the-outer-length trick as tlv_with_hits;
    the C++ lane does this in bulk (ops/_native.cpp › stamp_req_tlvs),
    this is the codec-free twin."""
    i, shift, ln = 1, 0, 0
    while True:
        b = tlv[i]
        ln |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            break
        shift += 7
    payload = tlv[i:i + ln] + b"\x50" + _varint(int(created_ms))
    return b"\x0a" + _varint(len(payload)) + payload


def resp_to_pb(r: RateLimitResponse) -> pb.RateLimitResp:
    m = pb.RateLimitResp(
        status=int(r.status), limit=int(r.limit), remaining=int(r.remaining),
        reset_time=int(r.reset_time))
    if r.error:
        m.error = r.error
    for k, v in r.metadata.items():
        m.metadata[k] = v
    return m


def resp_from_pb(m: pb.RateLimitResp) -> RateLimitResponse:
    return RateLimitResponse(
        status=Status(m.status), limit=m.limit, remaining=m.remaining,
        reset_time=m.reset_time, error=m.error, metadata=dict(m.metadata))


def reqs_to_pb(reqs: List[RateLimitRequest]) -> pb.GetRateLimitsReq:
    m = pb.GetRateLimitsReq()
    m.requests.extend(req_to_pb(r) for r in reqs)
    return m


def health_to_pb(h: HealthCheckResponse) -> pb.HealthCheckResp:
    return pb.HealthCheckResp(status=h.status, message=h.message,
                              peer_count=h.peer_count)

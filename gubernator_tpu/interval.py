"""Interval ticker driving the async managers.

reference: interval.go › Interval (holster clock-based ticker used by
global.go's runAsyncHits/runBroadcasts — reconstructed).  `wait()` blocks
until the next period boundary or `stop()`; background managers loop on
it.  A test clock can be injected for deterministic tests.
"""
from __future__ import annotations

import threading
import time
from typing import Callable


class Interval:
    """Periodic wakeup with early-fire support.

    ``wait()`` returns True on a tick, False once stopped.  ``fire()``
    wakes the waiter immediately (used to flush queues on demand or at
    shutdown, like the reference's batch-full early flush).
    """

    def __init__(self, period_ms: int,
                 now_fn: Callable[[], float] = time.monotonic):
        self.period_s = max(period_ms, 1) / 1000.0
        self._now = now_fn
        self._ev = threading.Event()
        self._stopped = False

    def wait(self) -> bool:
        if self._stopped:
            return False
        fired = self._ev.wait(self.period_s)
        if self._stopped:
            return False
        if fired:
            self._ev.clear()
        return True

    def fire(self) -> None:
        self._ev.set()

    def stop(self) -> None:
        self._stopped = True
        self._ev.set()


class IntervalLoop:
    """A daemon thread running ``fn()`` on every tick of an Interval.

    The analog of the reference's `go manager.run()` goroutines; `close()`
    runs one final ``fn()`` so pending queues flush at shutdown
    (global.go drains before exit).
    """

    def __init__(self, period_ms: int, fn: Callable[[], None], name: str):
        self.interval = Interval(period_ms)
        self._fn = fn
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while self.interval.wait():
            try:
                self._fn()
            except Exception:  # pragma: no cover - logged, loop survives
                import logging

                logging.getLogger("gubernator_tpu").exception(
                    "interval loop %s", self._thread.name)

    def poke(self) -> None:
        self.interval.fire()

    @staticmethod
    def _drain_timeout_s() -> float:
        """Per-loop drain bound: GUBER_DRAIN_GRACE when set (the
        operator's whole-daemon drain budget — one wedged loop must
        not eat more than it), else 5 s."""
        import os

        raw = os.environ.get("GUBER_DRAIN_GRACE", "")
        if raw:
            try:
                from .config import parse_duration_ms

                ms = parse_duration_ms(raw)
                if ms > 0:
                    return ms / 1000.0
            except ValueError:
                pass
        return 5.0

    def close(self, timeout_s: float | None = None) -> None:
        self.interval.stop()
        self._thread.join(timeout=self._drain_timeout_s()
                          if timeout_s is None else timeout_s)
        if self._thread.is_alive():
            # a wedged fn() (dead-peer RPC with no deadline, device
            # stall) must not hang shutdown — and running the final
            # flush CONCURRENTLY with the wedged tick would race the
            # very queues it flushes, so skip it and say so
            import logging

            logging.getLogger("gubernator_tpu").warning(
                "interval loop %s did not drain within its bound; "
                "skipping the final flush", self._thread.name)
            return
        try:
            self._fn()  # final flush
        except Exception:  # pragma: no cover
            pass

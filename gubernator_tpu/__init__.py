"""gubernator_tpu — a TPU-native distributed rate-limiting framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
gardod/gubernator (see SURVEY.md): token/leaky-bucket rate limiting over
millions of keys, batched GetRateLimits API, hash-sharded key ownership
across a TPU mesh, GLOBAL replication via ICI collectives, pluggable
persistence and peer discovery.

Counter state lives as an HBM-resident struct-of-arrays; each request
batch executes as one jit-compiled gather→update→scatter program; a pod
acts as a single coherent rate-limit region via psum delta sync instead of
gRPC peer fan-out.
"""

__version__ = "0.1.0"

from .types import (  # noqa: F401
    Algorithm,
    Behavior,
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    GregorianDuration,
    HealthCheckResponse,
    MAX_BATCH_SIZE,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    Status,
)
from .oracle import Oracle  # noqa: F401

# Service layer (lazy-import-safe: these pull in grpc/jax on use).
from .config import (  # noqa: F401
    BehaviorConfig,
    Config,
    DaemonConfig,
    setup_daemon_config,
)
from .store import CacheItem, FileLoader, MockLoader, MockStore  # noqa: F401


def __getattr__(name):
    """Lazy heavyweight exports: V1Instance, Daemon, spawn_daemon, Client."""
    if name in ("V1Instance",):
        from .instance import V1Instance

        return V1Instance
    if name in ("Daemon", "spawn_daemon"):
        from . import daemon

        return getattr(daemon, name)
    if name in ("Client", "HttpClient"):
        from . import client

        return getattr(client, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

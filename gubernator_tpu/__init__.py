"""gubernator_tpu — a TPU-native distributed rate-limiting framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
gardod/gubernator (see SURVEY.md): token/leaky-bucket rate limiting over
millions of keys, batched GetRateLimits API, hash-sharded key ownership
across a TPU mesh, GLOBAL replication via ICI collectives, pluggable
persistence and peer discovery.

Counter state lives as an HBM-resident struct-of-arrays; each request
batch executes as one jit-compiled gather→update→scatter program; a pod
acts as a single coherent rate-limit region via psum delta sync instead of
gRPC peer fan-out.
"""

__version__ = "0.1.0"


def _tune_xla_cpu_runtime() -> None:
    """Serving-path CPU tuning, applied before the XLA backend
    initializes: the thunk runtime that newer XLA:CPU builds default to
    executes the sort-heavy decision step ~3× slower than the legacy
    emitter (measured on this repo's serving program: 12.1 → 3.7 ms per
    dense 8192-row wave, PERF.md §8), which directly caps the wire
    front door.  ``xla_cpu_*`` flags are ignored by non-CPU backends,
    and an operator's own XLA_FLAGS choice for this flag is respected.
    """
    import os

    if os.environ.get("GUBER_XLA_CPU_TUNE", "1") != "1":
        # escape hatch: an XLA build that drops this flag fails backend
        # init on ANY unknown XLA_FLAGS entry (--undefok is itself
        # rejected by XLA's parser) — GUBER_XLA_CPU_TUNE=0 recovers
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_use_thunk_runtime" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_use_thunk_runtime=false").strip()


_tune_xla_cpu_runtime()

from .types import (  # noqa: F401
    Algorithm,
    Behavior,
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    GregorianDuration,
    HealthCheckResponse,
    MAX_BATCH_SIZE,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    Status,
)
from .oracle import Oracle  # noqa: F401

# Service layer (lazy-import-safe: these pull in grpc/jax on use).
from .config import (  # noqa: F401
    BehaviorConfig,
    Config,
    DaemonConfig,
    setup_daemon_config,
)
from .store import CacheItem, FileLoader, MockLoader, MockStore  # noqa: F401


def __getattr__(name):
    """Lazy heavyweight exports: V1Instance, Daemon, spawn_daemon, Client."""
    if name in ("V1Instance",):
        from .instance import V1Instance

        return V1Instance
    if name in ("Daemon", "spawn_daemon"):
        from . import daemon

        return getattr(daemon, name)
    if name in ("Client", "HttpClient"):
        from . import client

        return getattr(client, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

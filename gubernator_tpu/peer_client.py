"""Peer transport: columnar send lanes with pipelined flushes.

reference: peer_client.go › PeerClient — reconstructed, mount empty.

The forward hop is end-to-end columnar (ISSUE 3): callers enqueue raw
request TLV slices into a pooled per-peer send buffer (`_SendLane`);
a flusher thread drains it with the dispatcher's no-overshoot coalescer
rules (greedy backlog first, a tiny straggler window, never overshoot
the batch limit — the entry that would overflow leads the next flush)
and ships each flush as ONE raw-bytes RPC with up to depth-K in flight
(`BehaviorConfig.peer_inflight`).  RPC futures resolve off the flusher
thread (grpc callback threads), so the flusher packs flush N+1 while
N..N+K-1 ride the wire — the forward-hop analog of the dispatcher's
overlapped wave pipeline.  A failed flush retries with linear backoff;
after `peer_circuit_threshold` consecutive final failures the peer's
circuit OPENS and sends fail fast instead of queuing behind a dead
peer, until a cooldown elapses and one probe flush half-opens it.

Object-path forwards (`enqueue`) serialize to a TLV at enqueue time and
ride the same lane; GLOBAL hit flushes and owner broadcasts ride it too
(global_manager.py), aggregated per peer per window.  Without the C++
codec (`ops/_native`) the legacy object-batching flusher below serves
instead — same API, per-request pb2 objects.

Shutdown drains in-flight flushes before closing the channel.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional, Sequence

import grpc

from .config import BehaviorConfig
from .grpc_api import PeersV1Stub, dial_peer, raw_unary
from .proto import gubernator_pb2 as pb
from .proto import peers_pb2 as peers_pb
from .tracing import outbound_metadata
from .types import Behavior, PeerInfo, RateLimitRequest, RateLimitResponse
from .wire import req_to_pb, resp_from_pb

try:  # raw response splitting for the columnar lanes; optional
    from .ops import native as _wire_native
except ImportError:  # pragma: no cover - unbuilt extension
    _wire_native = None

log = logging.getLogger("gubernator_tpu.peer")


class ErrClosing(Exception):
    """Raised for requests that arrive while the client drains.
    reference: peer_client.go › ErrClosing."""


class ErrCircuitOpen(Exception):
    """Raised (fail-fast) for sends while the peer's circuit is open —
    a dead peer must cost an immediate error response, not a queue of
    callers waiting out its timeouts."""


class _Entry:
    """One send-buffer entry: ``n_items`` request TLVs whose bytes sit
    in the lane's shared buffer; ``future`` resolves to this entry's
    contiguous slice of the response bytes."""

    __slots__ = ("nbytes", "n_items", "future", "trace", "t_enq")

    def __init__(self, nbytes: int, n_items: int, future: Future,
                 trace: Optional[str], t_enq: float):
        self.nbytes = nbytes
        self.n_items = n_items
        self.future = future
        self.trace = trace
        self.t_enq = t_enq


class _SendLane:
    """Pooled send buffer + depth-K pipelined raw RPCs to one peer
    method.  ``split`` lanes (GetPeerRateLimits) resolve each entry
    with its contiguous response-TLV byte slice; non-split lanes
    (UpdatePeerGlobals) resolve with the raw response bytes."""

    def __init__(self, client: "PeerClient", method: str,
                 max_items: int, rpc_timeout_s: float, split: bool):
        self.client = client
        self.method = method
        self.max_items = max(int(max_items), 1)
        self.rpc_timeout_s = rpc_timeout_s
        self.split = split
        b = client.behaviors
        self.window_s = max(int(getattr(b, "peer_coalesce_us", 200)),
                            0) / 1e6
        self.depth = max(int(getattr(b, "peer_inflight", 4)), 1)
        self.retries = max(int(getattr(b, "peer_retry_limit", 2)), 0)
        self.backoff_s = max(int(getattr(b, "peer_retry_backoff_ms", 25)),
                             0) / 1e3
        self._cond = threading.Condition()
        #: pooled: entries append, flush cuts
        self._buf = bytearray()  # guarded-by: self._cond
        self._entries: "deque[_Entry]" = deque()  # guarded-by: self._cond
        self._queued_items = 0  # guarded-by: self._cond
        self._inflight = 0  # guarded-by: self._cond
        self._thread: Optional[threading.Thread] = None  # guarded-by: self._cond
        self._closing = False  # guarded-by: self._cond

    # ---- producer side -------------------------------------------------

    def enqueue(self, data: bytes, n_items: int,
                traceparent: Optional[str] = None) -> Future:
        """Queue ``n_items`` request TLVs for the next flush.  Raises
        ErrClosing / ErrCircuitOpen (fail fast) instead of queuing."""
        if self.client._circuit_blocked():
            raise ErrCircuitOpen(
                f"peer {self.client.info.grpc_address} circuit open")
        fut: Future = Future()
        e = _Entry(len(data), int(n_items), fut, traceparent,
                   time.monotonic())
        with self._cond:
            if self._closing:
                raise ErrClosing("peer client is closing")
            self._buf += data
            self._entries.append(e)
            self._queued_items += e.n_items
            depth = self._queued_items
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"peer-lane-{self.method}-"
                         f"{self.client.info.grpc_address}")
                self._thread.start()
            self._cond.notify_all()
        m = self.client._metrics
        if m is not None:
            m.peer_send_buffer_depth.labels(
                peer_addr=self.client.info.grpc_address).set(depth)
        return fut

    # ---- flusher -------------------------------------------------------

    # lock-free: caller holds self._cond (the flusher's take under its wait loop)
    def _take_locked(self) -> tuple:
        """Pop entries for one flush under _cond: greedy, never
        overshooting max_items — the entry that would overflow leads
        the NEXT flush (the dispatcher's no-overshoot rule)."""
        batch: List[_Entry] = []
        nbytes = items = 0
        while self._entries:
            e = self._entries[0]
            if batch and items + e.n_items > self.max_items:
                break
            self._entries.popleft()
            batch.append(e)
            items += e.n_items
            nbytes += e.nbytes
            if items >= self.max_items:
                break
        data = bytes(memoryview(self._buf)[:nbytes])
        del self._buf[:nbytes]
        self._queued_items -= items
        return batch, data, items

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._entries and not self._closing:
                    self._cond.wait(0.5)
                if not self._entries:
                    return  # closing and drained
                batch, data, items = self._take_locked()
            if (items < self.max_items and self.window_s > 0
                    # lock-free: racy bool read; a late close just skips the straggler wait
                    and not self._closing):
                # straggler window: only after the backlog was drained
                # (a full flush skips the wait entirely)
                deadline = time.monotonic() + self.window_s
                while items < self.max_items:
                    with self._cond:
                        remain = deadline - time.monotonic()
                        if remain <= 0:
                            break
                        if not self._entries:
                            self._cond.wait(remain)
                        if not self._entries:
                            break
                        e = self._entries[0]
                        if items + e.n_items > self.max_items:
                            break
                        self._entries.popleft()
                        batch.append(e)
                        items += e.n_items
                        extra = bytes(memoryview(self._buf)[:e.nbytes])
                        del self._buf[:e.nbytes]
                        self._queued_items -= e.n_items
                    data += extra
            with self._cond:
                while self._inflight >= self.depth and not self._closing:
                    self._cond.wait(0.2)
                depth_now = self._queued_items
            m = self.client._metrics
            if m is not None:
                m.peer_send_buffer_depth.labels(
                    peer_addr=self.client.info.grpc_address).set(
                        depth_now)
                m.peer_flush_size.observe(items)
                now = time.monotonic()
                for e in batch:
                    m.peer_flush_wait.observe(max(now - e.t_enq, 0.0))
            self._launch(batch, data, attempt=0)

    def _launch(self, entries: List[_Entry], data: bytes,
                attempt: int) -> None:
        client = self.client
        # lock-free: racy bool read; a retry racing close fails fast next hop
        if attempt and (self._closing or client._closing.is_set()):
            # a retry timer outliving shutdown must fail fast, never
            # re-dial a closed channel
            self._fail(entries, ErrClosing("peer client closed"))
            return
        if client._circuit_blocked():
            self._fail(entries, ErrCircuitOpen(
                f"peer {client.info.grpc_address} circuit open"))
            return
        t0 = time.perf_counter()
        try:
            # faultpoint: a chaos run failing/delaying this peer's
            # sends lands here — same handling as a real dial failure
            client._fault("peer_send")
            call = client._raw_call(self.method)
            tp = next((e.trace for e in entries if e.trace), None)
            md = ([("traceparent", tp)] if tp else outbound_metadata())
            rpc = call.future(data, timeout=self.rpc_timeout_s,
                              metadata=md)
        except Exception as e:  # noqa: BLE001 - incl. closed channel
            self._on_done(None, entries, data, attempt, t0, err=e)
            return
        with self._cond:
            self._inflight += 1
        m = client._metrics
        if m is not None:
            m.peer_inflight_rpcs.labels(
                peer_addr=client.info.grpc_address).inc()
        rpc.add_done_callback(
            lambda f: self._rpc_done(f, entries, data, attempt, t0))

    def _rpc_done(self, f, entries, data, attempt, t0) -> None:
        """grpc callback thread: resolve futures OFF the flusher so it
        keeps packing the next flush while responses land."""
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()
        m = self.client._metrics
        if m is not None:
            m.peer_inflight_rpcs.labels(
                peer_addr=self.client.info.grpc_address).dec()
        try:
            rbytes = f.result()
            # faultpoint: lose/delay the response after the RPC
            # succeeded (tests the retry path's idempotence)
            self.client._fault("peer_recv")
        except Exception as e:  # noqa: BLE001 - RpcError et al.
            self._on_done(None, entries, data, attempt, t0, err=e)
            return
        self._on_done(rbytes, entries, data, attempt, t0)

    def _on_done(self, rbytes, entries, data, attempt, t0,
                 err: Optional[BaseException] = None) -> None:
        client = self.client
        m = client._metrics
        dt = time.perf_counter() - t0
        if m is not None:
            m.batch_send_duration.labels(
                peer_addr=client.info.grpc_address).observe(dt)
        if client._analytics is not None:
            # the forward hop's share of a request's wall time
            client._analytics.observe_phase("peer_flush", dt)
            if err is None:
                # cost-model sample (ISSUE 11): one point-to-point hop
                # of len(data) wire bytes (failed sends excluded — a
                # timeout measures the deadline, not the transfer)
                client._analytics.tap_cost("peer_flush", len(data),
                                           2, dt)
        if err is not None:
            # lock-free: racy bool read; a retry racing close fails fast next hop
            if (attempt < self.retries and not self._closing
                    and not client._circuit_blocked()):
                if m is not None:
                    m.peer_retry_counter.labels(
                        peer_addr=client.info.grpc_address).inc()
                from .telemetry import exc_text

                log.warning("peer flush to %s failed (attempt %d/%d), "
                            "retrying: %s", client.info.grpc_address,
                            attempt + 1, self.retries + 1,
                            exc_text(err))
                t = threading.Timer(
                    self.backoff_s * (attempt + 1),
                    self._launch, args=(entries, data, attempt + 1))
                t.daemon = True
                t.start()
                return
            client._record_failure()
            self._fail(entries, err)
            return
        client._record_success()
        self._resolve(entries, rbytes)

    def _resolve(self, entries: List[_Entry], rbytes: bytes) -> None:
        if not self.split:
            for e in entries:
                if not e.future.done():
                    e.future.set_result(rbytes)
            return
        sp = (_wire_native.split_resp_items(rbytes)
              if _wire_native is not None else None)
        total = sum(e.n_items for e in entries)
        if sp is None or sp[0].size != total:
            self._fail(entries, RuntimeError(
                "malformed or short peer response batch"))
            return
        off, ln, _st = sp
        i = 0
        for e in entries:
            if e.n_items == 0:
                payload = b""
            else:
                a = int(off[i])
                j = i + e.n_items - 1
                b = int(off[j]) + int(ln[j])
                payload = rbytes[a:b]
            i += e.n_items
            if not e.future.done():
                e.future.set_result(payload)

    def _fail(self, entries: List[_Entry],
              err: BaseException) -> None:
        from .telemetry import exc_text

        # exc_text: a flush deadline (grpc DEADLINE_EXCEEDED while the
        # owner compiles) must not log as an empty string
        log.warning("peer flush to %s failed (%d items): %s",
                    self.client.info.grpc_address,
                    sum(e.n_items for e in entries), exc_text(err))
        for e in entries:
            if not e.future.done():
                e.future.set_exception(err)

    # ---- lifecycle -----------------------------------------------------

    def stats(self) -> dict:
        with self._cond:
            return {"queued_items": self._queued_items,
                    "queued_entries": len(self._entries),
                    "inflight": self._inflight}

    def close(self, timeout_s: float) -> None:
        """Flush the remaining backlog, wait out in-flight RPCs, then
        fail anything still unresolved with ErrClosing."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
            t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._inflight > 0 and time.monotonic() < deadline:
                self._cond.wait(0.1)
            leftovers, self._entries = list(self._entries), deque()
            self._buf = bytearray()
            self._queued_items = 0
        for e in leftovers:
            if not e.future.done():
                e.future.set_exception(ErrClosing("peer client closed"))


class PeerClient:
    """One gRPC connection + columnar send lanes to a single peer."""

    def __init__(self, info: PeerInfo, behaviors: BehaviorConfig,
                 tls_creds: Optional[grpc.ChannelCredentials] = None,
                 metrics=None, analytics=None, faults=None):
        self.info = info
        self.behaviors = behaviors
        self._tls = tls_creds
        self._metrics = metrics
        #: optional KeyAnalytics: flush round-trips feed the
        #: "peer_flush" phase of the latency ledger (ISSUE 4)
        self._analytics = analytics
        #: optional FaultSet (faults.py): peer_send / peer_recv /
        #: peer_circuit faultpoints, tagged with this peer's address
        self._faults = faults
        self._channel: Optional[grpc.Channel] = None  # guarded-by: self._lock
        self._stub: Optional[PeersV1Stub] = None  # guarded-by: self._lock
        #: method → bytes-lane call handle
        self._raw_calls: dict = {}  # guarded-by: self._lock
        #: legacy object-batching queue (no-native fallback):
        #: (request, future, captured traceparent-or-None)
        self._queue: "queue.Queue[tuple]" = queue.Queue()
        self._closing = threading.Event()
        self._lock = threading.Lock()
        self._flusher: Optional[threading.Thread] = None
        # circuit breaker, shared by both lanes: consecutive final
        # flush failures open it; one success closes it
        self._circ_mu = threading.Lock()
        self._consec_failures = 0  # guarded-by: self._circ_mu
        self._open_until = 0.0  # guarded-by: self._circ_mu
        self._circuit_opens = 0  # guarded-by: self._circ_mu
        # routing-health hysteresis (ISSUE 5, health-gated ring):
        # _route_bad_since = start of the current circuit-open streak
        # (0 while healthy); _route_recovered_at = when the last streak
        # ended; _route_ejected = this peer is currently out of the
        # routing ring and held out until the readmit window passes
        self._route_bad_since = 0.0  # guarded-by: self._circ_mu
        self._route_recovered_at = 0.0  # guarded-by: self._circ_mu
        self._route_ejected = False  # guarded-by: self._circ_mu
        fwd_timeout = behaviors.batch_timeout_ms / 1000.0 + 60.0
        upd_timeout = behaviors.global_timeout_ms / 1000.0
        if _wire_native is not None:
            self._forward_lane: Optional[_SendLane] = _SendLane(
                self, "GetPeerRateLimits", behaviors.batch_limit,
                fwd_timeout, split=True)
            self._globals_lane: Optional[_SendLane] = _SendLane(
                self, "UpdatePeerGlobals", behaviors.global_batch_limit,
                upd_timeout, split=False)
        else:  # pragma: no cover - unbuilt extension
            self._forward_lane = self._globals_lane = None

    # ---- connection ----------------------------------------------------

    def _ensure_stub(self) -> PeersV1Stub:
        with self._lock:
            if self._stub is None:
                self._channel = dial_peer(self.info.grpc_address, self._tls)
                self._stub = PeersV1Stub(self._channel)
            return self._stub

    def _raw_call(self, method: str):
        """bytes-in/bytes-out call handle (identity serializers)."""
        self._ensure_stub()
        with self._lock:
            call = self._raw_calls.get(method)
            if call is None:
                call = self._raw_calls[method] = raw_unary(
                    self._channel, method)
            return call

    # ---- circuit breaker -----------------------------------------------

    def _fault(self, point: str) -> None:
        """Fire a faultpoint tagged with this peer's address (no-op
        while disarmed — one attribute read)."""
        f = self._faults
        if f is not None and f.armed:
            f.fire(point, self.info.grpc_address)

    def _circuit_blocked(self) -> bool:
        f = self._faults
        if (f is not None and f.armed
                and f.should("peer_circuit", self.info.grpc_address)):
            return True
        with self._circ_mu:
            return time.monotonic() < self._open_until

    def _record_failure(self) -> None:
        b = self.behaviors
        threshold = max(int(getattr(b, "peer_circuit_threshold", 3)), 1)
        cooldown = max(int(getattr(b, "peer_circuit_cooldown_ms",
                                   2000)), 0) / 1e3
        with self._circ_mu:
            self._consec_failures += 1
            if self._consec_failures < threshold:
                return
            now = time.monotonic()
            was_open = now < self._open_until
            self._open_until = now + cooldown
            self._circuit_opens += 1
            # routing health: the open streak starts at the FIRST open
            # and survives half-open probe failures (re-opens extend
            # it) — only a success ends it
            if self._route_bad_since == 0.0:
                self._route_bad_since = now
            self._route_recovered_at = 0.0
        if not was_open:
            log.warning("peer %s circuit OPEN after %d consecutive "
                        "flush failures; failing fast for %.1fs",
                        # lock-free: diagnostic snapshot just off the lock
                        self.info.grpc_address, self._consec_failures,
                        cooldown)
            if self._metrics is not None:
                self._metrics.peer_circuit_open_counter.labels(
                    peer_addr=self.info.grpc_address).inc()
                self._metrics.peer_circuit_state.labels(
                    peer_addr=self.info.grpc_address).set(1)

    def _record_success(self) -> None:
        with self._circ_mu:
            was_open = self._open_until > 0
            self._consec_failures = 0
            self._open_until = 0.0
            if self._route_bad_since:
                self._route_bad_since = 0.0
                self._route_recovered_at = time.monotonic()
        if was_open:
            log.info("peer %s circuit closed (probe flush succeeded)",
                     self.info.grpc_address)
            if self._metrics is not None:
                self._metrics.peer_circuit_state.labels(
                    peer_addr=self.info.grpc_address).set(0)

    def circuit_open(self) -> bool:
        """Operator-facing circuit state (deep healthz)."""
        return self._circuit_blocked()

    def route_healthy(self, eject_after_s: float,
                      readmit_after_s: float) -> bool:
        """Routing-ring health with hysteresis (ISSUE 5): False ejects
        this peer from the health-gated ring.

        Eject only after the circuit-open streak has lasted
        ``eject_after_s`` (a transient blip never moves keys); once
        ejected, readmit only after the peer has stayed recovered for
        ``readmit_after_s`` — a peer flapping open/closed inside the
        window stays out, so keys rehome exactly once per outage."""
        now = time.monotonic()
        with self._circ_mu:
            if self._route_bad_since:
                if now - self._route_bad_since >= eject_after_s:
                    self._route_ejected = True
                    return False
                return True
            if self._route_ejected:
                if (self._route_recovered_at
                        and now - self._route_recovered_at
                        >= readmit_after_s):
                    self._route_ejected = False
                    return True
                return False
            return True

    def probe(self):
        """One empty flush through the globals lane — the health
        prober's half-open probe for EJECTED peers (rehomed keys mean
        no organic traffic would ever close their circuit).  A 0-item
        UpdatePeerGlobals is a real RPC the peer answers trivially;
        success runs ``_record_success`` and starts the readmit clock.
        Returns the flush Future, or None when probing isn't possible
        (no native lanes / closing)."""
        if self._closing.is_set() or self._globals_lane is None:
            return None
        try:
            return self._globals_lane.enqueue(b"", 0)
        except (ErrClosing, ErrCircuitOpen):
            return None

    def lane_stats(self) -> dict:
        """Send-lane + circuit state for /healthz?deep=1."""
        with self._circ_mu:
            circ = {"open": time.monotonic() < self._open_until,
                    "consecutive_failures": self._consec_failures,
                    "opens": self._circuit_opens,
                    "route_ejected": self._route_ejected}
        out = {"circuit": circ}
        if self._forward_lane is not None:
            out["forward"] = self._forward_lane.stats()
        if self._globals_lane is not None:
            out["globals"] = self._globals_lane.stats()
        return out

    # ---- forwarded checks ----------------------------------------------

    def get_peer_rate_limit(self, req: RateLimitRequest,
                            timeout_s: Optional[float] = None
                            ) -> RateLimitResponse:
        """Forward one request to the owning peer.  Batched unless the
        request (or config) disables batching."""
        if self._closing.is_set():
            raise ErrClosing("peer client is closing")
        if req.behavior & Behavior.NO_BATCHING:
            return self.get_peer_rate_limits([req])[0]
        fut = self.enqueue(req)
        if timeout_s is None:
            timeout_s = (self.behaviors.batch_timeout_ms
                         + self.behaviors.batch_wait_ms) / 1000.0 + 30.0
        return fut.result(timeout=timeout_s)

    def enqueue(self, req: RateLimitRequest) -> Future:
        """Queue one request for the next batch flush; resolve later.

        With the C++ codec the request serializes to its TLV slice NOW
        and rides the columnar forward lane (pipelined flushes, retry,
        circuit); the response TLV parses back in the lane's callback
        thread.  The caller's trace context is captured here (the
        flusher thread has none).  Without the codec the legacy
        object-batching flusher below serves."""
        if self._closing.is_set():
            raise ErrClosing("peer client is closing")
        from .tracing import hop_traceparent

        if self._forward_lane is not None:
            from .wire import req_to_tlv

            inner = self._forward_lane.enqueue(
                req_to_tlv(req), 1,
                hop_traceparent("peer.forward",
                                attrs={"peer": self.info.grpc_address,
                                       "items": 1}))
            outer: Future = Future()

            def _convert(f: Future) -> None:
                try:
                    rbytes = f.result()
                    msg = pb.GetRateLimitsResp.FromString(rbytes)
                    outer.set_result(resp_from_pb(msg.responses[0]))
                except Exception as e:  # noqa: BLE001
                    outer.set_exception(e)

            inner.add_done_callback(_convert)
            return outer
        fut = Future()
        self._queue.put((req, fut,
                         hop_traceparent(
                             "peer.forward",
                             attrs={"peer": self.info.grpc_address,
                                    "items": 1})))
        self._start_flusher()
        return fut

    def forward_raw(self, data: bytes, n_items: int,
                    traceparent: Optional[str] = None) -> Future:
        """Columnar forward hop: ``data`` is ``n_items`` verbatim
        request TLV slices (GetRateLimitsReq.requests framing — byte-
        compatible with GetPeerRateLimitsReq.requests).  Returns a
        Future resolving to this call's contiguous slice of response
        TLV bytes (exactly ``n_items`` items, count-verified).  Rides
        the pooled send buffer: concurrent callers forwarding to the
        same peer share flush RPCs, with depth-K in flight.  Raises
        ErrClosing / ErrCircuitOpen for fail-fast paths."""
        if self._closing.is_set():
            raise ErrClosing("peer client is closing")
        if self._forward_lane is None:
            raise RuntimeError("columnar peer lane needs the native "
                               "extension (run `make native`)")
        if traceparent is None:
            # mint + RECORD the hop (ISSUE 12): the header's span id
            # becomes the owner-side request span's parent, stitching
            # the two daemons' halves into one assembled trace
            from .tracing import hop_traceparent

            traceparent = hop_traceparent(
                "peer.forward",
                attrs={"peer": self.info.grpc_address,
                       "items": int(n_items)})
        return self._forward_lane.enqueue(data, n_items, traceparent)

    def send_globals_raw(self, data: bytes, n_items: int,
                         traceparent: Optional[str] = None) -> Future:
        """Owner-broadcast twin of ``forward_raw``: ``data`` is
        ``n_items`` serialized UpdatePeerGlobalsReq.globals TLVs; the
        future resolves to the (empty) response bytes.  Serialized
        once, shared across every peer's lane — the per-peer pb2
        re-serialization the typed stub forced is gone.  Like
        forward_raw, a None ``traceparent`` captures (and records the
        hop for) the calling thread's trace — the global manager's
        tick wraps itself in a request context, so a broadcast is
        traceable end-to-end (ISSUE 12)."""
        if self._closing.is_set():
            raise ErrClosing("peer client is closing")
        if self._globals_lane is None:
            raise RuntimeError("columnar peer lane needs the native "
                               "extension (run `make native`)")
        if traceparent is None:
            from .tracing import hop_traceparent

            traceparent = hop_traceparent(
                "peer.forward",
                attrs={"peer": self.info.grpc_address,
                       "items": int(n_items), "lane": "globals"})
        return self._globals_lane.enqueue(data, n_items, traceparent)

    def get_peer_rate_limits(self, reqs: Sequence[RateLimitRequest],
                             timeout_s: Optional[float] = None,
                             traceparent: Optional[str] = None
                             ) -> List[RateLimitResponse]:
        """Synchronous batch call (peers.proto › GetPeerRateLimits).
        Default deadline is generous (forwarded checks must survive the
        owner's first-compile); the global manager passes its own
        global_timeout_ms.  ``traceparent`` lets a flusher carry a
        trace captured at enqueue time (its own thread has none)."""
        stub = self._ensure_stub()
        msg = peers_pb.GetPeerRateLimitsReq()
        msg.requests.extend(req_to_pb(r) for r in reqs)
        if timeout_s is None:
            timeout_s = self.behaviors.batch_timeout_ms / 1000.0 + 60.0
        md = ([("traceparent", traceparent)] if traceparent
              else outbound_metadata())
        resp = stub.GetPeerRateLimits(msg, timeout=timeout_s, metadata=md)
        return [resp_from_pb(m) for m in resp.rate_limits]

    def get_peer_rate_limits_raw_future(self, data: bytes,
                                        timeout_s: Optional[float] = None):
        """Forward already-serialized request TLVs and return a Future
        of raw response bytes.  Since ISSUE 3 this is a thin wrapper
        over the pooled forward lane (``forward_raw``) — kept for
        callers that hold pre-counted TLV bytes; ``timeout_s`` is
        subsumed by the lane's RPC deadline."""
        cnt = (_wire_native.count_req_items(data)
               if _wire_native is not None else None)
        if cnt is None:
            raise ValueError("unparseable request TLV bytes")
        # clock-ok: pass-through — callers stamp created_at into the raw TLVs (stamp_req_tlvs / _req_stamped) before handing bytes here
        return self.forward_raw(data, cnt)

    def update_peer_globals(self, updates: Sequence[peers_pb.UpdatePeerGlobal]
                            ) -> None:
        stub = self._ensure_stub()
        msg = peers_pb.UpdatePeerGlobalsReq()
        msg.globals.extend(updates)
        stub.UpdatePeerGlobals(
            msg, timeout=self.behaviors.global_timeout_ms / 1000.0,
            metadata=outbound_metadata())

    # ---- legacy batching loop (no-native fallback) ---------------------

    def _start_flusher(self) -> None:
        if self._flusher is None or not self._flusher.is_alive():
            with self._lock:
                if self._flusher is None or not self._flusher.is_alive():
                    self._flusher = threading.Thread(
                        target=self._run, daemon=True,
                        name=f"peer-flush-{self.info.grpc_address}")
                    self._flusher.start()

    def _run(self) -> None:
        """Collect until batch_limit or batch_timeout, then flush.
        reference: peer_client.go › run()."""
        timeout_s = max(self.behaviors.batch_timeout_ms, 1) / 1000.0
        while not self._closing.is_set() or not self._queue.empty():
            batch: List[tuple] = []
            deadline = time.monotonic() + timeout_s
            while len(batch) < self.behaviors.batch_limit:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remain))
                except queue.Empty:
                    break
            if batch:
                self._flush(batch)

    def _flush(self, batch: List[tuple]) -> None:
        t0 = time.perf_counter()
        try:
            tp = next((t for _, _, t in batch if t), None)
            resps = self.get_peer_rate_limits([r for r, _, _ in batch],
                                              traceparent=tp)
            for (_, fut, _), resp in zip(batch, resps):
                fut.set_result(resp)
            missing = batch[len(resps):]
            for _, fut, _ in missing:
                fut.set_exception(
                    RuntimeError("peer returned short response batch"))
        except Exception as e:  # noqa: BLE001 - surfaced per-request
            from .telemetry import exc_text

            # exc_text: a flush deadline (grpc DEADLINE_EXCEEDED while
            # the owner compiles) must not log as an empty string
            log.warning("peer batch flush to %s failed (%d reqs): %s",
                        self.info.grpc_address, len(batch), exc_text(e))
            for _, fut, _ in batch:
                if not fut.done():
                    fut.set_exception(e)
        finally:
            dt = time.perf_counter() - t0
            if self._metrics is not None:
                self._metrics.batch_send_duration.labels(
                    peer_addr=self.info.grpc_address).observe(dt)
            if self._analytics is not None:
                self._analytics.observe_phase("peer_flush", dt)

    # ---- lifecycle -----------------------------------------------------

    def shutdown(self) -> None:
        """Drain queued requests, then close (peer_client.go › shutdown)."""
        self._closing.set()
        if self._flusher is not None and self._flusher.is_alive():
            self._flusher.join(
                timeout=self.behaviors.batch_timeout_ms / 1000.0 + 5)
        # fail anything still queued on the legacy path
        while True:
            try:
                _, fut, _ = self._queue.get_nowait()
                fut.set_exception(ErrClosing("peer client closed"))
            except queue.Empty:
                break
        lane_timeout = self.behaviors.batch_timeout_ms / 1000.0 + 5
        for lane in (self._forward_lane, self._globals_lane):
            if lane is not None:
                lane.close(lane_timeout)
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = self._stub = None
                self._raw_calls = {}

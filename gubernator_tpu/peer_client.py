"""Peer transport with request batching.

reference: peer_client.go › PeerClient — reconstructed, mount empty.
Forwarded checks are enqueued and flushed by a background thread when
either BehaviorConfig.batch_timeout elapses or batch_limit requests are
queued (the reference's `run()` loop); NO_BATCHING bypasses the queue.
Shutdown drains in-flight requests before closing the channel.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence

import grpc

from .config import BehaviorConfig
from .grpc_api import PeersV1Stub, dial_peer
from .proto import peers_pb2 as peers_pb
from .tracing import outbound_metadata
from .types import Behavior, PeerInfo, RateLimitRequest, RateLimitResponse
from .wire import req_to_pb, resp_from_pb

log = logging.getLogger("gubernator_tpu.peer")


class ErrClosing(Exception):
    """Raised for requests that arrive while the client drains.
    reference: peer_client.go › ErrClosing."""


class PeerClient:
    """One gRPC connection + batching queue to a single peer daemon."""

    def __init__(self, info: PeerInfo, behaviors: BehaviorConfig,
                 tls_creds: Optional[grpc.ChannelCredentials] = None,
                 metrics=None):
        self.info = info
        self.behaviors = behaviors
        self._tls = tls_creds
        self._metrics = metrics
        self._channel: Optional[grpc.Channel] = None
        self._stub: Optional[PeersV1Stub] = None
        self._raw_peer_call = None  # bytes-in/bytes-out GetPeerRateLimits
        #: (request, future, captured traceparent-or-None)
        self._queue: "queue.Queue[tuple]" = queue.Queue()
        self._closing = threading.Event()
        self._lock = threading.Lock()
        self._flusher: Optional[threading.Thread] = None

    # ---- connection ----------------------------------------------------

    def _ensure_stub(self) -> PeersV1Stub:
        with self._lock:
            if self._stub is None:
                self._channel = dial_peer(self.info.grpc_address, self._tls)
                self._stub = PeersV1Stub(self._channel)
            return self._stub

    # ---- forwarded checks ----------------------------------------------

    def get_peer_rate_limit(self, req: RateLimitRequest,
                            timeout_s: Optional[float] = None
                            ) -> RateLimitResponse:
        """Forward one request to the owning peer.  Batched unless the
        request (or config) disables batching."""
        if self._closing.is_set():
            raise ErrClosing("peer client is closing")
        if req.behavior & Behavior.NO_BATCHING:
            return self.get_peer_rate_limits([req])[0]
        fut = self.enqueue(req)
        if timeout_s is None:
            timeout_s = (self.behaviors.batch_timeout_ms
                         + self.behaviors.batch_wait_ms) / 1000.0 + 30.0
        return fut.result(timeout=timeout_s)

    def enqueue(self, req: RateLimitRequest) -> Future:
        """Queue one request for the next batch flush; resolve later.

        The caller's trace context is captured NOW (thread-local — the
        flusher thread has none): the flush RPC carries the first
        queued request's trace, best-effort continuity for batched
        hops (a shared batch has no single parent by construction)."""
        if self._closing.is_set():
            raise ErrClosing("peer client is closing")
        from .tracing import current_traceparent

        fut: Future = Future()
        self._queue.put((req, fut, current_traceparent()))
        self._start_flusher()
        return fut

    def get_peer_rate_limits(self, reqs: Sequence[RateLimitRequest],
                             timeout_s: Optional[float] = None,
                             traceparent: Optional[str] = None
                             ) -> List[RateLimitResponse]:
        """Synchronous batch call (peers.proto › GetPeerRateLimits).
        Default deadline is generous (forwarded checks must survive the
        owner's first-compile); the global manager passes its own
        global_timeout_ms.  ``traceparent`` lets the batch flusher carry
        a trace captured at enqueue time (its own thread has none)."""
        stub = self._ensure_stub()
        msg = peers_pb.GetPeerRateLimitsReq()
        msg.requests.extend(req_to_pb(r) for r in reqs)
        if timeout_s is None:
            timeout_s = self.behaviors.batch_timeout_ms / 1000.0 + 60.0
        md = ([("traceparent", traceparent)] if traceparent
              else outbound_metadata())
        resp = stub.GetPeerRateLimits(msg, timeout=timeout_s, metadata=md)
        return [resp_from_pb(m) for m in resp.rate_limits]

    def get_peer_rate_limits_raw_future(self, data: bytes,
                                        timeout_s: Optional[float] = None):
        """Forward an already-serialized GetPeerRateLimitsReq and return
        a grpc Future resolving to raw GetPeerRateLimitsResp bytes.

        The clustered wire fast lane (instance.py › _wire_check_clustered)
        builds ``data`` by concatenating request TLV slices from the
        client's own wire bytes — no pb2 objects on either side; the
        owner daemon's columnar peer lane decodes them in C."""
        if self._closing.is_set():
            raise ErrClosing("peer client is closing")
        self._ensure_stub()
        with self._lock:
            if self._raw_peer_call is None:
                # identity (de)serializers: bytes straight through
                self._raw_peer_call = self._channel.unary_unary(
                    "/pb.gubernator.PeersV1/GetPeerRateLimits")
            call = self._raw_peer_call
        if timeout_s is None:
            timeout_s = self.behaviors.batch_timeout_ms / 1000.0 + 60.0
        return call.future(data, timeout=timeout_s,
                           metadata=outbound_metadata())

    def update_peer_globals(self, updates: Sequence[peers_pb.UpdatePeerGlobal]
                            ) -> None:
        stub = self._ensure_stub()
        msg = peers_pb.UpdatePeerGlobalsReq()
        msg.globals.extend(updates)
        stub.UpdatePeerGlobals(
            msg, timeout=self.behaviors.global_timeout_ms / 1000.0,
            metadata=outbound_metadata())

    # ---- batching loop -------------------------------------------------

    def _start_flusher(self) -> None:
        if self._flusher is None or not self._flusher.is_alive():
            with self._lock:
                if self._flusher is None or not self._flusher.is_alive():
                    self._flusher = threading.Thread(
                        target=self._run, daemon=True,
                        name=f"peer-flush-{self.info.grpc_address}")
                    self._flusher.start()

    def _run(self) -> None:
        """Collect until batch_limit or batch_timeout, then flush.
        reference: peer_client.go › run()."""
        timeout_s = max(self.behaviors.batch_timeout_ms, 1) / 1000.0
        while not self._closing.is_set() or not self._queue.empty():
            batch: List[tuple[RateLimitRequest, Future]] = []
            deadline = time.monotonic() + timeout_s
            while len(batch) < self.behaviors.batch_limit:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remain))
                except queue.Empty:
                    break
            if batch:
                self._flush(batch)

    def _flush(self, batch: List[tuple]) -> None:
        t0 = time.perf_counter()
        try:
            tp = next((t for _, _, t in batch if t), None)
            resps = self.get_peer_rate_limits([r for r, _, _ in batch],
                                              traceparent=tp)
            for (_, fut, _), resp in zip(batch, resps):
                fut.set_result(resp)
            missing = batch[len(resps):]
            for _, fut, _ in missing:
                fut.set_exception(
                    RuntimeError("peer returned short response batch"))
        except Exception as e:  # noqa: BLE001 - surfaced per-request
            from .telemetry import exc_text

            # exc_text: a flush deadline (grpc DEADLINE_EXCEEDED while
            # the owner compiles) must not log as an empty string
            log.warning("peer batch flush to %s failed (%d reqs): %s",
                        self.info.grpc_address, len(batch), exc_text(e))
            for _, fut, _ in batch:
                if not fut.done():
                    fut.set_exception(e)
        finally:
            if self._metrics is not None:
                self._metrics.batch_send_duration.labels(
                    peer_addr=self.info.grpc_address).observe(
                        time.perf_counter() - t0)

    # ---- lifecycle -----------------------------------------------------

    def shutdown(self) -> None:
        """Drain queued requests, then close (peer_client.go › shutdown)."""
        self._closing.set()
        if self._flusher is not None and self._flusher.is_alive():
            self._flusher.join(
                timeout=self.behaviors.batch_timeout_ms / 1000.0 + 5)
        # fail anything still queued
        while True:
            try:
                _, fut, _ = self._queue.get_nowait()
                fut.set_exception(ErrClosing("peer client closed"))
            except queue.Empty:
                break
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = self._stub = self._raw_peer_call = None

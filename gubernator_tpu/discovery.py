"""Peer discovery sources: watch membership → SetPeers callback.

reference: etcd.go › EtcdPool, memberlist.go › MemberListPool,
kubernetes.go › K8sPool, dns.go › DNSPool — reconstructed, mount empty.
Each source resolves the current peer set and fires ``on_change`` with a
full []PeerInfo whenever it differs from the last one; the daemon wires
the callback to V1Instance.set_peers (SURVEY.md §3.4).

All sources are implemented natively, with no client-library
dependencies: static lists, file-watch, DNS polling, UDP gossip
(``GossipDiscovery``, the in-tree analog of hashicorp/memberlist),
etcd via its v3 JSON/REST gateway (``EtcdDiscovery``: lease +
keep-alive + range polling), and Kubernetes via the raw API server
(``K8sDiscovery``: service-account token + Endpoints/Pods polling).
"""
from __future__ import annotations

import json
import logging
import os
import random
import socket
import threading
import time
from typing import Callable, List, Optional, Sequence

from .config import DaemonConfig, parse_peer_list
from .interval import IntervalLoop
from .types import PeerInfo

log = logging.getLogger("gubernator_tpu.discovery")

OnChange = Callable[[List[PeerInfo]], None]


class Discovery:
    """Base: deduped change notification.  The lock serializes
    concurrent notifiers (e.g. gossip rx thread vs. tx tick) so a stale
    membership can never be applied after a newer one.  ``mark_closed``
    fences late notifiers: a background thread that outlives ``close()``
    (a watch stream blocked in a read, a straggler datagram) must not
    drive ``on_change`` into a torn-down daemon."""

    def __init__(self, on_change: OnChange):
        self._on_change = on_change
        self._last: Optional[tuple] = None  # guarded-by: self._notify_mu
        self._notify_mu = threading.Lock()
        self._discovery_closed = False  # guarded-by: self._notify_mu

    def _notify(self, peers: Sequence[PeerInfo]) -> None:
        key = tuple(sorted((p.grpc_address, p.http_address, p.datacenter)
                           for p in peers))
        with self._notify_mu:
            if self._discovery_closed or key == self._last:
                return
            self._last = key
            self._on_change(list(peers))

    def mark_closed(self) -> None:
        """Called first by every subclass close(): no further on_change
        callbacks after this returns."""
        with self._notify_mu:
            self._discovery_closed = True

    def close(self) -> None:  # pragma: no cover - overridden
        self.mark_closed()


class StaticDiscovery(Discovery):
    """Fixed peer list from config (GUBER_PEERS)."""

    def __init__(self, on_change: OnChange, peers: Sequence[PeerInfo]):
        super().__init__(on_change)
        self._notify(peers)


class FileDiscovery(Discovery):
    """Re-read a peers file on mtime change.  File format: one
    "grpc_addr[;http_addr][@dc]" per line, or a JSON array of objects."""

    def __init__(self, on_change: OnChange, path: str,
                 poll_interval_ms: int = 3000, default_dc: str = ""):
        super().__init__(on_change)
        self.path = path
        self.default_dc = default_dc
        self._mtime = -1.0
        self._poll()
        self._loop = IntervalLoop(poll_interval_ms, self._poll,
                                  name="file-discovery")

    def _poll(self) -> None:
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return
        if mtime == self._mtime:
            return
        self._mtime = mtime
        with open(self.path) as f:
            text = f.read()
        text_s = text.strip()
        if text_s.startswith("["):
            peers = [PeerInfo(grpc_address=o.get("grpc_address", ""),
                              http_address=o.get("http_address", ""),
                              datacenter=o.get("datacenter", self.default_dc))
                     for o in json.loads(text_s)]
        else:
            lines = [ln.strip() for ln in text.splitlines()
                     if ln.strip() and not ln.strip().startswith("#")]
            peers = parse_peer_list(lines, self.default_dc)
        self._notify(peers)

    def close(self) -> None:
        self.mark_closed()
        self._loop.close()


class DnsDiscovery(Discovery):
    """Periodic A/AAAA resolution of one FQDN (dns.go › DNSPool analog):
    every resolved address is a peer at ``grpc_port``."""

    def __init__(self, on_change: OnChange, fqdn: str, grpc_port: int,
                 poll_interval_ms: int = 30_000, default_dc: str = ""):
        super().__init__(on_change)
        self.fqdn = fqdn
        self.grpc_port = grpc_port
        self.default_dc = default_dc
        self._poll()
        self._loop = IntervalLoop(poll_interval_ms, self._poll,
                                  name="dns-discovery")

    def _poll(self) -> None:
        try:
            infos = socket.getaddrinfo(self.fqdn, self.grpc_port,
                                       proto=socket.IPPROTO_TCP)
        except socket.gaierror as e:
            log.warning("dns discovery %s: %s", self.fqdn, e)
            return
        addrs = sorted({i[4][0] for i in infos})
        self._notify([PeerInfo(
            # IPv6 literals need brackets to form a valid host:port target
            grpc_address=(f"[{a}]:{self.grpc_port}" if ":" in a
                          else f"{a}:{self.grpc_port}"),
            datacenter=self.default_dc) for a in addrs])

    def close(self) -> None:
        self.mark_closed()
        self._loop.close()


class GossipDiscovery(Discovery):
    """UDP heartbeat membership with SWIM-style failure confirmation —
    the in-tree stand-in for hashicorp/memberlist (memberlist.go ›
    MemberListPool analog).

    Design (hardened in round 2 — VERDICT r1 items 3/8):

    - **Liveness is direct evidence only.** ``last_seen`` refreshes
      exclusively on datagrams received FROM that address (heartbeat,
      ack, anything).  Hearsay (another node listing the member) only
      *introduces* unknown members; it never refreshes them — otherwise
      two nodes can keep a dead member alive forever by re-telling each
      other about it (the ghost-member loop).
    - **Suspicion before eviction.** A member silent past ``suspect_ms``
      is probed: a direct ping plus ping-reqs through up to
      ``indirect_probes`` random live members (the SWIM indirect probe —
      one lossy path must not evict a healthy peer).  Any datagram from
      the member — including the ack it sends the origin directly —
      clears suspicion.  Eviction happens only at ``dead_ms``
      (default 3 × suspect) of unbroken silence.
    - **State push on first contact.** Any datagram from an unknown
      address triggers an immediate unicast of our full member map to
      it, so a joiner converges in one round trip instead of waiting
      out heartbeat intervals (memberlist's push/pull state sync,
      minus TCP).
    - **Dead-member rejoin probes (anti-entropy across a healed
      partition).** Evicted members are retained in a dead list for
      ``dead_retain_ms``; each tick one random dead address also gets
      the heartbeat.  Without this, a full partition longer than
      ``dead_ms`` is permanent: both halves evict each other, neither
      heartbeats the other again, and only a static seed spanning the
      cut could ever re-merge them (memberlist's dead-node reconnect
      behavior).

    Full-mesh heartbeats (not SWIM's random sampling) — fine for the
    tens-of-nodes clusters the reference targets.
    """

    def __init__(self, on_change: OnChange, bind: str, self_info: PeerInfo,
                 known_hosts: Sequence[str], interval_ms: int = 1000,
                 suspect_ms: int = 5000, dead_ms: Optional[int] = None,
                 indirect_probes: int = 3,
                 dead_retain_ms: Optional[int] = None):
        super().__init__(on_change)
        self.self_info = self_info
        host, _, port = bind.rpartition(":")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host or "0.0.0.0", int(port)))
        self._sock.settimeout(0.25)
        self.gossip_addr = f"{host or '127.0.0.1'}:{self._sock.getsockname()[1]}"
        self.suspect_s = suspect_ms / 1000.0
        self.dead_s = (dead_ms / 1000.0 if dead_ms is not None
                       else 3 * self.suspect_s)
        self.indirect_probes = indirect_probes
        #: gossip_addr → (PeerInfo dict, last_seen monotonic); guarded by
        #: _members_mu (written by the rx thread, read by the tx tick).
        self._members: dict = {}  # guarded-by: self._members_mu
        #: gossip_addr → eviction monotonic time: rejoin-probe targets
        #: (same lock).  Bounded by dead_retain_s so a long-gone address
        #: doesn't collect datagrams forever.
        self._dead: dict = {}  # guarded-by: self._members_mu
        self.dead_retain_s = (dead_retain_ms / 1000.0
                              if dead_retain_ms is not None
                              else 30 * self.dead_s)
        self._members_mu = threading.Lock()
        self._seeds = list(known_hosts)
        self._stop = threading.Event()
        self._rng = random.Random(hash(self.gossip_addr))
        self._rx = threading.Thread(target=self._recv_loop, daemon=True,
                                    name="gossip-rx")
        self._rx.start()
        self._loop = IntervalLoop(interval_ms, self._tick, name="gossip-tx")
        self._notify([self_info])
        self._tick()  # join immediately: don't wait out the first interval

    def _send(self, addr: str, payload: bytes) -> None:
        host, _, port = addr.rpartition(":")
        try:
            self._sock.sendto(payload, (host, int(port)))
        except (OSError, ValueError):
            pass

    def _payload(self) -> bytes:
        now = time.monotonic()
        members = {self.gossip_addr: _peer_dict(self.self_info)}
        with self._members_mu:
            snapshot = list(self._members.items())
        for addr, (info, seen) in snapshot:
            # advertise only members we have direct recent evidence for:
            # suspects stay OUR members while probed, but we don't
            # vouch for them to others
            if now - seen <= self.suspect_s:
                members[addr] = info
        return json.dumps({"t": "gossip", "from": self.gossip_addr,
                           "members": members}).encode()

    def _tick(self) -> None:
        payload = self._payload()
        now = time.monotonic()
        with self._members_mu:
            known = list(self._members.keys())
            suspects = [a for a, (_, seen) in self._members.items()
                        if now - seen > self.suspect_s]
            alive = [a for a, (_, seen) in self._members.items()
                     if now - seen <= self.suspect_s]
            dead_pool = [a for a in self._dead if a not in self._members]
        # one random rejoin probe per tick: across a healed partition
        # the first datagram through re-introduces us to the other
        # half (state push on first contact does the rest)
        rejoin = self._rng.sample(dead_pool, 1) if dead_pool else []
        for t in set(self._seeds) | set(known) | set(rejoin):
            if t != self.gossip_addr:
                self._send(t, payload)
        # SWIM probe round for silent members: direct ping + indirect
        # ping-reqs through random live members
        for s in suspects:
            self._send(s, json.dumps(
                {"t": "ping", "from": self.gossip_addr}).encode())
            relays = self._rng.sample(
                alive, min(self.indirect_probes, len(alive)))
            for r in relays:
                self._send(r, json.dumps(
                    {"t": "ping-req", "from": self.gossip_addr,
                     "target": s}).encode())
        self._prune_and_notify()

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, _ = self._sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._handle_datagram(data)
            except Exception as e:  # noqa: BLE001
                # unauthenticated UDP: one malformed datagram (wrong
                # types, non-dict JSON) must never kill the rx thread —
                # a dead rx thread silently evicts the whole cluster
                log.warning("gossip: dropped malformed datagram: %s", e)

    def _handle_datagram(self, data: bytes) -> None:
        try:
            msg = json.loads(data)
        except ValueError:
            return
        if not isinstance(msg, dict):
            return
        sender = msg.get("from")
        if sender is not None and not isinstance(sender, str):
            return
        kind = msg.get("t", "gossip")
        members = msg.get("members", {})
        if not isinstance(members, dict):
            members = {}
        now = time.monotonic()
        first_contact = False
        with self._members_mu:
            if sender and sender != self.gossip_addr:
                # direct evidence: refresh (or meet) the sender
                prev = self._members.get(sender)
                first_contact = prev is None
                info = members.get(sender)
                if not isinstance(info, dict):
                    info = prev[0] if prev else None
                if info is not None:
                    self._members[sender] = (info, now)
                    self._dead.pop(sender, None)  # rejoined
            # hearsay only INTRODUCES members, never refreshes them
            # (and only well-formed entries: a null/garbage info dict
            # stored here would crash every later tick's notify)
            for addr, info in members.items():
                if isinstance(addr, str) and isinstance(info, dict) \
                        and addr != self.gossip_addr \
                        and addr != sender and addr not in self._members:
                    self._members[addr] = (info, now)
        if kind == "ping" and sender:
            # ack whoever is probing us (possibly on behalf of an
            # origin: ack the origin directly — a datagram from us
            # is the direct evidence it needs)
            origin = msg.get("origin") or sender
            if isinstance(origin, str):
                self._send(origin, json.dumps(
                    {"t": "ack", "from": self.gossip_addr}).encode())
        elif kind == "ping-req" and isinstance(msg.get("target"), str):
            self._send(msg["target"], json.dumps(
                {"t": "ping", "from": self.gossip_addr,
                 "origin": sender}).encode())
        if first_contact and kind == "gossip":
            # push full state to a joiner immediately
            self._send(sender, self._payload())
        self._prune_and_notify()

    def _prune_and_notify(self) -> None:
        """Evict members past the DEAD window (really drop them — a
        read-time filter alone would heartbeat dead addresses forever).
        Suspects (silent past suspect_ms but not yet dead_ms) remain
        members while the probe round runs, so one lossy path never
        churns the ring."""
        now = time.monotonic()
        with self._members_mu:
            dead = [a for a, (_, seen) in self._members.items()
                    if now - seen > self.dead_s]
            for a in dead:
                del self._members[a]
                self._dead[a] = now  # rejoin-probe target (see _tick)
            for a in [a for a, t in self._dead.items()
                      if now - t > self.dead_retain_s]:
                del self._dead[a]
            live = [_peer_info(i) for i, _ in self._members.values()]
        self._notify(sorted(live + [self.self_info],
                            key=lambda p: p.grpc_address))

    def close(self) -> None:
        self.mark_closed()
        self._stop.set()
        self._loop.close()
        self._rx.join(timeout=2)
        self._sock.close()


def _peer_dict(p: PeerInfo) -> dict:
    return {"grpc_address": p.grpc_address, "http_address": p.http_address,
            "datacenter": p.datacenter}


def _peer_info(d: dict) -> PeerInfo:
    return PeerInfo(grpc_address=d.get("grpc_address", ""),
                    http_address=d.get("http_address", ""),
                    datacenter=d.get("datacenter", ""))


class EtcdDiscovery(Discovery):
    """etcd.go › EtcdPool analog over the etcd v3 JSON/REST gateway —
    no client library needed.  Registers self under ``prefix`` with a
    TTL lease, keep-alives the lease every ttl/3, and tracks the peer
    set two ways: a **watch stream** on the prefix (the reference's
    watch-driven SetPeers — membership changes propagate in one event
    round trip) with range polling every ttl/3 as the resilience
    backstop (watch reconnects, missed events)."""

    def __init__(self, on_change: OnChange, endpoints: Sequence[str],
                 prefix: str, self_info: PeerInfo, ttl_s: int = 30,
                 watch: bool = True):
        import base64

        super().__init__(on_change)
        if not endpoints:
            raise ValueError("etcd discovery needs GUBER_ETCD_ENDPOINTS")
        self._b64 = lambda b: base64.b64encode(b).decode()
        self._unb64 = base64.b64decode
        self.endpoints = [e if e.startswith("http") else f"http://{e}"
                          for e in endpoints]
        self.prefix = prefix
        self.self_info = self_info
        self.ttl_s = ttl_s
        self.lease_id: Optional[str] = None
        #: serializes the fetch→notify sequence between the watch thread
        #: and the interval poll: without it an older range response
        #: could be applied AFTER a newer one (stale membership
        #: resurrection — with long TTLs it would persist for minutes)
        self._poll_mu = threading.Lock()
        self._register()
        self._poll()
        period = max(ttl_s * 1000 // 3, 1000)
        self._keep = IntervalLoop(period, self._keepalive, name="etcd-lease")
        self._loop = IntervalLoop(period, self._poll, name="etcd-poll")
        self._watch_stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        if watch:
            self._watcher = threading.Thread(
                target=self._watch_loop, daemon=True, name="etcd-watch")
            self._watcher.start()

    def _watch_loop(self) -> None:
        """Long-lived /v3/watch stream: the gateway answers the
        create_request with newline-delimited JSON frames; any frame
        carrying events triggers an immediate range re-poll (applying
        the authoritative range keeps this robust to event coalescing
        and compaction).  Errors back off and reconnect — the interval
        poll remains the floor on staleness either way."""
        import urllib.request

        key = self._b64(self.prefix.encode())
        range_end = self._b64(self._range_end(self.prefix.encode()))
        body = json.dumps({"create_request": {
            "key": key, "range_end": range_end}}).encode()
        while not self._watch_stop.is_set():
            for ep in self.endpoints:
                try:
                    req = urllib.request.Request(
                        f"{ep}/v3/watch", data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=30) as f:
                        while not self._watch_stop.is_set():
                            line = f.readline()
                            if not line:
                                break  # stream closed: reconnect
                            try:
                                frame = json.loads(line)
                            except ValueError:
                                continue
                            if (frame.get("result") or {}).get("events") \
                                    and not self._watch_stop.is_set():
                                self._poll()
                except Exception:  # noqa: BLE001 - reconnect below
                    pass
                if self._watch_stop.is_set():
                    return
            self._watch_stop.wait(1.0)  # back off before reconnecting

    # -- tiny JSON-over-HTTP client (gateway: POST /v3/<rpc>) -----------

    def _call(self, rpc: str, body: dict) -> dict:
        import json as _json
        import urllib.request

        last: Exception = RuntimeError("no etcd endpoints")
        for ep in self.endpoints:
            try:
                req = urllib.request.Request(
                    f"{ep}/v3/{rpc}", data=_json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as f:
                    return _json.loads(f.read() or b"{}")
            except Exception as e:  # noqa: BLE001 - try next endpoint
                last = e
        raise last

    def _self_key(self) -> bytes:
        return (self.prefix + self.self_info.grpc_address).encode()

    def _register(self) -> None:
        lease = self._call("lease/grant", {"TTL": str(self.ttl_s)})
        self.lease_id = lease["ID"]
        self._call("kv/put", {
            "key": self._b64(self._self_key()),
            "value": self._b64(json.dumps(
                _peer_dict(self.self_info)).encode()),
            "lease": self.lease_id,
        })

    def _keepalive(self) -> None:
        try:
            resp = self._call("lease/keepalive", {"ID": self.lease_id})
            # the gateway answers an EXPIRED lease with HTTP 200 and
            # TTL<=0/absent — that's a failure, not a success
            ttl = int((resp.get("result") or {}).get("TTL") or 0)
            if ttl > 0:
                return
            log.warning("etcd lease %s expired; re-registering",
                        self.lease_id)
        except Exception as e:  # noqa: BLE001 - re-register below
            log.warning("etcd keepalive: %s; re-registering", e)
        try:
            self._register()
        except Exception as e2:  # noqa: BLE001
            log.warning("etcd re-register failed: %s", e2)

    @staticmethod
    def _range_end(start: bytes) -> bytes:
        """etcd prefix range end: increment the last byte, carrying over
        0xff bytes; all-0xff or empty prefix scans to the end of the
        keyspace (etcd convention: range_end = b"\\x00")."""
        end = bytearray(start)
        while end:
            if end[-1] < 0xFF:
                end[-1] += 1
                return bytes(end)
            end.pop()
        return b"\x00"

    def _poll(self) -> None:
        with self._poll_mu:  # fetch→notify is atomic vs the watch thread
            start = self.prefix.encode()
            try:
                resp = self._call("kv/range", {
                    "key": self._b64(start),
                    "range_end": self._b64(self._range_end(start))})
            except Exception as e:  # noqa: BLE001 - keep last membership
                log.warning("etcd range: %s", e)
                return
            peers = []
            for kv in resp.get("kvs", []):
                try:
                    peers.append(_peer_info(
                        json.loads(self._unb64(kv["value"]))))
                except (ValueError, KeyError):
                    continue
            # empty-but-successful range = genuinely no registrations
            # (e.g. our own lease just expired): report it;
            # re-registration on the next keepalive tick restores
            # membership
            self._notify(sorted(peers, key=lambda p: p.grpc_address))

    def close(self) -> None:
        self.mark_closed()
        self._watch_stop.set()
        self._keep.close()
        self._loop.close()
        try:
            self._call("kv/deleterange",
                       {"key": self._b64(self._self_key())})
        except Exception:  # noqa: BLE001 - lease expiry cleans up
            pass
        if self._watcher is not None:
            # daemon thread; may be mid-blocking-read — don't linger
            self._watcher.join(timeout=0.2)


class K8sDiscovery(Discovery):
    """kubernetes.go › K8sPool analog over the raw API server (no
    client library): reads the in-cluster service-account token + CA,
    watches Endpoints (by service name) or Pods (by label selector) —
    `?watch=1` streaming, the raw form of client-go informers — and
    maps addresses to peers at ``grpc_port``.  The interval poll stays
    as the resilience backstop (watch reconnects, missed events), same
    structure as EtcdDiscovery's watch."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, on_change: OnChange, namespace: str, selector: str,
                 grpc_port: int, service: str = "", api_base: str = "",
                 token: str = "", ca_file: str = "",
                 insecure_skip_verify: bool = False,
                 poll_interval_ms: int = 15_000, watch: bool = True):
        super().__init__(on_change)
        self.grpc_port = grpc_port
        self.namespace = namespace or self._read(f"{self.SA_DIR}/namespace",
                                                 "default")
        self.selector = selector
        self.service = service
        if not selector and not service:
            raise ValueError(
                "k8s discovery needs GUBER_K8S_POD_SELECTOR or "
                "GUBER_K8S_SERVICE — listing every Endpoints object in "
                "the namespace would pull foreign services into the ring")
        if not api_base:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "k8s discovery: not in a cluster (no "
                    "KUBERNETES_SERVICE_HOST) and no api_base given; use "
                    "GUBER_PEER_DISCOVERY_TYPE=dns with a headless "
                    "service instead")
            api_base = f"https://{host}:{port}"
        self.api_base = api_base
        self.token = token or self._read(f"{self.SA_DIR}/token", "")
        self.ca_file = ca_file or (
            f"{self.SA_DIR}/ca.crt"
            if os.path.exists(f"{self.SA_DIR}/ca.crt") else "")
        self.insecure = insecure_skip_verify
        if (self.api_base.startswith("https") and not self.ca_file
                and not self.insecure):
            # never silently skip verification while sending the bearer
            # token — an impersonated API server could steal it and
            # inject attacker peers into the ring
            raise RuntimeError(
                "k8s discovery: HTTPS API server but no CA cert found; "
                "provide ca_file or set GUBER_K8S_INSECURE=true "
                "(insecure_skip_verify) explicitly")
        self._poll_mu = threading.Lock()  # watch vs interval ordering
        #: list resourceVersion: watches resume FROM it, so reconnects
        #: replay nothing (the informer pattern — without it the API
        #: server would re-send synthetic ADDED events for every object
        #: on each reconnect, each triggering a full relist)
        self._rv: Optional[str] = None
        self._poll()
        self._loop = IntervalLoop(poll_interval_ms, self._poll,
                                  name="k8s-discovery")
        self._watch_stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        if watch:
            self._watcher = threading.Thread(
                target=self._watch_loop, daemon=True, name="k8s-watch")
            self._watcher.start()

    @staticmethod
    def _read(path: str, default: str) -> str:
        try:
            with open(path) as f:
                return f.read().strip()
        except OSError:
            return default

    def _ssl_ctx(self):
        import ssl as _ssl

        ctx = _ssl.create_default_context(cafile=self.ca_file or None)
        if not self.ca_file and self.insecure:
            ctx.check_hostname = False
            ctx.verify_mode = _ssl.CERT_NONE
        return ctx

    def _request(self, path: str):
        import urllib.request

        req = urllib.request.Request(self.api_base + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return req

    def _get(self, path: str) -> dict:
        import urllib.request

        with urllib.request.urlopen(self._request(path), timeout=10,
                                    context=self._ssl_ctx()) as f:
            return json.loads(f.read())

    def _watch_path(self) -> str:
        from urllib.parse import quote

        if self.selector:
            base = (f"/api/v1/namespaces/{self.namespace}/pods"
                    f"?labelSelector={quote(self.selector)}&watch=1")
        else:
            base = (f"/api/v1/namespaces/{self.namespace}/endpoints"
                    f"?fieldSelector=metadata.name%3D{quote(self.service)}"
                    "&watch=1")
        # server-side timeout keeps idle streams cycling gracefully
        # (bounded, no client-side read-timeout churn); resuming from
        # the last list's resourceVersion means a reconnect replays
        # nothing
        base += "&timeoutSeconds=300&allowWatchBookmarks=true"
        if self._rv:
            base += f"&resourceVersion={quote(str(self._rv))}"
        return base

    def _watch_loop(self) -> None:
        """Long-lived `?watch=1` stream (newline-delimited JSON events);
        a real event triggers an authoritative re-poll (which also
        refreshes the resume resourceVersion) — serialized against the
        interval poll via _poll_mu.  BOOKMARK events only advance the
        resume point; ERROR (e.g. 410 Gone: the version expired) drops
        it so the next connect starts from a fresh list."""
        import urllib.request

        while not self._watch_stop.is_set():
            try:
                req = self._request(self._watch_path())
                with urllib.request.urlopen(req, timeout=330,
                                            context=self._ssl_ctx()) as f:
                    while not self._watch_stop.is_set():
                        line = f.readline()
                        if not line:
                            break  # stream closed: reconnect
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        kind = ev.get("type")
                        if kind == "ERROR":
                            self._rv = None
                            break
                        if kind == "BOOKMARK":
                            rv = ((ev.get("object") or {})
                                  .get("metadata", {})
                                  .get("resourceVersion"))
                            if rv:
                                self._rv = rv
                            continue
                        if kind and not self._watch_stop.is_set():
                            self._poll()
            except Exception:  # noqa: BLE001 - reconnect below
                pass
            self._watch_stop.wait(1.0)  # back off before reconnecting

    def _poll(self) -> None:
        with self._poll_mu:
            self._poll_locked()

    def _poll_locked(self) -> None:
        from urllib.parse import quote

        try:
            if self.selector:
                obj = self._get(
                    f"/api/v1/namespaces/{self.namespace}/pods"
                    f"?labelSelector={quote(self.selector)}")
                ips = sorted({
                    item["status"]["podIP"]
                    for item in obj.get("items", [])
                    if item.get("status", {}).get("podIP")
                    and item["status"].get("phase") == "Running"})
            else:
                obj = self._get(
                    f"/api/v1/namespaces/{self.namespace}/endpoints/"
                    f"{quote(self.service)}")
                ips = sorted({
                    addr["ip"]
                    for subset in obj.get("subsets", []) or []
                    for addr in subset.get("addresses", []) or []})
        except Exception as e:  # noqa: BLE001 - keep last membership
            log.warning("k8s discovery poll: %s", e)
            return
        # refresh the watch resume point from the authoritative list
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        if rv:
            self._rv = rv
        # an empty SUCCESSFUL result is real membership (all pods
        # unready): notify it so the instance falls back to local-only
        # instead of forwarding to dead addresses
        self._notify([PeerInfo(grpc_address=f"{ip}:{self.grpc_port}")
                      for ip in ips])

    def close(self) -> None:
        self.mark_closed()
        self._watch_stop.set()
        self._loop.close()
        if self._watcher is not None:
            # daemon thread; may be mid-blocking-read — don't linger
            self._watcher.join(timeout=0.2)


def make_discovery(cfg: DaemonConfig, self_info: PeerInfo,
                   on_change: OnChange) -> Optional[Discovery]:
    """Wire the configured discovery source (daemon.go › SpawnDaemon)."""
    t = cfg.peer_discovery_type
    if t in ("none", ""):
        return None
    if t == "static":
        peers = parse_peer_list(cfg.static_peers, cfg.data_center)
        if self_info.grpc_address not in [p.grpc_address for p in peers]:
            peers.append(self_info)
        return StaticDiscovery(on_change, peers)
    if t == "file":
        return FileDiscovery(on_change, cfg.peers_file,
                             default_dc=cfg.data_center)
    if t == "dns":
        from .netutil import split_host_port

        _, grpc_port = split_host_port(cfg.grpc_listen_address)
        return DnsDiscovery(on_change, cfg.dns_fqdn, grpc_port,
                            cfg.dns_resolve_interval_ms, cfg.data_center)
    if t in ("member-list", "memberlist", "gossip"):
        from .netutil import split_host_port

        host, grpc_port = split_host_port(self_info.grpc_address)
        bind = f"{host}:{grpc_port + 1}"
        return GossipDiscovery(on_change, bind, self_info,
                               cfg.memberlist_known_hosts)
    if t == "etcd":
        return EtcdDiscovery(on_change, cfg.etcd_endpoints, cfg.etcd_prefix,
                             self_info)
    if t == "k8s":
        from .netutil import split_host_port

        _, grpc_port = split_host_port(cfg.grpc_listen_address)
        return K8sDiscovery(on_change, cfg.k8s_namespace,
                            cfg.k8s_pod_selector, grpc_port,
                            service=cfg.k8s_service,
                            insecure_skip_verify=cfg.k8s_insecure_skip_verify)
    raise ValueError(f"unknown peer discovery type: {t!r}")

"""Fleet watchtower (ISSUE 19): cluster-wide observability plane +
an always-on conservation auditor.

Every prior plane (tenants, SLO, traces, memory, compile ledger) is
daemon-local, and the repo's strongest invariant — exact hit
conservation through the GLOBAL reconcile discipline — was only ever
proven inside tests and ``make chaos``.  This module turns that oracle
into production telemetry:

- **AuditTap** — a cheap double-entry ledger each ``GlobalManager``
  maintains: ``injected`` counts hit weight at queue-entry,
  ``applied`` counts it at flush-ack (or absorbed locally when this
  daemon IS the owner), ``lost`` counts weight dropped on the
  unparseable-TLV path.  The ledger is SENDER-side and self-contained,
  so per-daemon backlogs sum exactly across a fleet — no cross-daemon
  coordination, no clock agreement.

- **ConservationAuditor** — one per instance, always on (gate with
  ``GUBER_FLEET_AUDIT=0``): folds the tap with the live queue depth
  and the mesh tier's injected/folded counters into the
  ``GET /debug/audit`` document, drives the
  ``gubernator_fleet_conservation_drift`` gauge, and feeds the
  ``fleet_conservation`` SLO (threshold kind: seconds since the
  backlog last drained to zero vs the drift bound).  The vector lags
  true state by at most one ``global_sync_wait_ms`` flush window
  (RESILIENCE.md › Staleness bound).

- **fold_audits / RingWatch / merge_*** — the fleet aggregation
  plane: exact cross-daemon folds of the audit vectors, heavy-hitter
  sketches (via the sketch's exact Space-Saving merge), tenant RED
  ledgers (Σ per-daemon == fleet, asserted), SLO burn rollup
  (worst-of latch + summed burn), memory pressure, and a
  ring/membership consistency check whose disagreement emits the
  ``fleet_ring_divergence`` flight-recorder event (cleared by
  ``fleet_ring_converged``).  ``tools/fleet_watch.py`` and
  ``guber-cli fleet`` fan daemons' debug endpoints into these folds;
  the scenario lab and chaos matrix fold in-process documents — the
  same documents the endpoint serves.

The identity the auditor proves, per daemon and fleet-wide::

    injected == applied + queued + in_flight + lost

``backlog = injected - applied`` is the drift gauge: nonzero while a
partition holds flushed aggregates in the requeue loop, exactly zero
once reconcile completes.  ``in_flight`` (backlog - queued - lost) is
transiently nonzero mid-flush; persistently nonzero means hits left
the queue and never acked — the loss detector.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

#: per-tenant RED ledger columns (analytics.TenantLedger.FIELDS) —
#: duplicated literally so the merge plane works on plain JSON docs
#: without importing the analytics worker machinery
TENANT_FIELDS = ("requests", "hits", "over_limit", "errors",
                 "degraded", "shed")


def audit_enabled() -> bool:
    """GUBER_FLEET_AUDIT=0 disables the conservation audit taps (the
    /debug/audit document still serves, reporting zeros)."""
    return os.environ.get("GUBER_FLEET_AUDIT", "1") != "0"


def drift_bound_s(behaviors) -> float:
    """The fleet_conservation SLO target: how long the audit backlog
    may stay nonzero before the objective counts the tick bad.
    Default 2× the GLOBAL flush window (one window to flush + one to
    ack); GUBER_FLEET_DRIFT_BOUND overrides (duration string)."""
    v = os.environ.get("GUBER_FLEET_DRIFT_BOUND", "")
    if v:
        from .config import parse_duration_ms

        try:
            return max(parse_duration_ms(v) / 1000.0, 1e-3)
        except (ValueError, TypeError):
            pass
    wait_ms = int(getattr(behaviors, "global_sync_wait_ms", 1000))
    return 2.0 * max(wait_ms, 100) / 1000.0


class AuditTap:
    """Sender-side double-entry hit ledger for one GlobalManager.

    Monotonic counters only (the live queue depth is read from the
    queues themselves), own leaf lock, touched OUTSIDE the manager's
    ``_mu`` — the tap adds no edge to the lock order.  ``degraded``
    shares ride as parallel counters so the audit vector can report
    how much of the backlog is degraded-mode reconcile debt."""

    __slots__ = ("_mu", "injected", "applied", "deg_injected",
                 "deg_applied", "absorbed", "lost")

    def __init__(self):
        self._mu = threading.Lock()
        self.injected = 0  # guarded-by: self._mu
        self.applied = 0  # guarded-by: self._mu
        self.deg_injected = 0  # guarded-by: self._mu
        self.deg_applied = 0  # guarded-by: self._mu
        #: subset of ``applied`` that never crossed the wire (this
        #: daemon was the owner; the serve already applied the hits)
        self.absorbed = 0  # guarded-by: self._mu
        #: weight dropped on the unparseable-TLV path: injected,
        #: never applied — permanent drift, the loss detector
        self.lost = 0  # guarded-by: self._mu

    def inject(self, n: int, degraded: bool = False) -> None:
        if n <= 0:
            return
        with self._mu:
            self.injected += n
            if degraded:
                self.deg_injected += n

    def apply(self, n: int, deg: int = 0,
              absorbed: bool = False) -> None:
        if n <= 0:
            return
        with self._mu:
            self.applied += n
            self.deg_applied += min(deg, n)
            if absorbed:
                self.absorbed += n

    def lose(self, n: int, deg: int = 0) -> None:
        if n <= 0:
            return
        with self._mu:
            self.lost += n
            # a dropped entry's degraded share is settled too (it will
            # never flush); keeps deg_pending == pending degraded debt
            self.deg_applied += min(deg, n)

    def snapshot(self) -> Dict[str, int]:
        with self._mu:
            return {"injected": self.injected, "applied": self.applied,
                    "deg_injected": self.deg_injected,
                    "deg_applied": self.deg_applied,
                    "absorbed": self.absorbed, "lost": self.lost}


class ConservationAuditor:
    """One instance's always-on conservation audit vector.

    Reads only already-maintained state (the tap's counters, the
    queues' accumulators, the mesh tier's stats) — no new threads; the
    SLO engine's tick doubles as the sample cadence, and the
    ``GET /debug/audit`` handler computes the document on demand."""

    def __init__(self, instance):
        self.instance = instance
        self.enabled = audit_enabled()
        self.bound_s = drift_bound_s(instance.config.behaviors)
        self._mu = threading.Lock()
        #: monotonic stamp of the last drift == 0 observation; the SLO
        #: value is the age of this stamp (0 while conserved)
        self._last_zero = time.monotonic()  # guarded-by: self._mu

    # ---- the audit vector ----------------------------------------------

    def _lanes(self) -> Tuple[dict, Optional[dict]]:
        inst = self.instance
        g = {"injected": 0, "applied": 0, "deg_injected": 0,
             "deg_applied": 0, "absorbed": 0, "lost": 0,
             "queued": 0, "deg_queued": 0}
        gm = inst.global_manager
        if gm is not None:
            tap = gm.audit
            if tap is not None:
                g.update(tap.snapshot())
            q, dq = gm.queued_hits()
            g["queued"], g["deg_queued"] = q, dq
        g["backlog"] = g["injected"] - g["applied"]
        g["in_flight"] = g["backlog"] - g["queued"] - g["lost"]
        g["deg_pending"] = g["deg_injected"] - g["deg_applied"]
        m = None
        mge = inst._meshglobal
        if mge is not None:
            st = mge.stats()
            m = {"injected": int(st["injected_hits"]),
                 "folded": int(st["folded_hits"]),
                 "backlog": int(st["injected_hits"])
                 - int(st["folded_hits"]),
                 "generation": st["generation"],
                 "pinned_keys": st["pinned_keys"],
                 "last_staleness_s": st["last_staleness_s"]}
        return g, m

    def _drift_of(self, g: dict, m: Optional[dict]) -> int:
        return int(g["backlog"]) + (int(m["backlog"]) if m else 0)

    def _age(self, drift: int, now: float) -> float:
        with self._mu:
            if drift == 0:
                self._last_zero = now
            return max(now - self._last_zero, 0.0)

    def slo_sample(self) -> Tuple[float, float]:
        """fleet_conservation source (threshold kind): (seconds the
        audit backlog has been nonzero, the drift bound).  Also drives
        the drift gauge — the sample rides the SLO tick, no loop of
        its own."""
        g, m = self._lanes()
        drift = self._drift_of(g, m)
        age = self._age(drift, time.monotonic())
        self.instance.metrics.fleet_conservation_drift.set(float(drift))
        return (age, self.bound_s)

    def _ring(self) -> dict:
        inst = self.instance
        membership = sorted(p.info.grpc_address for p in inst.peers())
        ejected = sorted(set(inst._gate_bad) & set(membership))
        routing = [a for a in membership if a not in set(ejected)]
        return {"generation": int(inst._ring_gen),
                "self": inst._self_addr,
                "membership": membership, "routing": routing,
                "ejected": ejected}

    def doc(self) -> dict:
        """The ``GET /debug/audit`` document — also what the fleet
        fold, the scenario-lab oracle, and the chaos cells consume
        (the acceptance criterion's "no test-harness walking": every
        judge reads the daemon's own vector)."""
        g, m = self._lanes()
        drift = self._drift_of(g, m)
        age = self._age(drift, time.monotonic())
        self.instance.metrics.fleet_conservation_drift.set(float(drift))
        lanes = {"global": g}
        if m is not None:
            lanes["mesh"] = m
        behaviors = self.instance.config.behaviors
        return {"instance": self.instance._self_addr,
                "enabled": self.enabled,
                "drift": drift, "conserved": drift == 0,
                "lost": g["lost"],
                "drain_age_s": round(age, 6),
                "bound_s": round(self.bound_s, 6),
                "flush_window_ms":
                    int(behaviors.global_sync_wait_ms),
                "lanes": lanes, "ring": self._ring()}


# ---- fleet folds (pure functions over /debug documents) ----------------


def fold_audits(docs: List[dict]) -> dict:
    """Fold per-daemon audit vectors into the fleet conservation
    verdict.  The ledgers are sender-side and self-contained, so the
    fold is a plain sum: Σ backlog == fleet drift, exactly."""
    tot = {"injected": 0, "applied": 0, "queued": 0, "in_flight": 0,
           "absorbed": 0, "lost": 0, "deg_pending": 0,
           "mesh_injected": 0, "mesh_folded": 0}
    per: List[dict] = []
    drift = 0
    max_age = 0.0
    bound = 0.0
    stale_ms = 0
    for d in docs:
        g = d.get("lanes", {}).get("global", {})
        m = d.get("lanes", {}).get("mesh")
        for f in ("injected", "applied", "queued", "in_flight",
                  "absorbed", "lost", "deg_pending"):
            tot[f] += int(g.get(f, 0))
        if m:
            tot["mesh_injected"] += int(m.get("injected", 0))
            tot["mesh_folded"] += int(m.get("folded", 0))
        drift += int(d.get("drift", 0))
        max_age = max(max_age, float(d.get("drain_age_s", 0.0)))
        bound = max(bound, float(d.get("bound_s", 0.0)))
        stale_ms = max(stale_ms, int(d.get("flush_window_ms", 0)))
        per.append({"instance": d.get("instance"),
                    "drift": int(d.get("drift", 0)),
                    "drain_age_s": d.get("drain_age_s", 0.0),
                    "backlog": int(g.get("backlog", 0)),
                    "queued": int(g.get("queued", 0)),
                    "in_flight": int(g.get("in_flight", 0)),
                    "lost": int(g.get("lost", 0)),
                    "deg_pending": int(g.get("deg_pending", 0))})
    return {"daemons": len(docs), "drift": drift,
            "conserved": drift == 0, "totals": tot,
            "per_daemon": per,
            "max_drain_age_s": round(max_age, 6),
            "bound_s": round(bound, 6),
            #: audit vectors lag true state by at most one flush
            #: window (RESILIENCE.md › Staleness bound)
            "staleness_bound_s": round(stale_ms / 1000.0, 6)}


def ring_verdict(docs: List[dict]) -> dict:
    """Stateless ring/membership consistency check over audit docs:
    every daemon must agree on the peer set, and no daemon may be
    routing around an ejected member (routing == membership
    everywhere).  Ring generations are per-daemon local counters —
    reported for diagnosis, never compared across daemons."""
    reasons = []
    memberships = {tuple(d.get("ring", {}).get("membership", []))
                   for d in docs}
    routings = {tuple(d.get("ring", {}).get("routing", []))
                for d in docs}
    if len(memberships) > 1:
        reasons.append("membership_mismatch")
    if len(routings) > 1:
        reasons.append("routing_mismatch")
    ejected = sorted({a for d in docs
                      for a in d.get("ring", {}).get("ejected", [])})
    if ejected:
        reasons.append("peers_ejected")
    return {"consistent": not reasons, "reasons": reasons,
            "daemons": len(docs), "ejected": ejected,
            "generations": {d.get("instance"):
                            d.get("ring", {}).get("generation")
                            for d in docs}}


class RingWatch:
    """Edge-triggered wrapper around :func:`ring_verdict`: the first
    inconsistent check records ``fleet_ring_divergence`` into the
    given flight recorder; the first consistent check after that
    records ``fleet_ring_converged``.  One watch per observer (the
    fleet tick, a chaos cell) — the latch is the observer's."""

    def __init__(self):
        self._diverged = False

    def check(self, docs: List[dict], recorder=None) -> dict:
        v = ring_verdict(docs)
        if recorder is not None:
            if not v["consistent"] and not self._diverged:
                recorder.record("fleet_ring_divergence",
                                daemons=v["daemons"],
                                reasons=",".join(v["reasons"]),
                                ejected=",".join(v["ejected"]))
            elif v["consistent"] and self._diverged:
                recorder.record("fleet_ring_converged",
                                daemons=v["daemons"])
        self._diverged = not v["consistent"]
        return v


def _kh_int(v) -> int:
    return int(v, 16) if isinstance(v, str) else int(v)


def merge_topkeys(docs: List[dict], k: Optional[int] = None) -> dict:
    """Cluster top-K: fold every daemon's /debug/topkeys document
    through the sketch's exact Space-Saving merge
    (analytics.HeavyHitterSketch.merge_entries).  With key-partitioned
    traffic and enough width the merged sketch is EXACT — byte-equal
    to a single sketch fed the union stream (tests/test_fleet.py)."""
    from .analytics import HeavyHitterSketch

    kk = k or max([int(d.get("k") or 0) for d in docs] + [256])
    width = max([int(d.get("width") or 0) for d in docs] + [4 * kk])
    sk = HeavyHitterSketch(k=kk, width=width)
    owners: Dict[int, str] = {}
    for d in docs:
        entries = d.get("keys", [])
        sk.merge_entries(entries,
                         total_weight=d.get("total_hits_observed"))
        for e in entries:
            if e.get("owner"):
                owners[_kh_int(e["khash"])] = e["owner"]
    rows = sk.topk(kk)
    for e in rows:
        # ring-owner attribution survives the merge: all daemons agree
        # on owners while the ring is consistent (ring_verdict guards)
        e["owner"] = owners.get(e["khash"])
        e["khash"] = f"0x{e['khash']:016x}"
    return {"daemons": len(docs), "k": kk, "width": width,
            "total_hits_observed": int(sk.total_weight),
            "admission_error_bound": sk.error_bound(),
            "keys": rows}


def merge_tenants(docs: List[dict]) -> dict:
    """Fleet tenant RED rollup: field-wise sums per tenant across
    daemons, with the conservation assertion — every daemon's
    per-tenant counts must sum to its own totals row, and the fleet
    totals must equal the per-tenant fleet sums (both exact; a
    mismatch flags the source daemon by index)."""
    tenants: Dict[str, Dict[str, int]] = {}
    totals = {f: 0 for f in TENANT_FIELDS}
    mismatches: List[int] = []
    enabled = 0
    for i, d in enumerate(docs):
        if not d.get("enabled", True):
            continue
        enabled += 1
        own = {f: 0 for f in TENANT_FIELDS}
        for name, c in d.get("tenants", {}).items():
            row = tenants.setdefault(name,
                                     {f: 0 for f in TENANT_FIELDS})
            for f in TENANT_FIELDS:
                v = int(c.get(f, 0))
                row[f] += v
                own[f] += v
        dt = d.get("totals", {})
        if own != {f: int(dt.get(f, 0)) for f in TENANT_FIELDS}:
            mismatches.append(i)
        for f in TENANT_FIELDS:
            totals[f] += int(dt.get(f, 0))
    fleet_sum = {f: sum(t[f] for t in tenants.values())
                 for f in TENANT_FIELDS}
    conserved = not mismatches and fleet_sum == totals
    return {"daemons": len(docs), "enabled_daemons": enabled,
            "tenant_count": len(tenants), "tenants": tenants,
            "totals": totals, "conserved": conserved,
            "mismatched_daemons": mismatches,
            "overflowed": any(d.get("overflowed") for d in docs)}


def merge_slo(docs: List[dict]) -> dict:
    """Fleet SLO rollup: worst-of latch (breached anywhere == breached
    fleet-wide) plus summed burn across daemons — budget spend is
    additive when the objective is fleet-shared, while the max shows
    the worst single daemon."""
    rows: Dict[tuple, dict] = {}
    for d in docs:
        for r in d.get("slos", []):
            key = (r.get("slo"), r.get("tenant") or "")
            cur = rows.get(key)
            if cur is None:
                cur = rows[key] = {
                    "slo": r.get("slo"), "kind": r.get("kind"),
                    "objective": r.get("objective"),
                    "breached": False, "daemons": 0,
                    "fast_burn_max": 0.0, "slow_burn_max": 0.0,
                    "fast_burn_sum": 0.0, "slow_burn_sum": 0.0}
                if r.get("tenant"):
                    cur["tenant"] = r["tenant"]
            cur["daemons"] += 1
            cur["breached"] = cur["breached"] or bool(r.get("breached"))
            fb = float(r.get("fast_burn") or 0.0)
            sb = float(r.get("slow_burn") or 0.0)
            cur["fast_burn_max"] = max(cur["fast_burn_max"], fb)
            cur["slow_burn_max"] = max(cur["slow_burn_max"], sb)
            cur["fast_burn_sum"] = round(cur["fast_burn_sum"] + fb, 6)
            cur["slow_burn_sum"] = round(cur["slow_burn_sum"] + sb, 6)
            if r.get("value") is not None:
                cur["value_max"] = max(float(r["value"]),
                                       cur.get("value_max", 0.0))
                cur["target"] = r.get("target")
    out = sorted(rows.values(),
                 key=lambda r: (r["slo"], r.get("tenant", "")))
    return {"daemons": len(docs),
            "ticks": sum(int(d.get("ticks", 0)) for d in docs),
            "breached": sorted({r["slo"] for r in out
                                if r["breached"]}),
            "slos": out}


def merge_memory(docs: List[dict]) -> dict:
    """Fleet memory-ledger pressure: summed bytes, per-daemon pressure
    rows, and consumer byte totals folded by name."""
    consumers: Dict[str, int] = {}
    per: List[dict] = []
    dev = host = 0
    worst = 0.0
    for d in docs:
        dev += int(d.get("device_bytes", 0))
        host += int(d.get("host_bytes", 0))
        p = float(d.get("pressure", 0.0))
        worst = max(worst, p)
        per.append({"device_bytes": int(d.get("device_bytes", 0)),
                    "host_bytes": int(d.get("host_bytes", 0)),
                    "pressure": p,
                    "pressure_target": d.get("pressure_target")})
        for name, rec in d.get("consumers", {}).items():
            if isinstance(rec, dict) and "bytes" in rec:
                consumers[name] = (consumers.get(name, 0)
                                   + int(rec["bytes"]))
    return {"daemons": len(docs), "device_bytes": dev,
            "host_bytes": host, "max_pressure": round(worst, 6),
            "per_daemon": per, "consumer_bytes": consumers}


def merge_status(health_docs: List[dict],
                 audit_docs: Optional[List[dict]] = None) -> dict:
    """Fleet status: healthz rollup + the ring consistency verdict
    (when audit docs ride along)."""
    statuses = [d.get("status", "unreachable") for d in health_docs]
    out = {"daemons": len(health_docs),
           "healthy": sum(1 for s in statuses if s == "healthy"),
           "statuses": statuses,
           "peer_counts": [d.get("peer_count")
                           for d in health_docs]}
    if audit_docs:
        out["ring"] = ring_verdict(audit_docs)
        fold = fold_audits(audit_docs)
        out["conservation"] = {"drift": fold["drift"],
                               "conserved": fold["conserved"]}
    return out

"""Peer picking: who owns a key.

reference: hash.go › ConsistantHash (upstream spelling), replicated_hash.go
› ReplicatedConsistentHash (virtual-node ring, default 512 replicas),
region_picker.go › RegionPeerPicker — reconstructed, mount empty.

Two layers of ownership exist in the TPU design (SURVEY.md §2.3):

- **intra-node**: keys → device shards by hash range (hashing.shard_of),
  invisible to peers;
- **inter-node**: keys → daemon processes via these pickers, exactly like
  the reference (forwarded over the peer wire protocol).

Pickers map a key string to a peer object (anything carrying a
``.info: PeerInfo``).  They are immutable once built — SetPeers builds a
new picker and swaps it atomically (gubernator.go › SetPeers).
"""
from __future__ import annotations

import bisect
import hashlib
from typing import Callable, Dict, Generic, List, Optional, TypeVar

import numpy as np

from .hashing import fnv1a64, mix64, mixed_fnv1a64
from .types import PeerInfo

P = TypeVar("P")


def crc64_hash(data: bytes) -> int:
    """Alternate hash function option (reference offers fnv1/crc64)."""
    # crc64 isn't in hashlib; use blake2b-8byte as the "other" option —
    # pickers only need determinism + uniformity, and the choice is
    # per-deployment, not wire-visible.
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")


HashFn = Callable[[bytes], int]


class ConsistentHash(Generic[P]):
    """Modulo-style hash picker.

    reference: hash.go › ConsistantHash — hashes each key and picks
    ``peers[hash % len(peers)]`` over a sorted peer list.  Simple, even,
    but remaps ~all keys on membership change; kept for parity, the
    replicated ring below is the default.
    """

    def __init__(self, hash_fn: HashFn = mixed_fnv1a64):
        self._hash = hash_fn
        self._peers: List[P] = []
        self._by_addr: Dict[str, P] = {}

    def new(self) -> "ConsistentHash[P]":
        return ConsistentHash(self._hash)

    def add(self, peer: P) -> None:
        self._peers.append(peer)
        self._peers.sort(key=lambda p: p.info.grpc_address)  # type: ignore
        self._by_addr[peer.info.grpc_address] = peer  # type: ignore

    def peers(self) -> List[P]:
        return list(self._peers)

    def get_by_peer_info(self, info: PeerInfo) -> Optional[P]:
        return self._by_addr.get(info.grpc_address)

    def get(self, key: str) -> P:
        if not self._peers:
            raise RuntimeError("picker has no peers")
        return self.get_by_hash(self._hash(key.encode("utf-8")))

    def get_by_hash(self, h: int) -> P:
        """Owner for an already-hashed key (stateful handover routes by
        the table's 64-bit key hashes; valid only when this picker uses
        the default mixed_fnv1a64 — the same pipeline hashing.hash_key
        applies to build them)."""
        if not self._peers:
            raise RuntimeError("picker has no peers")
        return self._peers[h % len(self._peers)]

    def get_by_raw_hash(self, h: int) -> P:
        """Owner for a RAW FNV-1a64 key hash — the wire lanes' async
        queue key space (global_manager._hits_raw et al.).  Applies the
        mix64 finalizer, exactly matching get(key)'s mixed_fnv1a64
        pipeline, so raw-queue flushes route without materializing key
        strings.  Same default-hash caveat as get_by_hash."""
        return self.get_by_hash(mix64(h))

    def owner_indices(self, hashes: np.ndarray) -> np.ndarray:
        """Vectorized get_by_hash: int32 index into ``peers()`` order per
        uint64 key hash (the clustered wire fast lane's ring split —
        instance.py › _wire_check_clustered).  Same hash-pipeline caveat
        as get_by_hash."""
        if not self._peers:
            raise RuntimeError("picker has no peers")
        kh = np.asarray(hashes, np.uint64)
        return (kh % np.uint64(len(self._peers))).astype(np.int32)

    def owner_peers(self) -> List[P]:
        """The peer list ``owner_indices`` results index into."""
        return list(self._peers)


class ReplicatedConsistentHash(Generic[P]):
    """Virtual-node hash ring.

    reference: replicated_hash.go › ReplicatedConsistentHash — each peer
    is hashed onto the ring ``replicas`` times (default 512); a key is
    owned by the first ring point clockwise from its hash.  Membership
    change only remaps keys adjacent to the changed peer's points.
    """

    DEFAULT_REPLICAS = 512

    def __init__(self, hash_fn: HashFn = mixed_fnv1a64,
                 replicas: int = DEFAULT_REPLICAS):
        self._hash = hash_fn
        self.replicas = replicas
        self._ring: List[int] = []  # sorted ring point hashes
        self._ring_peer: List[P] = []  # peer at same index
        self._points: Dict[int, P] = {}
        self._peers: List[P] = []
        self._by_addr: Dict[str, P] = {}

    def new(self) -> "ReplicatedConsistentHash[P]":
        return ReplicatedConsistentHash(self._hash, self.replicas)

    def add(self, peer: P) -> None:
        addr = peer.info.grpc_address  # type: ignore
        self._peers.append(peer)
        self._by_addr[addr] = peer
        for i in range(self.replicas):
            h = self._hash(f"{addr}{i}".encode("utf-8"))
            self._points[h] = peer
        # rebuild sorted views (+ numpy mirrors for owner_indices)
        items = sorted(self._points.items())
        self._ring = [h for h, _ in items]
        self._ring_peer = [p for _, p in items]
        pos = {id(p): i for i, p in enumerate(self._peers)}
        self._ring_np = np.asarray(self._ring, dtype=np.uint64)
        self._ring_peer_idx = np.asarray(
            [pos[id(p)] for p in self._ring_peer], dtype=np.int32)

    def peers(self) -> List[P]:
        return list(self._peers)

    def get_by_peer_info(self, info: PeerInfo) -> Optional[P]:
        return self._by_addr.get(info.grpc_address)

    def get(self, key: str) -> P:
        if not self._ring:
            raise RuntimeError("picker has no peers")
        return self.get_by_hash(self._hash(key.encode("utf-8")))

    def get_by_hash(self, h: int) -> P:
        """Owner for an already-hashed key (see ConsistentHash
        .get_by_hash for the hash-pipeline caveat)."""
        if not self._ring:
            raise RuntimeError("picker has no peers")
        idx = bisect.bisect_left(self._ring, h)
        if idx == len(self._ring):
            idx = 0
        return self._ring_peer[idx]

    def get_by_raw_hash(self, h: int) -> P:
        """Owner for a RAW FNV-1a64 key hash (see ConsistentHash
        .get_by_raw_hash)."""
        return self.get_by_hash(mix64(h))

    def owner_indices(self, hashes: np.ndarray) -> np.ndarray:
        """Vectorized get_by_hash over the vnode ring: int32 index into
        ``peers()`` order per uint64 key hash.  np.searchsorted(side=
        "left") is exactly bisect_left, so this agrees with get()/
        get_by_hash bit-for-bit."""
        if not self._ring:
            raise RuntimeError("picker has no peers")
        idx = np.searchsorted(self._ring_np, np.asarray(hashes, np.uint64),
                              side="left")
        idx = np.where(idx == len(self._ring_np), 0, idx)
        return self._ring_peer_idx[idx]

    def owner_peers(self) -> List[P]:
        """The peer list ``owner_indices`` results index into."""
        return list(self._peers)


class RegionPeerPicker(Generic[P]):
    """Datacenter-aware picker: one inner picker per region.

    reference: region_picker.go › RegionPeerPicker — `get(key)` resolves
    in the local region; `pickers()` exposes every region for the
    multi-region manager's cross-DC fan-out (mutliregion.go).
    """

    def __init__(self, local_dc: str,
                 make_picker: Callable[[], object] = ReplicatedConsistentHash):
        self.local_dc = local_dc
        self._make = make_picker
        self.regions: Dict[str, object] = {}

    def new(self) -> "RegionPeerPicker[P]":
        return RegionPeerPicker(self.local_dc, self._make)

    def add(self, peer: P) -> None:
        dc = peer.info.datacenter or self.local_dc  # type: ignore
        picker = self.regions.get(dc)
        if picker is None:
            picker = self._make()
            self.regions[dc] = picker
        picker.add(peer)  # type: ignore

    def peers(self) -> List[P]:
        out: List[P] = []
        for picker in self.regions.values():
            out.extend(picker.peers())  # type: ignore
        return out

    def get_by_peer_info(self, info: PeerInfo) -> Optional[P]:
        picker = self.regions.get(info.datacenter or self.local_dc)
        return picker.get_by_peer_info(info) if picker else None  # type: ignore

    def _local_picker(self):
        """The local region's picker, or any region's as a degraded
        fallback — the single place the fallback policy lives."""
        picker = self.regions.get(self.local_dc)
        if picker is None:
            for picker in self.regions.values():
                break
            else:
                raise RuntimeError("picker has no peers")
        return picker

    def get(self, key: str) -> P:
        return self._local_picker().get(key)  # type: ignore

    def get_by_hash(self, h: int) -> P:
        return self._local_picker().get_by_hash(h)  # type: ignore

    def get_by_raw_hash(self, h: int) -> P:
        return self._local_picker().get_by_raw_hash(h)  # type: ignore

    def owner_indices(self, hashes: np.ndarray) -> np.ndarray:
        """Vectorized get() over the local region's ring; indices refer
        to ``owner_peers()`` order (NOT ``peers()``, which spans every
        region)."""
        return self._local_picker().owner_indices(hashes)  # type: ignore

    def owner_peers(self) -> List[P]:
        """The peer list ``owner_indices`` results index into."""
        return self._local_picker().peers()  # type: ignore

    def get_in_region(self, key: str, dc: str) -> Optional[P]:
        picker = self.regions.get(dc)
        return picker.get(key) if picker else None  # type: ignore

"""Prometheus metrics.

Mirrors the reference's metric surface (gubernator.go › Collector impl,
lrucache.go gauges, global.go queue/broadcast metrics — reconstructed)
with the same metric names where sensible, so existing dashboards can be
pointed at this service (SURVEY.md §5.5).  Each instance gets its own
CollectorRegistry (multiple daemons per process in the test cluster).

The full metric catalog lives in OBSERVABILITY.md; tools/check_metrics.py
(a tier-1 test) asserts every metric registered here is documented there
and that names are unique.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5, 1.0, 2.5)

#: wave durations reach minutes on a cold compile (250-305 s through
#: the axon tunnel) — the histogram must resolve that tail, not clip it
#: at 2.5 s, or the one event the watchdog exists for is invisible.
_WAVE_DURATION_BUCKETS = _BUCKETS + (10.0, 30.0, 60.0, 120.0, 300.0, 600.0)

#: requests per coalesced wave: 1 (idle inline) up to max_wave (8192
#: default) and beyond for merged packed columns
_WAVE_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 1024, 4096,
                      16384, 65536)


class Metrics:
    def __init__(self) -> None:
        r = self.registry = CollectorRegistry()
        self.getratelimit_counter = Counter(
            "gubernator_getratelimit", "GetRateLimits calls",
            ["calltype"], registry=r)
        self.over_limit_counter = Counter(
            "gubernator_over_limit", "OVER_LIMIT decisions", registry=r)
        self.check_error_counter = Counter(
            "gubernator_check_error", "errors while checking rate limits",
            ["error"], registry=r)
        self.func_duration = Histogram(
            "gubernator_func_duration", "handler durations (s)",
            ["name"], buckets=_BUCKETS, registry=r)
        self.batch_send_duration = Histogram(
            "gubernator_batch_send_duration",
            "peer batch flush durations (s)", ["peer_addr"],
            buckets=_BUCKETS, registry=r)
        self.queue_length = Gauge(
            "gubernator_global_queue_length",
            "pending GLOBAL hit aggregations", registry=r)
        self.broadcast_duration = Histogram(
            "gubernator_broadcast_duration", "GLOBAL broadcast durations (s)",
            buckets=_BUCKETS, registry=r)
        self.global_broadcast_counter = Counter(
            "gubernator_broadcast", "GLOBAL broadcasts sent", registry=r)
        self.cache_size = Gauge(
            "gubernator_cache_size", "live rows in the counter table",
            registry=r)
        self.cache_access_count = Counter(
            "gubernator_cache_access_count", "table lookups",
            ["type"], registry=r)
        self.concurrent_checks = Gauge(
            "gubernator_concurrent_checks_counter",
            "in-flight GetRateLimits batches", registry=r)
        self.cache_capacity = Gauge(
            "gubernator_cache_capacity",
            "total counter-table rows (grows under auto-grow)", registry=r)
        self.dropped_rows = Gauge(
            "gubernator_cache_dropped_rows",
            "live rows lost to grow/restore re-placement (each is a "
            "counter reset, the LRU-eviction analog)", registry=r)
        # Lane observability (VERDICT r1 weak #5/#8): the wire fast lane
        # and hot-set tier are perf cliffs when they silently disengage —
        # export where requests actually went so operators can see it.
        self.wire_lane_counter = Counter(
            "gubernator_wire_lane_requests",
            "requests by serving lane (wire-columnar vs pb2 fallback)",
            ["lane"], registry=r)
        self.hot_demotion_counter = Counter(
            "gubernator_hotset_demotions",
            "hot-set pinned keys demoted back to the sharded path",
            ["reason"], registry=r)
        # pallas-mode capacity safety (VERDICT r4 item 6): no on-device
        # grow, so full buckets — not total occupancy — are where new
        # keys start erring as table_full.  0 in xla mode.
        self.bucket_saturation = Gauge(
            "gubernator_pallas_bucket_saturation",
            "fraction of 8-slot buckets that are FULL (pallas serving "
            "mode; new keys hashing into a full bucket are unservable)",
            registry=r)
        # Fused serving engine (ISSUE 8): GUBER_ENGINE=pallas serves
        # each wave as ONE device program (decision kernel + on-device
        # heavy-hitter tap + mesh-GLOBAL accumulator scatter when that
        # tier is bound).  Zero for the classic engine.
        self.pallas_fused_waves = Counter(
            "gubernator_pallas_fused_waves",
            "waves served by the fused serving program (device tap "
            "emitted in-launch; no host-side tap copies)", registry=r)
        self.pallas_mesh_fused_hits = Counter(
            "gubernator_pallas_mesh_fused_hits",
            "mesh-GLOBAL hits scatter-added by the fused serving "
            "program (the injected side of the mesh conservation "
            "ledger for fused waves)", registry=r)
        self.jit_compiles = Counter(
            "gubernator_jit_compiles",
            "XLA compilations by jitted function (compile ledger, "
            "ISSUE 14); any growth after warmup is a retrace bug — "
            "a call site is recompiling the serving program",
            ["fn"], registry=r)
        self.scenario_runs = Counter(
            "gubernator_scenario_runs",
            "scenario-lab runs by verdict (scenarios.py, ISSUE 16)",
            ["verdict"], registry=r)
        # Dispatcher wave telemetry (ISSUE 1): the wave/queue/compile
        # layer is the hot path and was previously unobservable — a
        # 250-305 s cold compile surfaced only as an empty TimeoutError
        # at the caller.  dispatcher.py observes these per wave.
        self.wave_size = Histogram(
            "gubernator_dispatcher_wave_size",
            "requests per coalesced device wave",
            buckets=_WAVE_SIZE_BUCKETS, registry=r)
        self.wave_queue_wait = Histogram(
            "gubernator_dispatcher_queue_wait",
            "job wait from submit to its wave launching (s)",
            buckets=_BUCKETS, registry=r)
        self.wave_duration = Histogram(
            "gubernator_dispatcher_wave_duration",
            "device wave duration, launch to resolve (s); the tail "
            "buckets exist for cold compiles",
            buckets=_WAVE_DURATION_BUCKETS, registry=r)
        self.waves_in_flight = Gauge(
            "gubernator_dispatcher_waves_in_flight",
            "waves currently executing on the device (incl. pipelined "
            "launches awaiting sync)", registry=r)
        self.wave_timeout_counter = Counter(
            "gubernator_dispatcher_wave_timeouts",
            "caller waits that hit RESULT_TIMEOUT_S", registry=r)
        self.dispatcher_stalled = Gauge(
            "gubernator_dispatcher_stalled",
            "1 while any wave has been in flight longer than the stall "
            "threshold (a cold compile shows here minutes before "
            "callers time out)", registry=r)
        self.stall_event_counter = Counter(
            "gubernator_dispatcher_stall_events",
            "waves flagged stalled by the watchdog", registry=r)
        self.first_wave_duration = Gauge(
            "gubernator_dispatcher_first_wave_seconds",
            "duration of this dispatcher's FIRST wave (includes any "
            "cold compile the warmup did not cover)", registry=r)
        # Overlapped wave pipeline + wave-buffer pool (ISSUE 2): the
        # depth-K in-flight ring and the pooled packed-upload matrices
        # are new perf-critical moving parts — export their shape and
        # churn so a regression (pool thrash, a leaked lease, an
        # unexpected depth) is visible on /metrics.
        self.pipeline_depth = Gauge(
            "gubernator_dispatcher_pipeline_depth",
            "configured depth of the overlapped wave pipeline (0 = "
            "pipeline off: CPU default or capability-less engine)",
            registry=r)
        self.wave_buffer_pool_hit = Counter(
            "gubernator_wave_buffer_pool_hits",
            "wave upload-buffer leases served from the pool",
            registry=r)
        self.wave_buffer_pool_miss = Counter(
            "gubernator_wave_buffer_pool_misses",
            "wave upload-buffer leases that allocated fresh matrices",
            registry=r)
        self.wave_buffer_leaks = Counter(
            "gubernator_wave_buffer_leaks",
            "wave buffer leases dropped without release (reclaimed by "
            "the GC hook; must stay 0 — asserted by the soak tests)",
            registry=r)
        # Columnar peer send lanes (ISSUE 3): the pooled per-peer send
        # buffers, depth-K in-flight forward RPCs, and retry/circuit
        # machinery are the forward hop's moving parts — export their
        # shape so a backed-up or circuit-open peer is visible on
        # /metrics, not just as caller error strings.
        self.peer_send_buffer_depth = Gauge(
            "gubernator_peer_send_buffer_depth",
            "request TLVs queued in a peer's send buffer awaiting a "
            "flush", ["peer_addr"], registry=r)
        self.peer_flush_size = Histogram(
            "gubernator_peer_flush_size",
            "request TLVs per peer flush RPC",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096),
            registry=r)
        self.peer_flush_wait = Histogram(
            "gubernator_peer_flush_wait",
            "entry wait from send-buffer enqueue to its flush RPC "
            "launching (s)", buckets=_BUCKETS, registry=r)
        self.peer_inflight_rpcs = Gauge(
            "gubernator_peer_inflight_rpcs",
            "peer flush RPCs currently in flight (depth-K pipelined)",
            ["peer_addr"], registry=r)
        self.peer_retry_counter = Counter(
            "gubernator_peer_retries",
            "peer flush RPCs re-sent after a failure (backoff applies)",
            ["peer_addr"], registry=r)
        self.peer_circuit_open_counter = Counter(
            "gubernator_peer_circuit_opens",
            "times a peer's circuit opened (consecutive flush failures "
            "crossed peer_circuit_threshold)", ["peer_addr"],
            registry=r)
        self.peer_circuit_state = Gauge(
            "gubernator_peer_circuit_state",
            "1 while a peer's circuit is open (sends fail fast)",
            ["peer_addr"], registry=r)
        # Key-level analytics (ISSUE 4): per-phase latency attribution
        # + the bounded heavy-hitter ledger's export surface.  The
        # topkey gauge is label-bounded BY CONSTRUCTION: analytics.py ›
        # KeyAnalytics._publish removes departed keys' labels before
        # setting the current top-K, so cardinality never exceeds
        # GUBER_TOPK — per-key labels over the whole key space are
        # exactly what a million-key deployment must never export.
        self.phase_duration = Histogram(
            "gubernator_phase_duration",
            "request time attributed per serving phase (s): ingest, "
            "pack, queue_wait, device, resolve, build, peer_flush — "
            "pack+device+resolve partition wave_duration",
            ["phase"], buckets=_BUCKETS, registry=r)
        self.topkey_overlimit = Gauge(
            "gubernator_topkey_overlimit_total",
            "OVER_LIMIT decisions observed for each CURRENT top-K key "
            "while tracked (bounded labels: departed keys are removed)",
            ["key"], registry=r)
        self.analytics_waves = Counter(
            "gubernator_analytics_waves_tapped",
            "resolved waves folded into the heavy-hitter sketch",
            registry=r)
        self.analytics_dropped = Counter(
            "gubernator_analytics_tap_dropped",
            "wave taps dropped because the analytics queue was full "
            "(analytics never applies backpressure to serving)",
            registry=r)
        # Failure-domain resilience (ISSUE 5): degraded-mode serving,
        # health-gated ring churn, admission shedding, and fault
        # injection all need first-class visibility — a cluster riding
        # out a dead owner must LOOK like one on /metrics.
        self.forward_failed = Counter(
            "gubernator_forward_failed",
            "forwarded sub-batches that failed, by peer and reason "
            "(circuit_open, closing, rpc_error, short_response, "
            "send_error) — counts requests, whether they degraded to "
            "local answers or became error rows",
            ["peer_addr", "reason"], registry=r)
        self.degraded_served = Counter(
            "gubernator_degraded_served",
            "requests answered locally in degraded mode while their "
            "owner was unreachable or their keys were rehomed "
            "(response carries metadata degraded=true; hits reconcile "
            "to the owner through the GLOBAL hit-flush queues)",
            ["peer_addr"], registry=r)
        self.ring_generation = Gauge(
            "gubernator_ring_generation",
            "monotonic generation of the health-gated routing ring; "
            "bumps when a peer is ejected or readmitted (flap detector: "
            "one outage should cost exactly two bumps)", registry=r)
        self.ring_ejected_peers = Gauge(
            "gubernator_ring_ejected_peers",
            "peers currently ejected from the routing ring by the "
            "health gate (their keys are rehomed until readmit)",
            registry=r)
        self.admission_shed = Counter(
            "gubernator_admission_shed",
            "requests shed at ingress with RESOURCE_EXHAUSTED, by "
            "reason (queue_full, deadline, draining)",
            ["reason"], registry=r)
        self.draining = Gauge(
            "gubernator_draining",
            "1 while the daemon is in its shutdown drain window "
            "(shallow /healthz returns 503 'draining')", registry=r)
        self.fault_injected = Counter(
            "gubernator_fault_injected",
            "times an armed faultpoint fired (faults.py; 0 in healthy "
            "operation — nonzero means a chaos run is active)",
            ["point"], registry=r)
        # Mesh-resident GLOBAL (ISSUE 7): the collective reconcile
        # tier's shape — fold cadence, measured coherence staleness,
        # and the degraded fallback to the gRPC path must all be
        # visible, or a silently stood-down tier looks healthy while
        # every GLOBAL key quietly rides the slow path.
        self.mesh_global_folds = Counter(
            "gubernator_mesh_global_folds",
            "mesh-GLOBAL reconcile collectives completed (one "
            "all-reduce fold per generation)", registry=r)
        self.mesh_global_fold_errors = Counter(
            "gubernator_mesh_global_fold_errors",
            "mesh-GLOBAL reconcile ticks that failed (accumulators "
            "swap back — no hit is lost; consecutive failures past "
            "GUBER_MESH_FALLBACK_AFTER stand the tier down)",
            registry=r)
        self.mesh_global_staleness = Gauge(
            "gubernator_mesh_global_staleness_seconds",
            "measured coherence staleness at the last mesh-GLOBAL "
            "fold: age of the oldest hit the collective folded "
            "(bounded by the reconcile interval when ticks are "
            "healthy)", registry=r)
        self.mesh_global_degraded = Gauge(
            "gubernator_mesh_global_degraded",
            "1 while the mesh-GLOBAL tier is stood down (keys demoted "
            "to the owner-sharded path; reconcile rides the gRPC "
            "queues until the fold recovers)", registry=r)
        self.mesh_global_keys = Gauge(
            "gubernator_mesh_global_keys",
            "keys currently pinned in the mesh-GLOBAL replica table",
            registry=r)
        # Tiered key store (ISSUE 10): the hot/cold split only works if
        # its migration traffic is visible — a thrashing admission
        # policy or a cold tier absorbing most serves is a perf cliff
        # that decision latency alone won't attribute.
        self.tier_cold_keys = Gauge(
            "gubernator_tier_cold_keys",
            "keys resident in the host cold tier (device-table misses "
            "served exactly from host memory)", registry=r)
        self.tier_cold_serves = Counter(
            "gubernator_tier_cold_serves",
            "requests served from the host cold tier (device miss or "
            "table overflow; byte-exact with the device step)",
            registry=r)
        self.tier_promotions = Counter(
            "gubernator_tier_promotions",
            "cold rows migrated into the device table after their "
            "sketch rank cleared GUBER_TIER_PROMOTE", registry=r)
        self.tier_demotions = Counter(
            "gubernator_tier_demotions",
            "device rows evicted to the host cold tier (promotion "
            "victims and table-full writebacks; created_at-preserving, "
            "conservation-exact)", registry=r)
        self.tier_migrations_aborted = Counter(
            "gubernator_tier_migrations_aborted",
            "tier migrations abandoned at the tier_promote/tier_demote "
            "faultpoints (the row stays in its source tier — no state "
            "is lost)", registry=r)
        # Tenant-aware SLO plane (ISSUE 11): per-tenant RED ledger
        # gauges (bounded cardinality — GUBER_TENANT_MAX buckets plus
        # __other__; the analytics worker republishes on its paced
        # publish tick) and the burn-rate verdict gauge.
        self.tenant_requests = Gauge(
            "gubernator_tenant_requests",
            "rows attributed to this tenant (tenant = key-name prefix "
            "up to GUBER_TENANT_DELIM; overflow folds into __other__)",
            ["tenant"], registry=r)
        self.tenant_hits = Gauge(
            "gubernator_tenant_hits",
            "hit weight attributed to this tenant", ["tenant"],
            registry=r)
        self.tenant_over_limit = Gauge(
            "gubernator_tenant_over_limit",
            "OVER_LIMIT rows attributed to this tenant", ["tenant"],
            registry=r)
        self.tenant_errors = Gauge(
            "gubernator_tenant_errors",
            "error rows attributed to this tenant", ["tenant"],
            registry=r)
        self.tenant_degraded = Gauge(
            "gubernator_tenant_degraded",
            "degraded-mode serves attributed to this tenant",
            ["tenant"], registry=r)
        self.tenant_shed = Gauge(
            "gubernator_tenant_shed",
            "admission-shed rows attributed to the tenant that "
            "triggered the shed", ["tenant"], registry=r)
        self.slo_burn = Gauge(
            "gubernator_slo_burn",
            "fast-window burn rate per SLO (error-budget spend "
            "multiple; breach latches when fast AND slow exceed the "
            "threshold — see GET /debug/slo); tenant label empty for "
            "instance-level SLOs", ["slo", "tenant"], registry=r)
        self.fleet_conservation_drift = Gauge(
            "gubernator_fleet_conservation_drift",
            "conservation drift this daemon contributes to the fleet "
            "fold: GLOBAL hits injected minus applied across both "
            "backends (nonzero while flushes fail or are in flight; "
            "held nonzero past the flush-window bound it burns the "
            "fleet_conservation SLO — see GET /debug/audit)",
            registry=r)
        self.memledger_bytes = Gauge(
            "gubernator_memledger_bytes",
            "live bytes per memory-ledger consumer (host-side "
            "consumers report host bytes — see GET /debug/memory)",
            ["consumer"], registry=r)
        self.memledger_rows = Gauge(
            "gubernator_memledger_rows",
            "memory-ledger rows per consumer: state=capacity is the "
            "allocated row budget, state=occupied the live occupancy",
            ["consumer", "state"], registry=r)

    @contextmanager
    def time_func(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.func_duration.labels(name=name).observe(
                time.perf_counter() - t0)

    def render(self) -> bytes:
        """Text exposition for the /metrics endpoint."""
        return generate_latest(self.registry)


def observe_with_exemplar(hist, value: float, exemplar=None) -> None:
    """Histogram observe with a best-effort exemplar attach (ISSUE 12).

    ``exemplar`` is a small label dict (``{"trace_id": ...}`` from
    SpanRecorder.exemplar()) linking the observation's bucket to one
    concrete sampled trace — surfaced by the openmetrics exposition.
    Any client that rejects the exemplar (older prometheus_client, a
    >128-char label set) falls back to a plain observe: the exemplar
    is a debugging link, never worth failing the serving path."""
    if exemplar:
        try:
            hist.observe(value, exemplar)
            return
        except (TypeError, ValueError):
            pass
    hist.observe(value)

"""Prometheus metrics.

Mirrors the reference's metric surface (gubernator.go › Collector impl,
lrucache.go gauges, global.go queue/broadcast metrics — reconstructed)
with the same metric names where sensible, so existing dashboards can be
pointed at this service (SURVEY.md §5.5).  Each instance gets its own
CollectorRegistry (multiple daemons per process in the test cluster).
"""
from __future__ import annotations

import time
from contextlib import contextmanager

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5, 1.0, 2.5)


class Metrics:
    def __init__(self) -> None:
        r = self.registry = CollectorRegistry()
        self.getratelimit_counter = Counter(
            "gubernator_getratelimit", "GetRateLimits calls",
            ["calltype"], registry=r)
        self.over_limit_counter = Counter(
            "gubernator_over_limit", "OVER_LIMIT decisions", registry=r)
        self.check_error_counter = Counter(
            "gubernator_check_error", "errors while checking rate limits",
            ["error"], registry=r)
        self.func_duration = Histogram(
            "gubernator_func_duration", "handler durations (s)",
            ["name"], buckets=_BUCKETS, registry=r)
        self.batch_send_duration = Histogram(
            "gubernator_batch_send_duration",
            "peer batch flush durations (s)", ["peer_addr"],
            buckets=_BUCKETS, registry=r)
        self.queue_length = Gauge(
            "gubernator_global_queue_length",
            "pending GLOBAL hit aggregations", registry=r)
        self.broadcast_duration = Histogram(
            "gubernator_broadcast_duration", "GLOBAL broadcast durations (s)",
            buckets=_BUCKETS, registry=r)
        self.global_broadcast_counter = Counter(
            "gubernator_broadcast", "GLOBAL broadcasts sent", registry=r)
        self.cache_size = Gauge(
            "gubernator_cache_size", "live rows in the counter table",
            registry=r)
        self.cache_access_count = Counter(
            "gubernator_cache_access_count", "table lookups",
            ["type"], registry=r)
        self.concurrent_checks = Gauge(
            "gubernator_concurrent_checks_counter",
            "in-flight GetRateLimits batches", registry=r)
        self.cache_capacity = Gauge(
            "gubernator_cache_capacity",
            "total counter-table rows (grows under auto-grow)", registry=r)
        self.dropped_rows = Gauge(
            "gubernator_cache_dropped_rows",
            "live rows lost to grow/restore re-placement (each is a "
            "counter reset, the LRU-eviction analog)", registry=r)
        # Lane observability (VERDICT r1 weak #5/#8): the wire fast lane
        # and hot-set tier are perf cliffs when they silently disengage —
        # export where requests actually went so operators can see it.
        self.wire_lane_counter = Counter(
            "gubernator_wire_lane_requests",
            "requests by serving lane (wire-columnar vs pb2 fallback)",
            ["lane"], registry=r)
        self.hot_demotion_counter = Counter(
            "gubernator_hotset_demotions",
            "hot-set pinned keys demoted back to the sharded path",
            ["reason"], registry=r)
        # pallas-mode capacity safety (VERDICT r4 item 6): no on-device
        # grow, so full buckets — not total occupancy — are where new
        # keys start erring as table_full.  0 in xla mode.
        self.bucket_saturation = Gauge(
            "gubernator_pallas_bucket_saturation",
            "fraction of 8-slot buckets that are FULL (pallas serving "
            "mode; new keys hashing into a full bucket are unservable)",
            registry=r)

    @contextmanager
    def time_func(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.func_duration.labels(name=name).observe(
                time.perf_counter() - t0)

    def render(self) -> bytes:
        """Text exposition for the /metrics endpoint."""
        return generate_latest(self.registry)

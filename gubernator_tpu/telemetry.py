"""Flight recorder: a bounded in-memory ring of structured events.

The round-5 bench window lost three whole sections to an *empty*
``TimeoutError`` — the dispatcher had no record of what its waves were
doing when the caller gave up (dispatcher.py › RESULT_TIMEOUT_S).  This
module is the black box for that class of failure: every layer that can
wedge (dispatcher waves, handover passes, GLOBAL broadcasts) records
cheap structured events here, and the daemon exposes the ring as JSON at
``GET /debug/events`` (``guber-cli debug events`` round-trips it).

Events are plain dicts, JSON-safe by construction, ordered by a
monotonic ``seq``.  The ring is bounded (old events fall off), so
recording on the hot path is O(1) and allocation-light.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional


def exc_text(e: BaseException) -> str:
    """Non-empty error text for any exception.

    ``str(e)`` is EMPTY for a bare ``TimeoutError`` (and friends) —
    that is exactly how the round-5 undiagnosable rows happened.  Every
    error-row / log / recorder path must go through this instead of
    bare ``str(e)``: message when there is one, ``repr`` otherwise."""
    return str(e) or repr(e)


class FlightRecorder:
    """Bounded ring of structured events (thread-safe).

    Each event is ``{"seq": int, "t_ms": wall-clock ms, "kind": str,
    "trace": trace-id-or-None, **fields}``.  Non-primitive field values
    are coerced with ``repr`` so ``events()`` is always JSON-safe.
    """

    def __init__(self, capacity: int = 512, clock=time.time):
        if capacity < 1:
            raise ValueError("recorder capacity must be >= 1")
        self.capacity = capacity
        self._clock = clock
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0

    def record(self, kind: str, trace: Optional[str] = None,
               **fields) -> dict:
        """Append one event; returns the stored dict.  ``trace``
        defaults to the calling thread's active trace id (tracing.py),
        so handler-path events correlate with W3C traceparent hops —
        callers off the request path (worker/watchdog threads) pass the
        trace they captured at submit time."""
        if trace is None:
            from .tracing import current_trace_id

            trace = current_trace_id()
        ev = {"kind": kind, "t_ms": int(self._clock() * 1000),
              "trace": trace}
        for k, v in fields.items():
            ev[k] = self._coerce(v)
        with self._mu:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
        return ev

    @classmethod
    def _coerce(cls, v):
        """JSON-safe coercion: primitives pass, one-level dicts keep
        their structure (the wave_completed ``phases`` block must stay
        queryable, not a repr string), everything else reprs."""
        if v is None or isinstance(v, (str, int, float, bool)):
            return v
        if isinstance(v, dict):
            return {str(k): (vv if vv is None
                             or isinstance(vv, (str, int, float, bool))
                             else repr(vv))
                    for k, vv in v.items()}
        return repr(v)

    def record_error(self, kind: str, e: BaseException, **fields) -> dict:
        """``record`` with the exception's non-empty text in ``error``."""
        return self.record(kind, error=exc_text(e), **fields)

    def events(self, limit: Optional[int] = None,
               kind: Optional[str] = None,
               since_seq: Optional[int] = None,
               tenant: Optional[str] = None,
               trace: Optional[str] = None) -> List[dict]:
        """Chronological snapshot (oldest first).  ``kind`` keeps only
        events of that kind, ``since_seq`` only events with
        ``seq > since_seq``, ``tenant`` only events carrying that
        ``tenant`` field, and ``trace`` only events stamped with that
        trace id (all server-side, so isolating one tenant's — or one
        request's — incident doesn't download the whole ring);
        ``limit`` then keeps the newest N."""
        with self._mu:
            out = list(self._ring)
        if kind:
            out = [e for e in out if e.get("kind") == kind]
        if tenant:
            out = [e for e in out if e.get("tenant") == tenant]
        if trace:
            out = [e for e in out if e.get("trace") == trace]
        if since_seq is not None:
            out = [e for e in out if e.get("seq", 0) > since_seq]
        if limit is not None and limit >= 0:
            out = out[len(out) - min(limit, len(out)):]
        return out

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)


def write_debug_dump(dirpath: str, instance_id: str,
                     events: List[dict],
                     slo_verdicts: Optional[List[dict]] = None,
                     clock=time.time) -> str:
    """Crash-forensics dump (ISSUE 11): one JSONL file per drain —
    header line with the final SLO verdicts, then the whole event
    ring — so a killed pod leaves a post-mortem artifact in
    ``GUBER_DEBUG_DUMP_DIR``.  Returns the written path.  Callers
    (instance.close) treat any failure as best-effort: a dying
    process must never wedge on its own black box."""
    import json
    import os

    os.makedirs(dirpath, exist_ok=True)
    t_ms = int(clock() * 1000)
    safe = "".join(c if c.isalnum() or c in "-._" else "_"
                   for c in str(instance_id)) or "instance"
    path = os.path.join(dirpath, f"guber_dump_{safe}_{t_ms}.jsonl")
    header = {"kind": "dump_header", "t_ms": t_ms,
              "instance": str(instance_id), "events": len(events)}
    if slo_verdicts is not None:
        header["slo_verdicts"] = slo_verdicts
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(header) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return path


def write_trace_dump(dirpath: str, instance_id: str,
                     spans: List[dict], clock=time.time) -> str:
    """Trace-plane sibling of ``write_debug_dump`` (ISSUE 12): the
    SpanRecorder ring spilled as JSONL on drain — header line, then
    one completed span per line — so sampled traces survive the
    process.  ``tools/trace_assemble.py`` accepts these files
    directly.  Same best-effort contract as the event dump."""
    import json
    import os

    os.makedirs(dirpath, exist_ok=True)
    t_ms = int(clock() * 1000)
    safe = "".join(c if c.isalnum() or c in "-._" else "_"
                   for c in str(instance_id)) or "instance"
    path = os.path.join(dirpath, f"guber_traces_{safe}_{t_ms}.jsonl")
    header = {"kind": "trace_header", "t_ms": t_ms,
              "instance": str(instance_id), "spans": len(spans)}
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(header) + "\n")
        for s in spans:
            f.write(json.dumps(s) + "\n")
    return path

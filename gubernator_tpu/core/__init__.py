"""Single-chip device core: the HBM-resident counter table and the
jit-compiled batch decision step (SURVEY.md §7.1 design stance).

Replaces the reference's cache.go/lrucache.go (hashmap of CacheItems) and
algorithms.go (per-key state transitions) with one struct-of-arrays
resident in HBM and one gather→update→scatter program per request batch.
"""
from .table import TableState, init_table, occupancy, sweep_expired  # noqa: F401
from .batch import RequestBatch, pack_requests, empty_batch  # noqa: F401
from .step import decide_batch, StepOutput  # noqa: F401

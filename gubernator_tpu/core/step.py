"""The batch decision step: one jit program per GetRateLimits batch.

TPU-native replacement for the reference hot path (gubernator.go ›
getLocalRateLimit → algorithms.go › tokenBucket/leakyBucket over an LRU
map — reconstructed): hash-probe the key column → row indices (inserting
misses), gather row state, apply both algorithms branchlessly, scatter
back, return per-request (status, remaining, reset_time).

Duplicate keys inside a batch must behave exactly as the reference's
sequential per-request processing (SURVEY.md §7.3 "parity under
batching").  Requests are sorted by row (stable, preserving request
order); each segment (same key) is applied serially-equivalently:

- position 0 of every segment runs the full per-request transition
  vectorized across segments;
- "simple" tails (uniform request fields, no RESET/DRAIN flags) have a
  closed form: with per-request cost c and remaining r after position 0,
  position j ≥ 1 is admitted iff j ≤ r // c;
- LEAKY tails with uniform config but MIXED arrival times take a
  speculative segmented associative scan (maps x → min(m, x+b) compose
  closedly); segments where the speculation fails (any deny) fall back
  to the loop below;
- everything else (mixed hits/configs/flags on one key, or mixed-time
  leaky segments that actually deny) runs a while_loop over in-segment
  positions, vectorized across segments — bounded by the longest such
  segment, zero iterations when absent.

All arithmetic is int64 (x64 enabled); semantics match oracle.py
bit-for-bit — the parity tests enforce this on random + Zipf streams.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..types import FRAC_SAFE, TD_BOUND, Algorithm, Behavior
from .batch import RequestBatch
from .table import TableState

#: probe window per lookup (GUBER_PROBES overrides).  At the
#: north-star load (10M keys / CAP 2^24 = 0.60) a window of 8 leaves
#: ~4e-4 of requests unservable (their keys lost every claim round
#: during populate — r3 artifact `win_cap24.err_fraction`); the
#: default is sized so the flagship shape serves 100% of its working
#: set (verified empirically on the exact populate key set).
PROBES = int(__import__("os").environ.get("GUBER_PROBES", "8"))
INSERT_ROUNDS = 4  # slot-claim rounds per batch

#: K-split scatter fallback (GUBER_KSPLIT=<log2 window>, default off):
#: the 2026-08-01 backend compiler serialized the donated step's table
#: scatters at CAP >= 2^22 (217-258 ms/step) while CAP 2^21 lowered
#: well (0.118 ms).  With GUBER_KSPLIT=21, every table-row scatter is
#: performed as CAP/2^21 slice-local scatters whose operands are the
#: 2^21-row size that lowers well — subtracting each window's base
#: preserves BOTH scatter promises (an ascending+unique index vector
#: stays ascending+unique; rows outside the window fall out of bounds
#: and drop), so no masking is needed.  Opt-in: on backends WITHOUT
#: the pathology it is pure overhead (measured 2x on XLA:CPU at CAP
#: 2^22 — the per-window concatenate streams the table), so it is an
#: escalation tier between "promises fixed it" and "serve large CAP
#: from the Pallas kernel", A/B-able on-chip in one compile
#: (tools/cap_ab.py records the active value; tpu_session stage 2b
#: fires it automatically when the plain probe stays pathological).
KSPLIT_LOG2 = int(__import__("os").environ.get("GUBER_KSPLIT", "0"))


def _scatter_rows(col, idx, vals, *, sorted_idx: bool):
    """Table-row scatter with the backend promises, K-split when
    enabled (see KSPLIT_LOG2).  ``idx`` entries out of [0, len(col))
    are drop sentinels; ``sorted_idx`` mirrors each call site's
    indices_are_sorted claim (the insert claim vector is unique but
    unsorted)."""
    cap = col.shape[0]
    if not KSPLIT_LOG2 or cap <= (1 << KSPLIT_LOG2):
        return col.at[idx].set(vals, mode="drop", unique_indices=True,
                               indices_are_sorted=sorted_idx)
    S = 1 << KSPLIT_LOG2
    # Out-of-window rows get DISTINCT >= S sentinels (dropped): a plain
    # idx - base would send below-window rows NEGATIVE, and negative
    # scatter indices WRAP (numpy semantics), corrupting the window's
    # tail.  The remap keeps uniqueness but not global order, so the
    # per-window scatters promise unique only — uniqueness is what
    # unlocks the parallel lowering; sortedness is a secondary hint the
    # split trades away.
    arange_b = jnp.arange(idx.shape[0], dtype=idx.dtype)
    parts = []
    for k in range(cap // S):
        base = k * S
        loc = jnp.where((idx >= base) & (idx < base + S),
                        idx - base, S + arange_b)
        sl = lax.slice_in_dim(col, base, base + S)
        parts.append(sl.at[loc].set(vals, mode="drop",
                                    unique_indices=True))
    return lax.concatenate(parts, 0)

_RESET = int(Behavior.RESET_REMAINING)
_DRAIN = int(Behavior.DRAIN_OVER_LIMIT)
_GREG = int(Behavior.DURATION_IS_GREGORIAN)

_I64_MAX = jnp.iinfo(jnp.int64).max

#: test hook (tests/test_scatter_invariants.py): when True at TRACE
#: time, every step asserts EVERY index vector a scatter makes promises
#: about really satisfies them — wrow must be strictly ascending +
#: unique (unique_indices + indices_are_sorted), _insert's tkey claim
#: vector and body_fn's idxj must be all-distinct (unique_indices).
#: The promises are UB if lied about, and a CPU parity run would not
#: catch the lie.
_CHECK_SCATTER_INVARIANTS = False
_SCATTER_INVARIANT_VIOLATIONS: list = []


#: per-site fire counters: a hook that never ran would make the
#: invariant test pass vacuously
_SCATTER_INVARIANT_CHECKS = {"wrow": 0, "insert_tkey": 0,
                             "body_idxj": 0}


def _record_wrow(wrow_np):
    import numpy as np

    _SCATTER_INVARIANT_CHECKS["wrow"] += 1
    w = np.asarray(wrow_np)
    if not (np.diff(w.astype(np.int64)) > 0).all():
        _SCATTER_INVARIANT_VIOLATIONS.append(("wrow", w.copy()))


def _record_unique(label, idx_np):
    """unique_indices-only promise sites (no sortedness claimed)."""
    import numpy as np

    _SCATTER_INVARIANT_CHECKS[label] += 1
    w = np.asarray(idx_np)
    if np.unique(w).size != w.size:
        _SCATTER_INVARIANT_VIOLATIONS.append((label, w.copy()))


class StepOutput(NamedTuple):
    """Per-request results in original request order."""

    status: jax.Array  # int32[B], Status values
    remaining: jax.Array  # int64[B]
    reset_time: jax.Array  # int64[B]
    limit: jax.Array  # int64[B]
    err: jax.Array  # bool[B], True = table full / dropped
    over_count: jax.Array  # int64, OVER_LIMIT decisions this batch
    insert_count: jax.Array  # int64, new keys inserted


class _Item(NamedTuple):
    """Per-segment item state carried through in-segment positions."""

    alg: jax.Array  # int32
    status: jax.Array  # int32
    limit: jax.Array
    duration: jax.Array
    eff: jax.Array
    burst: jax.Array
    rem: jax.Array
    t: jax.Array
    exp: jax.Array


class _Req(NamedTuple):
    """One request's fields, vectorized across segments."""

    hits: jax.Array
    limit: jax.Array
    duration: jax.Array
    eff: jax.Array
    greg_end: jax.Array
    behavior: jax.Array
    alg: jax.Array
    burst: jax.Array
    now: jax.Array  # per-request arrival time (epoch ms)


def _probe_slots(key: jax.Array, cap: int) -> jax.Array:
    """[B, PROBES] int32 probe sequence (double hashing, odd stride)."""
    stride = (key >> jnp.uint64(17)) | jnp.uint64(1)
    p = jnp.arange(PROBES, dtype=jnp.uint64)
    slots = (key[:, None] + p[None, :] * stride[:, None]) & jnp.uint64(cap - 1)
    return slots.astype(jnp.int32)


def _lookup(tkey: jax.Array, slots: jax.Array, key: jax.Array):
    """(row int32[B] or -1, keys_at [B,P]) — first probe slot holding key."""
    keys_at = tkey[slots]
    match = keys_at == key[:, None]
    found = match.any(axis=1)
    fp = jnp.argmax(match, axis=1)
    row = jnp.take_along_axis(slots, fp[:, None], axis=1)[:, 0]
    return jnp.where(found, row, -1), keys_at


def _insert(tkey: jax.Array, slots: jax.Array, key: jax.Array,
            valid: jax.Array, row: jax.Array):
    """Claim first-empty probe slots for missing keys, deterministically.

    Per round: resolve matches (covers same-key losers of earlier
    rounds), pick each active miss's first empty slot, dedupe claims by
    slot (stable sort → lowest request index wins), scatter winners.
    The analog of lrucache.go › Add, without locks: one batch is one
    program, so claim conflicts are resolved by sort order, not mutexes.
    """
    cap = tkey.shape[0]
    B = key.shape[0]
    n_claimed = jnp.asarray(0, jnp.int64)

    for _ in range(INSERT_ROUNDS):
        keys_at = tkey[slots]
        match = keys_at == key[:, None]
        found = match.any(axis=1)
        fp = jnp.argmax(match, axis=1)
        frow = jnp.take_along_axis(slots, fp[:, None], axis=1)[:, 0]
        row = jnp.where((row < 0) & valid & found, frow, row)

        active = valid & (row < 0)
        empty = keys_at == 0
        has_empty = empty.any(axis=1)
        ep = jnp.argmax(empty, axis=1)
        cand = jnp.take_along_axis(slots, ep[:, None], axis=1)[:, 0]
        cand_eff = jnp.where(active & has_empty, cand, cap)
        order = jnp.argsort(cand_eff, stable=True)
        c_s = cand_eff[order]
        first = jnp.concatenate([jnp.ones(1, bool), c_s[1:] != c_s[:-1]])
        first = first & (c_s < cap)
        # order is a permutation and winning cands are slot-deduped, so
        # both scatters can promise uniqueness (losers get DISTINCT
        # out-of-bounds sentinels, dropped by mode="drop") — without the
        # promise the TPU backend must assume colliding writes and can
        # emit a serialized scatter loop (observed 2026-08-01: 217 ms
        # per step at CAP >= 2^22 vs 0.118 ms at 2^21)
        winner = jnp.zeros(B, bool).at[order].set(first,
                                                  unique_indices=True)
        claim = jnp.where(winner, cand,
                          cap + jnp.arange(B, dtype=cand.dtype))
        if _CHECK_SCATTER_INVARIANTS:  # traced-ok: test-only scatter-invariant hook, off in production
            jax.debug.callback(_record_unique, "insert_tkey", claim)
        tkey = _scatter_rows(tkey, claim, key, sorted_idx=False)
        row = jnp.where(winner, cand, row)
        n_claimed = n_claimed + winner.sum(dtype=jnp.int64)

    # final resolve for same-key losers of the last round
    keys_at = tkey[slots]
    match = keys_at == key[:, None]
    found = match.any(axis=1)
    fp = jnp.argmax(match, axis=1)
    frow = jnp.take_along_axis(slots, fp[:, None], axis=1)[:, 0]
    row = jnp.where((row < 0) & valid & found, frow, row)
    return tkey, row, n_claimed


def _apply_position(item: _Item, req: _Req):
    """One request applied to its item — the full §2.4 transition,
    vectorized across segments, at the request's OWN arrival time
    (req.now).  Mirrors oracle.apply_token/apply_leaky exactly (same
    operation order, same integer arithmetic).

    Time is clamped per key to never run backward (max with the item's
    clock): a no-op on monotonic streams (where oracle parity is
    asserted), and a sane defined behavior when merged callers' clocks
    invert — without it a leaky replenish would see negative elapsed."""
    i64 = jnp.int64
    now = jnp.maximum(req.now, item.t)
    is_leaky = req.alg == int(Algorithm.LEAKY_BUCKET)
    is_greg = (req.behavior & _GREG) != 0
    reset = (req.behavior & _RESET) != 0
    drain = (req.behavior & _DRAIN) != 0

    # --- fresh determination (missing/expired/algorithm switch)
    fresh = (now >= item.exp) | (item.alg != req.alg)
    # token duration change → recompute expiry from created_at; expiring
    # now means start fresh
    tok_dur_change = (~is_leaky) & (~fresh) & (req.duration != item.duration)
    new_exp_tok = jnp.where(is_greg, req.greg_end, item.t + req.eff)
    exp1 = jnp.where(tok_dur_change, new_exp_tok, item.exp)
    fresh = fresh | (tok_dur_change & (exp1 <= now))

    # --- adopt fresh or existing state
    # Leaky td products multiply by eff only on leaky rows (operand
    # masked to 1/0 otherwise): token hits/limit go up to VALUE_MAX
    # (2^53), so an unmasked product would wrap int64 even though its
    # value is discarded by the jnp.where select.
    eff_l = jnp.where(is_leaky, req.eff, 1)
    tok_exp_fresh = jnp.where(is_greg, req.greg_end, now + req.eff)
    rem_fresh = jnp.where(is_leaky, req.burst, req.limit) * eff_l
    limit0 = jnp.where(fresh, req.limit, item.limit)
    eff0 = jnp.where(fresh, req.eff, item.eff)
    rem0 = jnp.where(fresh, rem_fresh, item.rem)
    t0 = jnp.where(fresh, now, item.t)
    exp0 = jnp.where(fresh, jnp.where(is_leaky, now + req.eff, tok_exp_fresh), exp1)
    status0 = jnp.where(fresh, 0, item.status)

    # --- leaky denominator change → rescale td fixed point.  Whole
    # tokens clamp to TD_BOUND // new_eff (they could not survive the
    # burst cap anyway); the sub-token fraction is kept only while
    # frac × eff fits int64 (both denominators ≤ FRAC_SAFE), else the
    # rescale floors to whole tokens — identical in oracle.apply_leaky.
    leaky_eff_change = is_leaky & (~fresh) & (req.eff != eff0)
    whole = rem0 // jnp.maximum(eff0, 1)
    frac = rem0 % jnp.maximum(eff0, 1)
    whole = jnp.minimum(whole, TD_BOUND // jnp.maximum(req.eff, 1))
    frac_ok = (eff0 <= FRAC_SAFE) & (req.eff <= FRAC_SAFE)
    frac_term = (jnp.where(frac_ok, frac, 0) * req.eff) // jnp.maximum(eff0, 1)
    rem_rescaled = whole * req.eff + frac_term
    rem0 = jnp.where(leaky_eff_change, rem_rescaled, rem0)
    eff0 = jnp.where(is_leaky, req.eff, jnp.where(tok_dur_change, req.eff, eff0))

    # --- RESET_REMAINING (existing items only; fresh items already start
    # full — for leaky that means burst, not limit, as in the oracle)
    reset_live = reset & (~fresh)
    rem0 = jnp.where(reset_live, req.limit * eff_l, rem0)
    status0 = jnp.where(reset_live, 0, status0)
    limit_after_reset = jnp.where(reset_live & (~is_leaky), req.limit, limit0)

    # --- token limit change in place
    tok_lim_change = (~is_leaky) & (req.limit != limit_after_reset)
    rem_adj = jnp.clip(rem0 + req.limit - limit_after_reset, 0, req.limit)
    rem0 = jnp.where(tok_lim_change, rem_adj, rem0)
    limit1 = req.limit

    # --- leaky replenish (exact: elapsed × limit td, clamped to burst).
    # elapsed > TD_BOUND // limit means the true product already exceeds
    # the burst cap (cap_td ≤ TD_BOUND), so the bucket is simply full —
    # the guard is exact, not an approximation (oracle.apply_leaky
    # mirrors it).
    burst1 = jnp.where(is_leaky, req.burst, limit1)
    elapsed = now - t0
    cap_td = burst1 * jnp.where(is_leaky, eff0, 0)
    safe_el = TD_BOUND // jnp.maximum(limit1, 1)
    rem_rep = jnp.where(
        elapsed > safe_el, cap_td,
        jnp.minimum(rem0 + jnp.minimum(elapsed, safe_el) * limit1, cap_td))
    rem0 = jnp.where(is_leaky, rem_rep, rem0)
    t1 = jnp.where(is_leaky, now, t0)

    rate = jnp.where(limit1 > 0, eff0 // jnp.maximum(limit1, 1), eff0)
    exp_out = jnp.where(is_leaky, now + eff0, exp0)
    reset_time = jnp.where(is_leaky, now + rate, exp_out)

    # --- hits
    cost = req.hits * jnp.where(is_leaky, eff0, 1)
    is_query = req.hits == 0
    ok = cost <= rem0
    rem2 = jnp.where((~is_query) & ok, rem0 - cost, rem0)
    rem2 = jnp.where((~is_query) & (~ok) & drain, i64(0), rem2)
    status1 = jnp.where(is_query, status0,
                        jnp.where(ok, 0, 1)).astype(jnp.int32)

    out_rem = jnp.where(is_leaky, rem2 // jnp.maximum(eff0, 1), rem2)
    dur1 = req.duration
    new_item = _Item(alg=req.alg, status=status1, limit=limit1, duration=dur1,
                     eff=eff0, burst=burst1, rem=rem2, t=t1, exp=exp_out)
    out = (status1, out_rem, reset_time, limit1)
    return new_item, out


def _tree_where(mask, a, b):
    return jax.tree.map(lambda x, y: jnp.where(mask, x, y), a, b)


def decide_batch_impl(state: TableState, batch: RequestBatch, now_ms: jax.Array
                      ) -> tuple[TableState, StepOutput]:
    """Apply one request batch to the table; returns (new state, outputs).

    Semantically equivalent to the reference's per-request loop in
    gubernator.go › GetRateLimits over a local cache, for any batch
    composition including duplicate keys.

    Unjitted building block: compose under jit/scan/shard_map.  Use
    ``decide_batch`` for direct host dispatch.
    """
    cap = state.key.shape[0]
    B = batch.key.shape[0]
    i32 = jnp.int32
    i64 = jnp.int64
    now = jnp.asarray(now_ms, i64)

    key = batch.key
    valid = batch.valid & (key != 0)
    # per-request arrival time; 0 entries (padding / legacy callers
    # without the column) fall back to the scalar argument
    if batch.now is None:
        now_col = jnp.full((B,), now, i64)
    else:
        now_col = jnp.where(jnp.asarray(batch.now, i64) > 0,
                            jnp.asarray(batch.now, i64), now)

    # ---- probe / insert -------------------------------------------------
    slots = _probe_slots(key, cap)
    tkey = state.key
    row, _ = _lookup(tkey, slots, key)
    row = jnp.where(valid & (row >= 0), row, -1)
    miss = valid & (row < 0)

    tkey, row, insert_count = lax.cond(
        miss.any(),
        lambda ops: _insert(*ops),
        # zero derived from a varying operand so both branches have the
        # same varying-manual-axes type under shard_map
        lambda ops: (ops[0], ops[4], (ops[4].sum() * 0).astype(i64)),
        (tkey, slots, key, valid, row),
    )
    err = valid & (row < 0)  # probe window exhausted: table overfull
    row = jnp.where(valid & (row >= 0), row, cap)  # cap = dropped sentinel

    # ---- sort into segments ordered by (row, now, original index) ----
    # Two stable sorts = lexicographic: within a key's segment, requests
    # apply in arrival-time order (then original order) — sequential
    # parity even when the dispatcher merges batches from callers whose
    # clocks differ (the oracle, like the reference's sequential loop,
    # assumes per-key time-monotonic application; a time-inverted leaky
    # replenish would see negative elapsed).  Uniform-now batches — the
    # common case: any unmerged call — take the single-sort branch;
    # lax.cond executes only the taken side, so the extra sort costs
    # nothing unless instants actually mixed.
    def _sort_single(_):
        return jnp.argsort(row, stable=True)

    def _sort_by_time(_):
        p0 = jnp.argsort(now_col, stable=True)
        return p0[jnp.argsort(row[p0], stable=True)]

    perm = lax.cond(jnp.all(now_col == now_col[0]),
                    _sort_single, _sort_by_time, None)
    r_s = row[perm]
    head = jnp.concatenate([jnp.ones(1, bool), r_s[1:] != r_s[:-1]])
    seg_id = (jnp.cumsum(head) - 1).astype(i32)
    seg = partial(jax.ops.segment_min, segment_ids=seg_id, num_segments=B)
    seg_max = partial(jax.ops.segment_max, segment_ids=seg_id, num_segments=B)
    seg_start = seg(jnp.arange(B, dtype=i32))
    seg_len = jax.ops.segment_sum(jnp.ones(B, i32), seg_id, num_segments=B)
    seg_row = seg(r_s)
    exists = (seg_len > 0) & (seg_row < cap)

    sf = _Req(
        hits=batch.hits[perm], limit=batch.limit[perm],
        duration=batch.duration[perm], eff=batch.eff_ms[perm],
        greg_end=batch.greg_end[perm], behavior=batch.behavior[perm],
        alg=batch.algorithm[perm], burst=batch.burst[perm],
        now=now_col[perm],
    )

    def uni(x):
        return seg_max(x) == seg(x)

    uniform_cfg = (uni(sf.hits) & uni(sf.limit) & uni(sf.duration)
                   & uni(sf.eff) & uni(sf.behavior) & uni(sf.alg)
                   & uni(sf.burst))
    uni_now = uni(sf.now)
    any_flag = seg_max((sf.behavior & (_RESET | _DRAIN))) > 0
    # (simple/complex masks are finalized after the head apply: token
    # segments with mixed arrival times can still take the closed form
    # when no tail request crosses the head's window — see below)

    # ---- gather item state per segment ---------------------------------
    def grow(col, fill=0):
        return col.at[seg_row].get(mode="fill", fill_value=fill)

    item0 = _Item(
        alg=(grow(state.meta) & 1).astype(i32),
        status=((grow(state.meta) >> 1) & 1).astype(i32),
        limit=grow(state.limit), duration=grow(state.duration),
        eff=grow(state.eff_ms, 1), burst=grow(state.burst),
        rem=grow(state.remaining), t=grow(state.t_ms),
        exp=grow(state.expire_at),
    )

    idx0 = jnp.where(exists, seg_start, B).astype(i32)

    def greq(x):
        return x.at[idx0].get(mode="fill", fill_value=0)

    req0 = _Req(*[greq(f) for f in sf])

    item1, out0 = _apply_position(item0, req0)
    item1 = _tree_where(exists, item1, item0)

    # ---- simple tails: closed form, fully vectorized -------------------
    is_leaky0 = req0.alg == int(Algorithm.LEAKY_BUCKET)
    # Mixed arrival times usually force the per-position path (leaky
    # replenishes per request), but a TOKEN transition is time-invariant
    # except for the expiry check: with uniform config/flags and every
    # tail arrival inside the head's window (max now < item1.exp after
    # the head applied), the decrement-only closed form is exact.  This
    # keeps dispatcher-coalesced concurrent callers — distinct clocks,
    # shared hot keys — on the vectorized path instead of a while_loop
    # as long as the longest such segment (the serving common case).
    time_safe = uni_now | ((~is_leaky0) & (seg_max(sf.now) < item1.exp))
    uniform = uniform_cfg & time_safe
    simple = exists & uniform & (~any_flag)
    complex_seg = exists & (seg_len > 1) & (~simple)
    cost0 = req0.hits * jnp.where(is_leaky0, item1.eff, 1)
    k_raw = jnp.where(cost0 > 0, item1.rem // jnp.maximum(cost0, 1), _I64_MAX)
    tail_n = jnp.maximum(seg_len - 1, 0).astype(i64)
    k = jnp.minimum(k_raw, tail_n)  # accepted tail requests
    # final per-segment state after the whole tail
    s_rem_final = item1.rem - k * jnp.maximum(cost0, 0)
    s_status_final = jnp.where(
        cost0 > 0, jnp.where(tail_n <= k_raw, 0, 1), item1.status
    ).astype(i32)
    simple_tail_seg = simple & (seg_len > 1)
    item_final = _Item(
        alg=item1.alg,
        status=jnp.where(simple_tail_seg, s_status_final, item1.status),
        limit=item1.limit, duration=item1.duration, eff=item1.eff,
        burst=item1.burst,
        rem=jnp.where(simple_tail_seg, s_rem_final, item1.rem),
        t=item1.t, exp=item1.exp,
    )

    # per-position outputs for simple tails
    pos = jnp.arange(B, dtype=i32) - seg_start.at[seg_id].get(mode="fill", fill_value=0)
    sid = seg_id
    jj = pos.astype(i64)
    tail_ok = jj <= k_raw[sid]
    t_status = jnp.where(cost0[sid] > 0,
                         jnp.where(tail_ok, 0, 1), item1.status[sid]).astype(i32)
    t_rem = item1.rem[sid] - jnp.minimum(jj, k[sid]) * jnp.maximum(cost0[sid], 0)
    t_rem_out = jnp.where(is_leaky0[sid],
                          t_rem // jnp.maximum(item1.eff[sid], 1), t_rem)
    tail_mask = simple[sid] & (pos > 0)

    # assemble sorted-order outputs: heads then simple tails.  out0 is
    # per-SEGMENT-ID; a segment's head value lands on its head lane.
    # The historical `zeros.at[idx0].set(out0)` scatter (idx0 =
    # seg_start per segment id) is equivalent to a head-masked gather
    # by seg_id — a select + contiguous gather lowers cheaply on every
    # backend, where scatter is the op the TPU backend can serialize.
    head_w = head & exists[sid]
    o_status = jnp.where(head_w, out0[0][sid], 0).astype(i32)
    o_rem = jnp.where(head_w, out0[1][sid], 0)
    o_reset = jnp.where(head_w, out0[2][sid], 0)
    o_limit = jnp.where(head_w, out0[3][sid], 0)
    o_status = jnp.where(tail_mask, t_status, o_status)
    o_rem = jnp.where(tail_mask, t_rem_out, o_rem)
    o_reset = jnp.where(tail_mask, out0[2][sid], o_reset)
    o_limit = jnp.where(tail_mask, out0[3][sid], o_limit)

    # ---- leaky mixed-time tails: speculative associative scan ----------
    # The last per-position exposure (ROUND_NOTES r2 open #4).  A
    # uniform-config, no-flag LEAKY segment whose arrivals mix instants
    # has the exact per-position transition (on the clamped clock
    # e_j = max(now_j, e_{j-1}), d_j = e_j - e_{j-1}):
    #     u_j = min(cap_td, r_{j-1} + d_j*limit)        (replenish)
    #     r_j = u_j - c  if c <= u_j (allow)  else  u_j (deny)
    # Crossing the expiry inside such a segment is EXACTLY replenish
    # saturation for leaky (fresh rem = burst*eff = cap_td, same t/exp
    # writes), so expiry needs no special case.  SPECULATE that every
    # tail position is allowed: each position becomes x -> min(m, x+b)
    # with m = cap_td - c, b = d_j*limit - c, and such maps compose
    # closedly: (m1,b1) then (m2,b2) = (min(m2, m1+b2), b1+b2) — a
    # segmented associative scan yields every prefix in O(log B)
    # instead of a while_loop iteration per position.  Validation: the
    # speculation holds iff min_j r_j >= 0 (nothing was denied);
    # segments where it fails keep the while_loop.  Queries (hits == 0)
    # consume nothing, never fail, and propagate the item status —
    # flipping to 0 once any position crossed the expiry (the fresh
    # reset).  The deny branch itself is non-monotone (a denied caller
    # keeps more tokens than an allowed one), which is why the general
    # mixed allow/deny case has no bounded-state scan.
    lseg = (exists & uniform_cfg & (~any_flag) & is_leaky0
            & (~uni_now) & (seg_len > 1))

    def _leaky_mixed_scan(carry):
        (os_, or_, ot_, ol_), item_f, cplx = carry
        i64max = _I64_MAX
        INF = jnp.asarray(1 << 62, i64)
        LOWC = jnp.asarray(-(1 << 62), i64)
        now_s = sf.now
        T = item1.t[sid]  # head's post-apply clock, per position
        e = jnp.maximum(now_s, T)
        now_prev = jnp.concatenate([now_s[:1], now_s[:-1]])
        e_prev = jnp.where(pos > 0, jnp.maximum(now_prev, T), T)
        d = jnp.maximum(e - e_prev, 0)
        L = sf.limit
        effp = jnp.maximum(sf.eff, 1)
        c = sf.hits * jnp.where(lseg[sid], effp, 1)  # mask: token
        # hits*eff of a non-participating segment may wrap int64
        cap_td = sf.burst * jnp.where(lseg[sid], effp, 1)
        safe_el = TD_BOUND // jnp.maximum(L, 1)
        tail_sel = lseg[sid] & (pos > 0)
        m_el = jnp.where(tail_sel, cap_td - c, INF)
        # d >= eff crosses the expiry: the bucket goes FRESH (rem =
        # burst*eff = cap_td) — NOT mere replenishment, which would
        # under-fill whenever burst > limit and d*limit < cap_td.
        # d > safe_el is the int64 overflow guard (same arm: the true
        # product exceeds every cap).
        b_raw = jnp.where((d >= effp) | (d > safe_el), cap_td - c,
                          jnp.minimum(d, safe_el) * L - c)
        # low clamp preserves "speculation fails" (r0 <= cap_td < 2^61
        # so r0 + LOWC < 0 always) while keeping every later sum in
        # int64 range; identity positions contribute (INF, 0)
        b_el = jnp.where(tail_sel, jnp.maximum(b_raw, LOWC), 0)
        flag = pos == 1  # segment start, for the segmented combine

        def comb(lft, rgt):
            ml, bl, fl = lft
            mr, br, fr = rgt
            m = jnp.minimum(mr, ml + br)
            b = jnp.minimum(jnp.maximum(bl + br, LOWC), m)
            return (jnp.where(fr, mr, m), jnp.where(fr, br, b), fl | fr)

        M, Bc, _ = lax.associative_scan(comb, (m_el, b_el, flag))
        r0 = item1.rem[sid]
        r = jnp.minimum(M, r0 + Bc)
        min_r = jax.ops.segment_min(
            jnp.where(tail_sel, r, i64max), seg_id, num_segments=B)
        ok_seg = lseg & (min_r >= 0)

        # per-position outputs (only adopted where ok_seg & tail)
        is_query = c == 0
        fi = (tail_sel & (d >= effp)).astype(i32)
        cs = jnp.cumsum(fi)
        cs_head = cs.at[seg_start[sid]].get(mode="fill", fill_value=0)
        crossed = (cs - cs_head) > 0  # any expiry crossing at <= this pos
        st_pos = jnp.where(is_query,
                           jnp.where(crossed, 0, item1.status[sid]),
                           0).astype(i32)
        rate = jnp.where(L > 0, effp // jnp.maximum(L, 1), effp)
        ap = ok_seg[sid] & tail_sel
        os_ = jnp.where(ap, st_pos, os_)
        or_ = jnp.where(ap, r // effp, or_)
        ot_ = jnp.where(ap, e + rate, ot_)
        ol_ = jnp.where(ap, L, ol_)

        # per-segment final item from the last tail position
        idxL = jnp.where(ok_seg, seg_start + seg_len - 1, B).astype(i32)

        def glast(x, fill=0):
            return x.at[idxL].get(mode="fill", fill_value=fill)

        item_scan = item1._replace(
            status=glast(st_pos), rem=glast(r), t=glast(e),
            exp=glast(e) + item1.eff)
        item_f = _tree_where(ok_seg, item_scan, item_f)
        return (os_, or_, ot_, ol_), item_f, cplx & (~ok_seg)

    (o_status, o_rem, o_reset, o_limit), item_final, complex_seg = lax.cond(
        lseg.any(), _leaky_mixed_scan, lambda carry: carry,
        ((o_status, o_rem, o_reset, o_limit), item_final, complex_seg))

    # ---- complex tails: while_loop over in-segment positions -----------
    max_complex = jnp.max(jnp.where(complex_seg, seg_len, 0))

    def cond_fn(c):
        return c[0] < max_complex

    def body_fn(c):
        j, item, (os_, or_, ot_, ol_) = c
        m = complex_seg & (j < seg_len)
        # active indices seg_start+j are distinct across segments and
        # inactive lanes get DISTINCT OOB sentinels (dropped), so the
        # unique promise holds — same backend-vectorization rationale
        # as the table writeback below
        idxj = jnp.where(m, seg_start + j,
                         B + jnp.arange(B, dtype=i32)).astype(i32)
        if _CHECK_SCATTER_INVARIANTS:  # traced-ok: test-only scatter-invariant hook, off in production
            jax.debug.callback(_record_unique, "body_idxj", idxj)
        reqj = _Req(*[x.at[idxj].get(mode="fill", fill_value=0) for x in sf])
        item2, outj = _apply_position(item, reqj)
        item = _tree_where(m, item2, item)
        os_ = os_.at[idxj].set(outj[0], mode="drop", unique_indices=True)
        or_ = or_.at[idxj].set(outj[1], mode="drop", unique_indices=True)
        ot_ = ot_.at[idxj].set(outj[2], mode="drop", unique_indices=True)
        ol_ = ol_.at[idxj].set(outj[3], mode="drop", unique_indices=True)
        return j + 1, item, (os_, or_, ot_, ol_)

    _, item_final, (o_status, o_rem, o_reset, o_limit) = lax.while_loop(
        cond_fn, body_fn,
        (jnp.asarray(1, i32), item_final, (o_status, o_rem, o_reset, o_limit)),
    )

    # ---- write back per-segment final state ----------------------------
    # wrow is per SEGMENT ID — one writer per segment already (sorted
    # by row, so live segments have distinct rows).  The non-existent
    # segments get DISTINCT out-of-bounds sentinels (dropped by
    # mode="drop") so the unique_indices promise below is honest: it
    # lets the TPU backend vectorize the scatters instead of assuming
    # colliding writes (the CAP>=2^22 217 ms/step serialization,
    # 2026-08-01).  The vector is also globally ASCENDING — both sort
    # paths end with a stable argsort by row, so seg_row rises across
    # live segment ids (err/invalid rows are remapped to cap and sort
    # LAST into a non-exists segment), and the cap+i sentinels occupy
    # ids >= n_segments with values > any live row — hence
    # indices_are_sorted too (verified on real wrow vectors by
    # tests/test_scatter_invariants.py)
    wrow = jnp.where(exists, seg_row, cap + jnp.arange(B, dtype=i32))
    if _CHECK_SCATTER_INVARIANTS:  # traced-ok: test-only scatter-invariant hook, no cost when off
        jax.debug.callback(_record_wrow, wrow)
    meta_new = (item_final.alg & 1) | ((item_final.status & 1) << 1)

    # Hot/cold column split (PERF.md §4.1, VERDICT r1 item 2): the four
    # hot columns (meta, remaining, t_ms, expire_at) change on ~every
    # step; the cold config columns (limit, duration, eff_ms, burst —
    # and key, via the insert cond above) change only on insert or
    # config change.  Gate the cold scatters behind a cond so clean
    # steps return those buffers untouched: under donation
    # (decide_batch_donated) the pass-through aliases in place and
    # steady-state HBM traffic drops from 9 streamed columns to 4.
    cold_dirty = miss.any() | (exists & (
        (item_final.limit != item0.limit)
        | (item_final.duration != item0.duration)
        | (item_final.eff != item0.eff)
        | (item_final.burst != item0.burst))).any()

    def _cold_scatter(cols):
        limit_c, duration_c, eff_c, burst_c = cols
        return (_scatter_rows(limit_c, wrow, item_final.limit,
                              sorted_idx=True),
                _scatter_rows(duration_c, wrow, item_final.duration,
                              sorted_idx=True),
                _scatter_rows(eff_c, wrow, item_final.eff,
                              sorted_idx=True),
                _scatter_rows(burst_c, wrow, item_final.burst,
                              sorted_idx=True))

    limit_n, duration_n, eff_n, burst_n = lax.cond(
        cold_dirty, _cold_scatter, lambda cols: cols,
        (state.limit, state.duration, state.eff_ms, state.burst))

    new_state = TableState(
        key=tkey,
        meta=_scatter_rows(state.meta, wrow, meta_new.astype(i32),
                           sorted_idx=True),
        limit=limit_n,
        duration=duration_n,
        eff_ms=eff_n,
        burst=burst_n,
        remaining=_scatter_rows(state.remaining, wrow, item_final.rem,
                                sorted_idx=True),
        t_ms=_scatter_rows(state.t_ms, wrow, item_final.t,
                           sorted_idx=True),
        expire_at=_scatter_rows(state.expire_at, wrow, item_final.exp,
                                sorted_idx=True),
    )

    # ---- back to request order -----------------------------------------
    inv = jnp.zeros(B, i32).at[perm].set(jnp.arange(B, dtype=i32),
                                         unique_indices=True)
    status = jnp.where(valid & (~err), o_status[inv], 0)
    remaining = jnp.where(valid & (~err), o_rem[inv], 0)
    reset_time = jnp.where(valid & (~err), o_reset[inv], 0)
    limit_out = jnp.where(valid & (~err), o_limit[inv], 0)
    over_count = (valid & (~err) & (status == 1)).sum(dtype=i64)

    return new_state, StepOutput(
        status=status, remaining=remaining, reset_time=reset_time,
        limit=limit_out, err=err, over_count=over_count,
        insert_count=insert_count,
    )


#: Host-dispatch entry point WITHOUT buffer donation — test/debug use.
#:
#: Serving uses the donated variant below (and has since the v5e
#: measurement of 2026-07-31, PERF.md §5.1): on that lowering the
#: NON-donated row scatters serialize at ~3 µs/row — 209 ms/batch at
#: B=65536, 365× slower than donated — and donation also wins 6.3× on
#: CPU.  Copy mode survives only for callers that cannot thread state
#: linearly (tests asserting on both old and new tables, lowerings
#: without aliasing support).
decide_batch = jax.jit(decide_batch_impl)

#: Donated variant: the table aliases in/out, so the cond-gated cold
#: columns (limit/duration/eff/burst; key when no insert) pass through
#: with ZERO copies on clean steps, and — lowering permitting — the hot
#: scatters update in place, making per-step HBM traffic ~B-sized
#: instead of CAP-sized (the VERDICT r1 "streaming wall" fix).  Inside
#: lax.scan the loop-carried state gets the same in-place treatment
#: automatically, which is how the round-0 551 M/s on-chip rate was
#: reached.  Callers MUST thread state linearly: the old state dies at
#: the call.  bench.py measures both entry points and records which one
#: wins on the current backend.
decide_batch_donated = jax.jit(decide_batch_impl, donate_argnums=0)

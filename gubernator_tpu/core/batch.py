"""Host-side request packing: wire requests → fixed-shape device arrays.

The analog of the reference's request batching (peer_client.go › run()
flush loop + gubernator.go › GetRateLimits fan-out): requests are
coalesced into padded fixed-shape arrays so every batch reuses the same
compiled program (SURVEY.md §7.3 — bucketed batch sizes avoid
recompilation storms).

Everything calendar- or string-shaped happens here, on the host: key
hashing, Gregorian period-end computation, input clamps.  The device only
ever sees integers.
"""
from __future__ import annotations

from typing import List, NamedTuple, Sequence

import jax
import numpy as np

from ..gregorian import gregorian_expiration, gregorian_rate_duration_ms
from ..hashing import hash_keys
from ..types import (DURATION_MAX, EFF_MAX, TD_BOUND, VALUE_MAX, Behavior,
                     RateLimitRequest)

#: Batch sizes are rounded up to one of these to bound compile cache size.
BATCH_BUCKETS = (64, 256, 1024, 4096)

#: Back-compat alias for the old global input ceiling; the real bounds
#: are algorithm-aware now (types.py: DURATION_MAX / VALUE_MAX / EFF_MAX
#: / TD_BOUND — see oracle.py "Input clamps").
MAX_INPUT = VALUE_MAX


def clamp_config(algorithm, limit, duration, burst, behavior=0):
    """Scalar mirror of the packer clamps for (alg, limit, duration, burst).

    Used by the hot-set pin path (parallel/hotset.py) so pinned rows agree
    bit-for-bit with every packed request carrying the same config — a
    disagreement reads as a config change on the device and resets the
    row.  Must stay in lockstep with pack_requests/pack_columns and the
    oracle's _clamp_token/_clamp_leaky.
    """
    alg = 1 if int(algorithm) == 1 else 0
    duration = min(int(duration), DURATION_MAX)
    if alg == 1:
        if int(behavior) & int(Behavior.DURATION_IS_GREGORIAN):
            eff = gregorian_rate_duration_ms(duration)
        else:
            eff = max(duration, 1)
        cap_v = min(TD_BOUND // min(eff, EFF_MAX), VALUE_MAX)
    else:
        cap_v = VALUE_MAX
    limit = min(max(int(limit), 0), cap_v)
    burst = min(int(burst), cap_v) if int(burst) > 0 else limit
    return alg, limit, duration, burst


class RequestBatch(NamedTuple):
    """Fixed-shape [B] device view of a GetRateLimitsReq batch.

    ``now`` is per-request arrival time (epoch ms) — the device honors
    it per position, so batches packed at different wall-clock instants
    coalesce into one launch without quantizing time (the reference's
    sequential loop also reads the clock per request).  The packers
    always fill it; None (or a 0 entry) falls back to the scalar
    ``now_ms`` argument in ``decide_batch_impl`` ONLY — the serving
    paths (check_packed / check_columns / pack_wave_host) require the
    column.
    """

    key: jax.Array | np.ndarray  # uint64, 0 = padding
    hits: jax.Array | np.ndarray  # int64, clamped ≥ 0
    limit: jax.Array | np.ndarray  # int64, clamped ≥ 0
    duration: jax.Array | np.ndarray  # int64, as given
    eff_ms: jax.Array | np.ndarray  # int64, ≥ 1
    greg_end: jax.Array | np.ndarray  # int64, calendar period end (0 if n/a)
    behavior: jax.Array | np.ndarray  # int32 flags
    algorithm: jax.Array | np.ndarray  # int32
    burst: jax.Array | np.ndarray  # int64, already defaulted to limit
    valid: jax.Array | np.ndarray  # bool
    now: jax.Array | np.ndarray | None = None  # int64 epoch ms, 0 = unset


class WaveLease:
    """One leased pair of packed upload matrices (a64 [8,m] i64,
    a32 [3,m] i32) from a :class:`WaveBufferPool`.

    The holder must call :meth:`release` on EVERY path (success, engine
    raise, close) once the device launch has consumed the buffers —
    jax copies host operands during dispatch, so release-after-launch
    is safe.  A lease dropped without release is detected by the GC
    hook: the pool counts it as a leak (``gubernator_wave_buffer_leaks``)
    and reclaims the buffers, so a bug degrades to a counter, not an
    unbounded allocation regression."""

    __slots__ = ("a64", "a32", "_pool", "_released", "__weakref__")

    def __init__(self, pool: "WaveBufferPool", a64, a32):
        self._pool = pool
        self.a64 = a64
        self.a32 = a32
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._pool._return(self.a64, self.a32)

    def __del__(self):  # pragma: no cover - exercised via gc in tests
        if not self._released:
            self._released = True
            self._pool._record_leak()
            self._pool._return(self.a64, self.a32)


class WaveBufferPool:
    """Ring of reusable packed wave-upload matrices, keyed by padded
    wave width ``m`` (= n_shards × wave bucket).

    The serving loop used to allocate a fresh [8,m] i64 + [3,m] i32
    pair (~0.7 MB at the default big bucket) for EVERY device wave;
    under the overlapped wave pipeline the same few shapes recur every
    couple hundred microseconds, so the allocator/page-fault churn is
    pure host-glue overhead (PERF.md §4.2).  ``lease(m)`` hands back a
    pooled pair (zeroed to ``empty_batch`` padding semantics: all zeros,
    ``eff_ms`` row = 1) or allocates on miss; ``WaveLease.release``
    returns it.  Thread-safe; the per-width ring is bounded (pipeline
    depth + a small margin) so a burst of odd widths cannot grow the
    pool without bound.

    ``metrics`` may be bound post-construction (V1Instance does) to a
    ``Metrics`` registry carrying ``wave_buffer_pool_hit`` /
    ``wave_buffer_pool_miss`` / ``wave_buffer_leaks`` counters.
    """

    #: pooled buffers kept per width — covers pipeline depth K plus the
    #: wave being packed while K are in flight
    MAX_PER_WIDTH = 4

    def __init__(self, max_per_width: int | None = None):
        import threading

        self._mu = threading.Lock()
        #: m → [(a64, a32), ...]
        self._free: dict[int, list] = {}  # guarded-by: self._mu
        self.max_per_width = (max_per_width if max_per_width is not None
                              else self.MAX_PER_WIDTH)
        self.hits = 0  # guarded-by: self._mu
        self.misses = 0  # guarded-by: self._mu
        self.leaks = 0  # guarded-by: self._mu
        self.outstanding = 0  # guarded-by: self._mu
        self.metrics = None  # bound by V1Instance after construction

    def lease(self, m: int) -> WaveLease:
        """Lease a zeroed (a64 [8,m] i64, a32 [3,m] i32) pair.  Padding
        rows keep ``empty_batch`` semantics: zeros everywhere, eff_ms 1
        (the eff_ms re-fill is the caller's job — ``_fill_packed``
        writes that row for every slot it doesn't scatter)."""
        with self._mu:
            ring = self._free.get(m)
            buf = ring.pop() if ring else None
            if buf is not None:
                self.hits += 1
            else:
                self.misses += 1
            self.outstanding += 1
        if buf is not None:
            a64, a32 = buf
            a64.fill(0)
            a32.fill(0)
            if self.metrics is not None:
                self.metrics.wave_buffer_pool_hit.inc()
        else:
            a64 = np.zeros((8, m), np.int64)
            a32 = np.zeros((3, m), np.int32)
            if self.metrics is not None:
                self.metrics.wave_buffer_pool_miss.inc()
        return WaveLease(self, a64, a32)

    def _return(self, a64, a32) -> None:
        m = a64.shape[1]
        with self._mu:
            self.outstanding -= 1
            ring = self._free.setdefault(m, [])
            if len(ring) < self.max_per_width:
                ring.append((a64, a32))

    def _record_leak(self) -> None:
        with self._mu:
            self.leaks += 1
        if self.metrics is not None:
            self.metrics.wave_buffer_leaks.inc()

    def stats(self) -> dict:
        with self._mu:
            return {"hits": self.hits, "misses": self.misses,
                    "leaks": self.leaks, "outstanding": self.outstanding,
                    "pooled": sum(len(v) for v in self._free.values())}

    def mem_stats(self) -> dict:
        """Memory-ledger probe feed (ISSUE 13): host bytes the idle
        rings hold right now — summed from the live arrays, so an
        odd-width burst or a shrunk ring stays exact."""
        with self._mu:
            pooled = nbytes = 0
            for ring in self._free.values():
                for a64, a32 in ring:
                    pooled += 1
                    nbytes += int(a64.nbytes) + int(a32.nbytes)
            return {"pooled": pooled, "pooled_bytes": nbytes,
                    "hits": self.hits}


def bucket_size(n: int) -> int:
    for b in BATCH_BUCKETS:
        if n <= b:
            return b
    return ((n + BATCH_BUCKETS[-1] - 1) // BATCH_BUCKETS[-1]) * BATCH_BUCKETS[-1]


def empty_batch(size: int) -> RequestBatch:
    return RequestBatch(
        key=np.zeros(size, np.uint64),
        hits=np.zeros(size, np.int64),
        limit=np.zeros(size, np.int64),
        duration=np.zeros(size, np.int64),
        eff_ms=np.ones(size, np.int64),
        greg_end=np.zeros(size, np.int64),
        behavior=np.zeros(size, np.int32),
        algorithm=np.zeros(size, np.int32),
        burst=np.zeros(size, np.int64),
        valid=np.zeros(size, bool),
        now=np.zeros(size, np.int64),
    )


def pack_requests(
    reqs: Sequence[RateLimitRequest],
    now_ms: int,
    size: int | None = None,
    key_hashes: np.ndarray | None = None,
) -> tuple[RequestBatch, List[str]]:
    """Pack wire requests into a padded RequestBatch.

    Returns (batch, errors) where errors[i] is a per-request error string
    ("" if OK).  Requests with errors (e.g. invalid Gregorian ordinal —
    the reference surfaces these as resp.Error) are marked invalid in the
    batch and skipped by the device.

    ``key_hashes`` lets a dispatcher that already hashed the keys (for
    shard routing) skip re-hashing — string hashing is the host-side
    bottleneck.
    """
    n = len(reqs)
    b = empty_batch(size if size is not None else bucket_size(n))
    errors = [""] * n
    b.key[:n] = key_hashes if key_hashes is not None else hash_keys(
        [r.key for r in reqs])
    GREG = int(Behavior.DURATION_IS_GREGORIAN)  # hot loop: plain-int flags
    b.now[:n] = now_ms
    for i, r in enumerate(reqs):
        if r.created_at:
            # caller's accepted-at clock (forward hop, types.py): the
            # request applies at ITS time base, so a key served through
            # two daemons never mixes bases in one bucket row
            b.now[i] = r.created_at
        behavior = int(r.behavior)
        leaky = int(r.algorithm) == 1
        duration = min(int(r.duration), DURATION_MAX)
        if behavior & GREG:
            try:
                b.greg_end[i] = gregorian_expiration(now_ms, duration)
                eff = gregorian_rate_duration_ms(duration)
            except (ValueError, KeyError):
                errors[i] = f"invalid gregorian duration ordinal: {duration}"
                b.key[i] = 0
                continue
        else:
            eff = max(duration, 1)
        # leaky td bounds: eff ≤ EFF_MAX, values ≤ TD_BOUND // eff
        # (oracle.py › _clamp_leaky); token values ≤ VALUE_MAX
        if leaky:
            eff = min(eff, EFF_MAX)
            cap_v = min(TD_BOUND // eff, VALUE_MAX)
        else:
            cap_v = VALUE_MAX
        limit = min(max(int(r.limit), 0), cap_v)
        b.eff_ms[i] = eff
        b.hits[i] = min(max(int(r.hits), 0), cap_v)
        b.limit[i] = limit
        b.duration[i] = duration
        b.behavior[i] = behavior
        # clamp to {0,1}: any other wire value must mean TOKEN_BUCKET
        # (like the oracle's `== LEAKY_BUCKET` test) — an unclamped
        # value would never equal the stored alg&1 and the row would
        # re-create fresh on every request, bypassing the limit
        b.algorithm[i] = 1 if leaky else 0
        b.burst[i] = min(int(r.burst), cap_v) if int(r.burst) > 0 else limit
        b.valid[i] = True
    return b, errors


def pack_columns(
    khash: np.ndarray,
    hits: np.ndarray,
    limit: np.ndarray,
    duration: np.ndarray,
    algorithm: np.ndarray,
    behavior: np.ndarray,
    burst: np.ndarray,
    now_ms: int,
    created_at: np.ndarray | None = None,
) -> tuple[RequestBatch, dict]:
    """Vectorized pack of already-columnar requests (the C++ wire-ingest
    lane, ops/_native.cpp › parse_get_rate_limits) → RequestBatch.

    Same clamps and semantics as ``pack_requests``, applied as array ops
    — no per-request Python.  Returns (batch, errors) where errors maps
    request index → error string (invalid Gregorian ordinals, as on the
    pb2 path).  ``khash`` must already be mixed and zero-remapped.

    ``created_at`` (optional i64[n], 0 = unset) is the caller's
    accepted-at clock from the forward hop: rows carrying it take it as
    their ``now`` so they apply at the CALLER's time base (Gregorian
    period ends still derive from ``now_ms`` — calendar rows never ride
    the forward stamp).
    """
    n = len(khash)
    behavior32 = behavior.astype(np.int32)
    dur = np.minimum(np.asarray(duration, np.int64), DURATION_MAX)
    eff = np.maximum(dur, 1)
    greg_end = np.zeros(n, np.int64)
    valid = np.ones(n, bool)
    key_col = khash.astype(np.uint64).copy()
    errors: dict = {}
    greg = (behavior32 & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    if greg.any():
        # ≤ a handful of distinct calendar ordinals per batch: compute
        # each period end once on the host, broadcast to its requests
        for d in np.unique(dur[greg]):
            m = greg & (dur == d)
            try:
                greg_end[m] = gregorian_expiration(now_ms, int(d))
                eff[m] = gregorian_rate_duration_ms(int(d))
            except (ValueError, KeyError):
                valid[m] = False
                key_col[m] = 0
                msg = f"invalid gregorian duration ordinal: {int(d)}"
                for i in np.nonzero(m)[0]:
                    errors[int(i)] = msg
    # leaky td bounds (oracle.py › _clamp_leaky): eff ≤ EFF_MAX and
    # hits/limit/burst ≤ TD_BOUND // eff; token values ≤ VALUE_MAX
    leaky = np.asarray(algorithm) == 1
    eff = np.where(leaky, np.minimum(eff, EFF_MAX), eff)
    cap_v = np.where(leaky, np.minimum(TD_BOUND // eff, VALUE_MAX),
                     VALUE_MAX)
    lim = np.minimum(np.clip(np.asarray(limit, np.int64), 0, None), cap_v)
    now_col = np.full(n, now_ms, np.int64)
    if created_at is not None:
        created = np.asarray(created_at, np.int64)
        now_col = np.where(created > 0, created, now_col)
    b = RequestBatch(
        key=key_col,
        hits=np.minimum(np.clip(np.asarray(hits, np.int64), 0, None), cap_v),
        limit=lim,
        duration=dur.copy(),
        eff_ms=eff,
        greg_end=greg_end,
        behavior=behavior32,
        algorithm=leaky.astype(np.int32),
        burst=np.where(burst > 0, np.minimum(burst, cap_v), lim),
        valid=valid,
        now=now_col,
    )
    return b, errors

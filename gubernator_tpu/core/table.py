"""The counter table: one struct-of-arrays resident in HBM.

TPU-native replacement for the reference's Cache interface + LRUCache
(cache.go › Cache{Add, GetItem, UpdateExpiration, Each, Remove},
lrucache.go › LRUCache — reconstructed): instead of millions of heap
items behind a map + intrusive list, all state lives in fixed-capacity
parallel arrays; key→row is an open-addressing (double-hash probe) table
over the ``key`` column.

Eviction model (documented deviation, SURVEY.md §7.1): the reference
evicts strict-LRU at capacity; here expired rows are reclaimed by
``sweep_expired`` and capacity pressure is handled by sizing CAPACITY for
the working set.  Decision parity is unaffected: an expired item and a
missing item produce identical responses (both take the fresh-item path).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

# meta column bit layout
META_ALG_MASK = 1  # bit0: Algorithm (0 token, 1 leaky)
META_STATUS_SHIFT = 1  # bit1: stored Status (for hits=0 queries)


class TableState(NamedTuple):
    """Parallel [capacity] arrays; one row per tracked rate-limit key.

    ``key`` is the 64-bit identity hash (0 = empty slot).  ``remaining``
    holds tokens for TOKEN_BUCKET rows and token-duration fixed-point for
    LEAKY_BUCKET rows (see oracle.py module docstring).  ``t_ms`` is
    created_at for token rows, updated_at for leaky rows.
    """

    key: jax.Array  # uint64[cap], 0 = empty
    meta: jax.Array  # int32[cap], bit0 alg, bit1 stored status
    limit: jax.Array  # int64[cap]
    duration: jax.Array  # int64[cap], as given (ms or Gregorian ordinal)
    eff_ms: jax.Array  # int64[cap], effective ms denominator
    burst: jax.Array  # int64[cap]
    remaining: jax.Array  # int64[cap]
    t_ms: jax.Array  # int64[cap]
    expire_at: jax.Array  # int64[cap], 0 = never-written (always expired)

    @property
    def capacity(self) -> int:
        return self.key.shape[0]


def init_table(capacity: int) -> TableState:
    """Empty table.  ``capacity`` must be a power of two (probe masking)."""
    if capacity & (capacity - 1) or capacity <= 0:
        raise ValueError(f"capacity must be a power of two, got {capacity}")
    if not jax.config.jax_enable_x64:
        # Guard against an embedding application resetting the flag after
        # our import-time enable: int64 columns would silently become
        # int32 and overflow on epoch-ms arithmetic.
        raise RuntimeError(
            "gubernator_tpu requires jax_enable_x64 (int64 epoch-ms "
            "arithmetic); it was disabled after import")
    return TableState(
        key=jnp.zeros((capacity,), jnp.uint64),
        meta=jnp.zeros((capacity,), jnp.int32),
        limit=jnp.zeros((capacity,), jnp.int64),
        duration=jnp.zeros((capacity,), jnp.int64),
        eff_ms=jnp.ones((capacity,), jnp.int64),
        burst=jnp.zeros((capacity,), jnp.int64),
        remaining=jnp.zeros((capacity,), jnp.int64),
        t_ms=jnp.zeros((capacity,), jnp.int64),
        expire_at=jnp.zeros((capacity,), jnp.int64),
    )


def occupancy(state: TableState) -> jax.Array:
    """Number of live rows (cache-size gauge analog, lrucache.go)."""
    return (state.key != 0).sum()


@jax.jit
def sweep_expired(state: TableState, now_ms: jax.Array) -> TableState:
    """Reclaim rows whose expiry has passed.

    Parity-safe: an expired row and an empty row behave identically on
    next access (fresh-item path), so clearing keys changes no decisions.
    Replaces the reference's LRU eviction + UpdateExpiration bookkeeping.
    """
    dead = state.expire_at <= now_ms
    return state._replace(
        key=jnp.where(dead, jnp.uint64(0), state.key),
        # Also zero expire_at so a later occupant of the slot is
        # unconditionally fresh even if its first access carries an
        # earlier now_ms (caller clock skew) than the dead row's expiry.
        expire_at=jnp.where(dead, jnp.int64(0), state.expire_at),
    )

"""Device-memory ledger: the fourth debug plane (ISSUE 13).

Every device-resident allocation enrolls here at creation with a probe
closure; the ledger itself stores NO tensor references.  Engines rebind
their state arrays constantly (grow, sweep, donated steps), so a probe
re-reads the live attributes at snapshot time and returns the current
byte count — which is what makes the exactness audit
(tests/test_memledger.py) possible: accounted bytes == live ``nbytes``
at any instant, not at enrollment time.

Probe contract — a zero-arg callable returning a dict::

    {"bytes": int,            # live bytes, summed over the consumer
     "capacity_rows": int,    # 0 when the consumer has no row notion
     "occupied_rows": int,    # live occupancy counter the tier keeps
     "demand": {...}}         # optional per-consumer rate counters

Probes run OUTSIDE the ledger lock (they take engine/state locks of
their own; ``self._mu`` is leaf-ranked in the lock hierarchy), so a
probe must never call back into the ledger.

The advisor (``advise``) is the headline deliverable: a "One Pool, Two
Caches"-style water-filling over the measured demand vector.  It is a
recommendation only — nothing repartitions live.  Each advisable
consumer contributes a marginal-hit-density curve (the hot table's from
the Space-Saving rank distribution analytics exports, everything else
flat from its occupancy + rate counters) and granules of the shared row
budget go to whoever's next granule buys the most hits.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int, lo: int = 1) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return max(int(raw), lo)
    except ValueError:
        return default


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n - 1).bit_length(), 0) if n > 1 else 1


class MemoryLedger:
    """Per-instance registry of device (and host) memory consumers."""

    def __init__(self, recorder=None):
        self._mu = threading.Lock()
        self._probes: Dict[str, tuple] = {}  # guarded-by: self._mu
        self._enabled = True  # guarded-by: self._mu
        self._pressure_hi = False  # guarded-by: self._mu
        self._published: set = set()  # guarded-by: self._mu
        self._recorder = recorder
        self.pressure_target = _env_float("GUBER_MEM_PRESSURE", 0.85)
        self.advise_floor = _env_int("GUBER_MEM_ADVISE_FLOOR", 64)

    # ------------------------------------------------------------------
    # enrollment
    def enroll(self, consumer: str,
               probe: Callable[[], dict],
               host: bool = False,
               advisable: bool = False) -> None:
        """Register (or re-register) a consumer.  ``host=True`` keeps it
        out of the device ledger (cold store, numpy pools, sketch);
        ``advisable=True`` marks its row capacity as a knob the advisor
        may move."""
        with self._mu:
            self._probes[consumer] = (probe, bool(host), bool(advisable))

    def release(self, consumer: str) -> bool:
        """Drop a consumer at stand-down; True if it was enrolled."""
        with self._mu:
            return self._probes.pop(consumer, None) is not None

    def consumers(self) -> List[str]:
        with self._mu:
            return sorted(self._probes)

    # ------------------------------------------------------------------
    # bench A/B toggle: a suspended ledger answers snapshots with an
    # empty plane but keeps its enrollment table, so resume is exact.
    def suspend(self) -> None:
        with self._mu:
            self._enabled = False

    def resume(self) -> None:
        with self._mu:
            self._enabled = True

    @property
    def enabled(self) -> bool:
        # lock-free: GIL-atomic single read of a bool
        return self._enabled

    # ------------------------------------------------------------------
    # snapshot
    def snapshot(self) -> dict:
        """Bytes, rows, occupancy and the demand vector, per consumer.

        The enrollment table is copied under the leaf lock, then probes
        run unlocked — they acquire engine/state locks themselves."""
        with self._mu:
            enabled = self._enabled
            probes = dict(self._probes)
        out: Dict[str, dict] = {}
        dev_bytes = host_bytes = 0
        w_occ = w_cap = 0.0
        if enabled:
            for name in sorted(probes):
                probe, host, advisable = probes[name]
                try:
                    rec = dict(probe())
                except Exception as e:  # pragma: no cover - defensive
                    out[name] = {"error": f"{type(e).__name__}: {e}",
                                 "host": host}
                    continue
                rec.setdefault("bytes", 0)
                rec.setdefault("capacity_rows", 0)
                rec.setdefault("occupied_rows", 0)
                rec["host"] = host
                rec["advisable"] = advisable
                out[name] = rec
                if host:
                    host_bytes += int(rec["bytes"])
                else:
                    dev_bytes += int(rec["bytes"])
                    if rec["capacity_rows"] > 0:
                        w_cap += float(rec["bytes"])
                        frac = (min(rec["occupied_rows"],
                                    rec["capacity_rows"])
                                / rec["capacity_rows"])
                        w_occ += float(rec["bytes"]) * frac
        pressure = (w_occ / w_cap) if w_cap > 0 else 0.0
        return {"enabled": enabled,
                "consumers": out,
                "device_bytes": dev_bytes,
                "host_bytes": host_bytes,
                "pressure": pressure,
                "pressure_target": self.pressure_target}

    # ------------------------------------------------------------------
    # pressure plane
    def pressure_sample(self) -> tuple:
        """``(pressure, target)`` for the threshold-kind ``hbm_pressure``
        SLO.  Edge-triggers one ``memory_pressure`` flight-recorder
        event per excursion above target — before table-full or
        cap-overflow starts demoting."""
        snap = self.snapshot()
        p = snap["pressure"]
        hot = p > self.pressure_target
        with self._mu:
            was = self._pressure_hi
            self._pressure_hi = hot
        if hot and not was and self._recorder is not None:
            top = {name: round(
                       rec.get("occupied_rows", 0)
                       / max(rec.get("capacity_rows", 1), 1), 4)
                   for name, rec in snap["consumers"].items()
                   if not rec.get("host") and "error" not in rec
                   and rec.get("capacity_rows", 0) > 0}
            self._recorder.record("memory_pressure",
                                  pressure=round(p, 4),
                                  target=self.pressure_target,
                                  device_bytes=snap["device_bytes"],
                                  occupancy=top)
        return p, self.pressure_target

    # ------------------------------------------------------------------
    # gauges
    def republish(self, metrics) -> None:
        """Refresh the two ledger gauge families; departed consumers'
        label sets are removed so a released tier doesn't linger at its
        last value."""
        if metrics is None:
            return
        snap = self.snapshot()
        seen = set()
        for name, rec in snap["consumers"].items():
            if "error" in rec:
                continue
            metrics.memledger_bytes.labels(consumer=name).set(
                rec["bytes"])
            metrics.memledger_rows.labels(
                consumer=name, state="capacity").set(rec["capacity_rows"])
            metrics.memledger_rows.labels(
                consumer=name, state="occupied").set(rec["occupied_rows"])
            seen.add(name)
        with self._mu:
            gone = self._published - seen
            self._published = seen
        for name in gone:
            try:
                metrics.memledger_bytes.remove(name)
                metrics.memledger_rows.remove(name, "capacity")
                metrics.memledger_rows.remove(name, "occupied")
            except KeyError:
                pass

    # ------------------------------------------------------------------
    # the advisor
    def advise(self, total_rows: Optional[int] = None,
               granule: Optional[int] = None) -> dict:
        """Water-fill the shared row budget over the demand vector.

        ``total_rows`` defaults to the sum of the advisable consumers'
        current capacities (the budget a repartition could move around);
        the dryrun passes the combined configured budget explicitly.
        Returns the current split, the advised split (raw and pow2-
        rounded), and the demand evidence — a recommendation, never a
        live repartition."""
        snap = self.snapshot()
        cands: Dict[str, dict] = {
            name: rec for name, rec in snap["consumers"].items()
            if rec.get("advisable") and "error" not in rec}
        current = {n: int(r["capacity_rows"]) for n, r in cands.items()}
        if total_rows is None:
            total_rows = sum(current.values())
        floor = max(1, self.advise_floor)
        gran = max(1, granule if granule is not None else floor)
        advised = {n: min(floor, total_rows) for n in cands}
        budget = total_rows - sum(advised.values())
        densities = {n: self._density_fn(n, r) for n, r in cands.items()}
        while budget >= gran and densities:
            best, best_d = None, -1.0
            for n, fn in densities.items():
                d = fn(advised[n])
                if d > best_d:
                    best, best_d = n, d
            if best is None or best_d <= 0.0:
                break
            advised[best] += gran
            budget -= gran
        if budget > 0 and advised:
            # leftover rows go to the steepest remaining curve
            best = max(advised,
                       key=lambda n: densities[n](advised[n]))
            advised[best] += budget
        return {"total_rows": int(total_rows),
                "floor_rows": floor,
                "granule_rows": gran,
                "current": current,
                "advised": advised,
                "advised_pow2": {n: _pow2_ceil(v)
                                 for n, v in advised.items()},
                "demand": {n: r.get("demand", {})
                           for n, r in cands.items()},
                "pressure": snap["pressure"]}

    @staticmethod
    def _density_fn(name: str, rec: dict) -> Callable[[int], float]:
        """Marginal hit density at row index r for one consumer.

        A ``demand.ranks`` vector (the Space-Saving rank distribution,
        descending counts) gives a real curve with a harmonic tail
        extrapolation past the sketch's horizon; otherwise the demand
        rate spreads flat over the occupied rows and falls to zero past
        a 2x headroom band — rows beyond twice the live working set buy
        nothing."""
        demand = rec.get("demand", {}) or {}
        ranks = demand.get("ranks")
        if ranks:
            ranks = [max(float(v), 0.0) for v in ranks]
            n = len(ranks)
            tail = ranks[-1] if ranks[-1] > 0 else 0.0

            def density(r: int, _ranks=ranks, _n=n, _tail=tail) -> float:
                if r < _n:
                    return _ranks[r]
                return _tail * _n / (r + 1)

            return density
        rate = 0.0
        for k in ("hit_rate", "rate", "promote_rate", "fold_rate"):
            if demand.get(k):
                rate = float(demand[k])
                break
        occ = max(int(rec.get("occupied_rows", 0)), 0)
        if rate <= 0.0 or occ == 0:
            return lambda r: 0.0
        flat = rate / occ

        def density(r: int, _flat=flat, _occ=occ) -> float:
            return _flat if r < 2 * _occ else 0.0

        return density

"""The core instance: request routing over the device engine.

reference: gubernator.go › V1Instance{GetRateLimits, GetPeerRateLimits,
UpdatePeerGlobals, HealthCheck, SetPeers} — reconstructed, mount empty.

The hot path inverts the reference design (SURVEY.md §7.1): instead of a
per-request loop over a mutex-guarded LRU, all locally-owned requests in
a client batch execute as ONE device program (probe → gather →
branchless update → scatter) on the sharded HBM table.  Peer routing
(consistent hash over daemon processes) wraps around that device core
exactly like the reference wraps around its cache.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import Future
from dataclasses import replace
from typing import Dict, List, Optional, Sequence

import numpy as np

from .config import Config
from .global_manager import GlobalManager
from .gregorian import gregorian_rate_duration_ms
from .hashing import hash_key
from .metrics import Metrics
from .multiregion import MultiRegionManager
from .peer_client import ErrClosing, PeerClient
from .peers import RegionPeerPicker, ReplicatedConsistentHash
from .telemetry import FlightRecorder, exc_text
from .proto import gubernator_pb2 as pb
from .proto import peers_pb2 as peers_pb
from .store import CacheItem
from .types import (
    Algorithm,
    Behavior,
    HealthCheckResponse,
    MAX_BATCH_SIZE,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    Status,
)

log = logging.getLogger("gubernator_tpu.instance")

#: empty boolean mask — the "no rows match" result when a behavior_or
#: gate proves a column scan unnecessary (.any() is False)
_NO_ROWS = np.zeros(0, bool)

try:  # C++ wire-ingest lane (ops/_native.cpp); optional
    from .ops import native as _wire_native
except ImportError:  # pragma: no cover - unbuilt extension
    _wire_native = None



def _created_at_fwd_enabled() -> bool:
    """GUBER_CREATED_AT_FWD=0 disables caller-clock forwarding (the
    created_at stamp on forwarded TLVs and deferred hit queues) —
    restoring the pre-fix behavior where every hop applies requests at
    its own wall clock.  Exists so tools/racer.py and the conservation
    regression tests can demonstrate the cold-key loss the stamp fixes;
    never disable it in production."""
    return os.environ.get("GUBER_CREATED_AT_FWD", "1") != "0"

def clock_ms() -> int:
    return time.time_ns() // 1_000_000  # clock-ok: the clock source itself


def _forward_fail_reason(e: Optional[BaseException]) -> str:
    """Stable low-cardinality reason label for
    gubernator_forward_failed (ISSUE 5 satellite)."""
    from .peer_client import ErrCircuitOpen

    if isinstance(e, ErrCircuitOpen):
        return "circuit_open"
    if isinstance(e, ErrClosing):
        return "closing"
    if isinstance(e, (TimeoutError,)) or \
            type(e).__name__ == "TimeoutError":
        return "timeout"
    if isinstance(e, RuntimeError) and "short" in (str(e) or ""):
        return "short_response"
    return "rpc_error"


class V1Instance:
    """One daemon's rate-limit brain: device engine + peer router."""

    def __init__(self, config: Config, mesh=None, engine=None,
                 peer_tls_creds=None):
        self.config = config
        self.metrics = Metrics()
        #: bounded structured-event ring (telemetry.py): wave launches/
        #: stalls/timeouts, handover passes, GLOBAL broadcasts, errors —
        #: served as JSON at the daemon's GET /debug/events
        self.recorder = FlightRecorder()
        # Trace plane (ISSUE 12, tracing.py): bounded ring of completed
        # spans, armed per request by the daemon's handlers; head
        # sampling at GUBER_TRACE_SAMPLE (default 0 — forced-sample
        # outcomes still record), capacity GUBER_TRACE_SPANS.  Served
        # at GET /debug/traces; spilled as JSONL on close.
        from .tracing import SpanRecorder

        try:
            _sample = float(os.environ.get("GUBER_TRACE_SAMPLE") or 0.0)
        except ValueError:
            _sample = 0.0
        try:
            _tcap = int(os.environ.get("GUBER_TRACE_SPANS") or 2048)
        except ValueError:
            _tcap = 2048
        self.span_recorder = SpanRecorder(capacity=max(_tcap, 1),
                                          sample=_sample)
        # Fault injection (ISSUE 5, faults.py): per-instance named
        # faultpoints, armed from GUBER_FAULT / POST /debug/faults.
        # One attribute read per instrumented site while disarmed.
        from .faults import FaultSet

        self.faults = FaultSet.from_env()
        self.faults.metrics = self.metrics
        self.faults.recorder = self.recorder
        # Device-memory ledger (ISSUE 13, memledger.py): every device-
        # resident allocation enrolls with a probe closure; serves the
        # memledger gauges, GET /debug/memory (+?advise=1), and the
        # hbm_pressure SLO.  GUBER_MEM_LEDGER=0 disables the plane.
        self.memledger = None
        self._memledger_live = 0  # last occupancy_nowait sample
        if os.environ.get("GUBER_MEM_LEDGER", "1") != "0":
            from .memledger import MemoryLedger

            self.memledger = MemoryLedger(recorder=self.recorder)
        # Compile ledger (ISSUE 14, compileledger.py): per-fn XLA
        # compile counts + the steady-state recompile verdict — the
        # runtime twin of guberlint's retrace pass.  Process-wide
        # singleton (compiles are process-wide events); each instance
        # mirrors counts into its own registry.
        from .compileledger import LEDGER as _compile_ledger
        from .compileledger import install_if_enabled

        if install_if_enabled():
            _compile_ledger.attach_metrics(self.metrics)
        self.compile_ledger = _compile_ledger
        if engine is None:
            # lazy: an injected engine (tests, alternative backends)
            # must not drag the sharded/jax stack in
            from .parallel import make_mesh

            m = mesh if mesh is not None else make_mesh()
            n = m.shape["shard"]
            cap_local = max(config.cache_size // n, 1024)
            cap_local = 1 << (cap_local - 1).bit_length()
            step_impl = (os.environ.get("GUBER_STEP_IMPL")
                         or config.step_impl or "xla")
            if step_impl not in ("xla", "pallas"):
                # a typo must not silently serve the wrong mode — the
                # pallas choice carries domain restrictions the
                # operator believes are live
                raise ValueError(
                    f"unknown step_impl {step_impl!r} (want 'xla' or "
                    "'pallas')")
            import jax as _jax

            from .parallel.pallas_engine import resolve_engine_kind

            # GUBER_ENGINE (ISSUE 8): auto → fused pallas on TPU,
            # classic xla elsewhere; explicit pallas → fused serving
            # everywhere (compiled XLA flavor off-TPU); unknown raises
            # inside resolve_engine_kind.
            kind = resolve_engine_kind(
                os.environ.get("GUBER_ENGINE") or config.engine or "",
                step_impl, _jax.default_backend())
            engine = self._build_engine(kind, m, n, cap_local, config)
        self.engine = engine
        self._engine_mu = threading.Lock()
        from .dispatcher import Dispatcher

        # Key-level analytics (ISSUE 4, analytics.py): heavy-hitter
        # ledger + per-phase latency attribution, fed off the hot path
        # from resolved waves' columns.  GUBER_ANALYTICS=0 disables the
        # whole subsystem (GUBER_TOPK / GUBER_SKETCH_WIDTH tune it).
        analytics = None
        if os.environ.get("GUBER_ANALYTICS", "1") != "0":
            from .analytics import KeyAnalytics

            analytics = KeyAnalytics(metrics=self.metrics)
        # Cross-request coalescing: concurrent handler threads share
        # device launches instead of serializing on the engine lock
        # (the worker-pool analog, see dispatcher.py).  Wave telemetry
        # lands on this instance's registry + recorder.
        self.dispatcher = Dispatcher(engine, lock=self._engine_mu,
                                     metrics=self.metrics,
                                     recorder=self.recorder,
                                     analytics=analytics,
                                     faults=self.faults)
        # waves emit fan-in spans + exact phase children (ISSUE 12)
        self.dispatcher.span_recorder = self.span_recorder
        # Fused-engine wiring (ISSUE 8): the fused serving program
        # emits the heavy-hitter tap columns ON DEVICE — hand the
        # analytics sink + metrics registry to the engine BEFORE any
        # serving starts (single assignment, read-only afterwards).
        if getattr(engine, "fused_tap", False):
            if analytics is not None:
                engine.tap_sink = analytics.tap_device
            engine.metrics_ref = self.metrics
        # wave-buffer pool counters (hit/miss/leak) land on this
        # instance's registry; the pool lives engine-side (lease scope
        # is the engine's fill→launch window)
        pool = getattr(engine, "wave_pool", None)
        if pool is not None:
            pool.metrics = self.metrics
        # Tiered key store (ISSUE 10, tiering.py): host cold tier
        # behind the device table with sketch-rank admission.  The
        # controller binds as engine.tier; check_packed pre-masks and
        # cold-serves through it.  Victim picks skip mesh-/hot-set-
        # pinned keys: their device row is a replica-coherence home
        # copy, and demoting it would fork state.
        self._tier = None
        tier_cold = os.environ.get("GUBER_TIER_COLD")
        if (tier_cold == "1" if tier_cold is not None
                else config.tier_cold):
            from .tiering import TierController

            thr = int(os.environ.get("GUBER_TIER_PROMOTE")
                      or config.tier_promote_threshold)
            rank_fn = (analytics.sketch_count
                       if analytics is not None else None)
            tap = None
            if getattr(engine, "fused_tap", False) \
                    and analytics is not None:
                # fused engines tap on device and the device tap gates
                # out invalid rows — cold rows ride the wave invalid,
                # so the tier feeds their counts to the sketch itself
                tap = analytics.tap_packed
            self._tier = TierController(
                engine, rank_fn=rank_fn, promote_threshold=thr,
                metrics=self.metrics, recorder=self.recorder,
                fault=self._fault_point,
                skip_victim=self._tier_victim_pinned, tap=tap,
                rank_batch=(analytics.sketch_counts
                            if analytics is not None else None))
        # every eagerly-built consumer enrolls now; the lazy tiers
        # (hot set, mesh-GLOBAL) enroll inside their _ensure_* builders
        self._enroll_memledger()
        self._peer_tls = peer_tls_creds
        # Datacenter-aware deployments route through a region picker
        # (region_picker.go); single-region uses the flat ring.
        if config.data_center:
            self._picker = RegionPeerPicker(config.data_center)  # guarded-by: self._peer_mu
        else:
            self._picker = ReplicatedConsistentHash()  # guarded-by: self._peer_mu
        self._peer_mu = threading.Lock()
        self._self_addr = config.advertise_address
        # Health-gated routing ring (ISSUE 5): peers whose circuit has
        # been open past peer_eject_after_ms are EJECTED from a derived
        # routing picker (their keys deterministically rehome to the
        # next ring point) and readmitted only after staying recovered
        # for peer_readmit_after_ms.  All under _peer_mu.
        #: lock-free reads are fine (immutable frozenset swap); all
        #: WRITES and read-modify-write derivations hold _peer_mu
        self._gate_bad: frozenset = frozenset()
        self._gate_picker = None  # guarded-by: self._peer_mu
        self._ring_gen = 0  # guarded-by: self._peer_mu
        #: IntervalLoop probing EJECTED peers (rehomed keys carry no
        #: organic traffic, so nothing else would half-open their
        #: circuit); started lazily on first ejection
        self._probe_loop = None
        self.global_manager: Optional[GlobalManager] = None
        self.mr_manager: Optional[MultiRegionManager] = None
        self._gm_mu = threading.Lock()
        # GLOBAL reconcile backend (ISSUE 7): "grpc" keeps the
        # reference's hit-queue/broadcast machinery; "mesh" serves
        # pod-local GLOBAL keys from the mesh-resident replica tier
        # (parallel/meshglobal.py) and reconciles with ONE collective
        # fold per GlobalSyncWait tick — zero gRPC peer fan-out.  The
        # gRPC path stays for cross-pod owners and as the degraded
        # fallback when the fold is unhealthy.
        global_mode = (os.environ.get("GUBER_GLOBAL_MODE")
                       or config.global_mode or "grpc")
        if global_mode not in ("grpc", "mesh"):
            # a typo must not silently serve the wrong coherence model
            raise ValueError(
                f"unknown global_mode {global_mode!r} (want 'grpc' or "
                "'mesh')")
        self._global_mode = global_mode
        self._meshglobal = None
        #: single-writer state (the GlobalManager hits-loop thread owns
        #: the reconcile tick); request threads only read — a stale
        #: read routes one batch the conservative (sharded) way
        self._mesh_fail_streak = 0  # lock-free: tick-thread only
        self._mesh_degraded = False  # lock-free: single racy bool
        self._mesh_down_until = 0.0  # lock-free: single racy float
        # Replicated hot-set (psum GLOBAL tier, parallel/hotset.py):
        # lazily built on first promotion; pod-local only.  Unused in
        # mesh mode (the mesh tier serves ALL qualifying GLOBAL keys —
        # two replica tiers for one key would double-count).
        self._hotset = None
        self._hot_mu = threading.Lock()
        #: key_hash → weight
        self._hot_counts: Dict[int, int] = {}  # guarded-by: self._hot_mu
        self._hot_sync_loop = None
        self._promote_pending: List[tuple] = []
        # stateful-handover serialization: one pass at a time, and a
        # generation counter so a newer membership change supersedes an
        # in-flight pass (it re-snapshots whatever is left)
        self._handover_mu = threading.Lock()
        self._handover_gen = 0  # guarded-by: self._handover_gen_mu
        self._handover_gen_mu = threading.Lock()
        self._closed = False
        self._last_sweep = clock_ms()  # clock-ok: sweep cadence bookkeeping, never a bucket stamp
        self.store = config.store
        self.loader = config.loader
        if self.loader is not None:
            self._load_from_loader()
        if self._mesh_mode():
            # the reconcile tick rides the GlobalManager's hits loop
            # (its mesh backend) — start it now so folds run even
            # before any gRPC-lane work would have built the manager
            self._ensure_global_manager()
            # pre-compile the mesh tier's step + fold NOW: a lazy
            # first-touch compile would land inside a caller's GLOBAL
            # request, long enough (CPU: seconds) to idle-expire
            # short-duration buckets before their second request
            self._ensure_meshglobal().warmup()
            # fused engines: also pre-compile the fused mesh program
            # (decide + scatter in one launch) per wave bucket
            if hasattr(self.engine, "warmup_mesh_fused"):
                self.engine.warmup_mesh_fused()
        # Always-on conservation auditor (ISSUE 19, fleet.py): folds
        # the GLOBAL lanes' audit vectors into a per-daemon drift doc
        # served at GET /debug/audit and sampled by the
        # fleet_conservation SLO below.
        from .fleet import ConservationAuditor
        self.auditor = ConservationAuditor(self)
        # Tenant-aware SLO plane (ISSUE 11, slo.py): multi-window
        # burn-rate verdicts over the signals the layers above emit
        # (phase ledger p99, mesh staleness, tenant RED ledger).
        self.slo = None
        self._slo_loop = None
        #: monotonic stamp of the last SUCCESSFUL mesh fold; the
        #: staleness SLO ages against it so a wedged/failing fold
        #: breaches even though last_staleness_s stops updating
        self._mesh_last_fold_ok: Optional[float] = None  # lock-free: tick-thread writes, SLO tick reads
        if os.environ.get("GUBER_SLO", "1") != "0":
            self._build_slo()

    def _build_engine(self, kind: str, m, n: int, cap_local: int,
                      config: Config):
        """Construct the resolved engine kind (ISSUE 8).  Fused kinds
        selected through GUBER_ENGINE fall back LOUDLY to the classic
        sharded engine on construction failure (engine_fallback event +
        warning, decisions stay correct — availability beats mode
        fidelity); the legacy explicit GUBER_STEP_IMPL=pallas raises as
        it always has (the operator asked for that kernel engine
        specifically, e.g. for a parity battery)."""
        from .parallel.sharded import (ShardedEngine,
                                       autogrow_limit_per_shard)

        if kind in ("pallas-kernel", "pallas-fused", "xla-fused"):
            try:
                if kind == "xla-fused":
                    from .parallel.pallas_engine import XlaFusedEngine

                    return XlaFusedEngine(
                        m, capacity_per_shard=cap_local,
                        batch_per_shard=config.batch_rows,
                        auto_grow_limit=autogrow_limit_per_shard(
                            config.cache_autogrow_max, n, cap_local))
                from .parallel.pallas_engine import PallasServingEngine

                if config.cache_autogrow_max:
                    # silently different capacity semantics would be a
                    # trap: the xla engine grows to this bound, pallas
                    # mode never grows (VERDICT r4 weak #4)
                    log.warning(
                        "pallas serving engine ignores "
                        "cache_autogrow_max=%d: this mode has no "
                        "on-device grow — size cache_size for peak "
                        "keys up front (full 8-slot buckets err as "
                        "table_full; watch "
                        "gubernator_pallas_bucket_saturation)",
                        config.cache_autogrow_max)
                return PallasServingEngine(
                    m, capacity_per_shard=cap_local,
                    batch_per_shard=config.batch_rows)
            except Exception as e:  # noqa: BLE001 - loud fallback below
                if kind == "pallas-kernel":
                    raise
                log.warning(
                    "fused engine %r unavailable (%s) — serving falls "
                    "back to the classic sharded engine; decisions are "
                    "identical, the fused-wave perf tier is OFF",
                    kind, exc_text(e))
                self.recorder.record("engine_fallback", wanted=kind,
                                     error=exc_text(e))
        return ShardedEngine(
            m, capacity_per_shard=cap_local,
            batch_per_shard=config.batch_rows,
            auto_grow_limit=autogrow_limit_per_shard(
                config.cache_autogrow_max, n, cap_local))

    # ---- persistence wiring (store.go › Loader/Store) ------------------

    def _load_from_loader(self) -> None:
        from .store import arrays_from_items

        self._fault_point("restore")
        t0 = time.perf_counter()
        items = list(self.loader.load())
        if items:
            arrays = arrays_from_items(items)
            placed = self.engine.restore(arrays)
            log.info("loader: restored %d/%d items", placed, len(items))
        # restore is a serving-blackout window — attribute it (ISSUE 5
        # satellite; closes the PR-4 ROADMAP item with broadcast/
        # snapshot)
        self.dispatcher._obs_phase("restore", time.perf_counter() - t0)

    def _save_to_loader(self) -> None:
        from .store import items_from_arrays

        if self.loader is None:
            return
        self._fault_point("snapshot")
        t0 = time.perf_counter()
        # hot-set / mesh-tier rows live outside the sharded table; fold
        # them back in so the snapshot is complete
        self._demote_all()
        self._mesh_demote_all()
        arrays = self.engine.snapshot()
        if self._tier is not None:
            # cold-tier rows are first-class state: a snapshot covers
            # BOTH tiers (restore re-adopts whatever the device table
            # cannot hold — engine.restore's unplaced → tier path)
            cold = self._tier.snapshot_arrays()
            if cold is not None:
                arrays = {f: np.concatenate([arrays[f], cold[f]])
                          for f in arrays}
        self.loader.save(iter(items_from_arrays(arrays)))
        self.dispatcher._obs_phase("snapshot", time.perf_counter() - t0)

    def _fault_point(self, point: str, tag: Optional[str] = None) -> None:
        """Instance-level faultpoint check (one attribute read while
        disarmed — the acceptance A/B bound)."""
        f = self.faults
        if f.armed:
            f.fire(point, tag)

    # ---- peer management (gubernator.go › SetPeers) --------------------

    def set_peers(self, infos: Sequence[PeerInfo]) -> None:
        """Rebuild the picker atomically; drain clients for departed
        peers.  Keys silently re-home on ring change; moved keys reset
        (documented reference behavior, SURVEY.md §5.3)."""
        with self._peer_mu:
            old_picker = self._picker  # immutable; handover routes by it
            old = {p.info.grpc_address: p for p in self._picker.peers()}
            picker = self._picker.new()
            for info in infos:
                existing = old.pop(info.grpc_address, None)
                if existing is not None:
                    picker.add(existing)
                else:
                    picker.add(PeerClient(info, self.config.behaviors,
                                          tls_creds=self._peer_tls,
                                          metrics=self.metrics,
                                          analytics=self.analytics,
                                          faults=self.faults))
            self._picker = picker
            # membership change invalidates the health-gated view —
            # the next routing lookup re-derives it from live health
            self._gate_bad = frozenset()
            self._gate_picker = None
            self._ring_gen += 1
            self.metrics.ring_generation.set(self._ring_gen)
            self.metrics.ring_ejected_peers.set(0)
        for departed in old.values():
            threading.Thread(target=departed.shutdown, daemon=True,
                             name="peer-shutdown").start()
        # The hot-set psum tier is pod-local: once any non-self peer
        # exists (hot routing turns off), hot keys must go back to
        # daemon-level ownership with their consumption intact.
        have_others = any(info.grpc_address != self._self_addr
                          for info in infos)
        if have_others:
            self._demote_all()
            # the mesh-GLOBAL tier is pod-local by the same rule
            self._mesh_demote_all()
        # Stateful re-sharding (beyond-reference, opt-in): the
        # reference resets re-homed keys (SURVEY.md §5.3); with the
        # flag on, rows whose ring owner moved are handed to the new
        # owner over the peer wire instead.
        if self.config.handover_on_reshard and have_others:
            with self._handover_gen_mu:
                self._handover_gen += 1
                gen = self._handover_gen
            threading.Thread(target=self._handover_moved_rows,
                             args=(old_picker, gen),
                             daemon=True, name="handover").start()

    @staticmethod
    def _uses_default_hash(picker) -> bool:
        """Hash-level routing is only valid on the default pipeline
        (table key hashes ARE mixed fnv1a64 of the identity string)."""
        from .hashing import mixed_fnv1a64

        pickers = (list(picker.regions.values())
                   if isinstance(picker, RegionPeerPicker) else [picker])
        return all(getattr(pk, "_hash", None) is mixed_fnv1a64
                   for pk in pickers)

    def _handover_moved_rows(self, old_picker, gen: int) -> None:
        """Send every live row that this daemon OWNED under the old
        ring and no longer owns to its new owner (UpdatePeerGlobals
        with the key_hash + eff_ms extension fields), then drop it
        locally.  Rows held only as GLOBAL/MULTI_REGION replicas (owned
        by another peer under the old ring too) stay put — handing a
        replica over would overwrite the owner's authoritative state.

        Best effort: delivery failure leaves the row in place (the new
        owner serves a fresh bucket — the reference's reset-on-rehome
        behavior).  ``gen`` guards against a second membership change
        mid-flight: a newer set_peers bumps the generation, this pass
        aborts before its next chunk, and the newer pass re-snapshots
        whatever is left.  Interim hits on the new owner between the
        picker swap and the upsert are overwritten — the same bounded
        window GLOBAL broadcasts already have."""
        # route by the health-gated ring: a handover triggered by an
        # ejection/readmit must target where requests actually go
        picker = self._routing_picker()
        if not self._uses_default_hash(picker) or (
                old_picker.peers()
                and not self._uses_default_hash(old_picker)):
            log.warning("handover_on_reshard requires the default "
                        "picker hash; skipping handover")
            return
        with self._handover_mu:  # one in-flight pass at a time
            with self._handover_gen_mu:
                if self._handover_gen != gen:
                    return  # superseded before it started
            with self._engine_mu:
                snap = self.engine.snapshot()
            keys = snap.get("key")
            if keys is None or not len(keys):
                return
            had_old = bool(old_picker.peers())
            moved: Dict[str, list] = {}
            peers_by_addr: Dict[str, PeerClient] = {}
            for i, k in enumerate(keys):
                try:
                    # only rows we OWNED may move (solo ⇒ we owned all)
                    if had_old and not self.is_self(
                            old_picker.get_by_hash(int(k))):
                        continue
                    p = picker.get_by_hash(int(k))
                except RuntimeError:
                    return  # picker emptied concurrently
                addr = p.info.grpc_address
                if addr != self._self_addr:
                    moved.setdefault(addr, []).append(i)
                    peers_by_addr[addr] = p
            if not moved:
                return
            limit = self.config.behaviors.global_batch_limit
            sent = 0
            for addr, idxs in moved.items():
                peer = peers_by_addr[addr]
                for a in range(0, len(idxs), limit):
                    with self._handover_gen_mu:
                        if self._handover_gen != gen:
                            log.info("handover superseded after %d rows",
                                     sent)
                            return
                    chunk = idxs[a:a + limit]
                    batch = []
                    for i in chunk:
                        meta = int(snap["meta"][i])
                        alg = meta & 1
                        eff = max(int(snap["eff_ms"][i]), 1)
                        batch.append(peers_pb.UpdatePeerGlobal(
                            key_hash=int(keys[i]), eff_ms=eff,
                            algorithm=alg,
                            duration=int(snap["duration"][i]),
                            created_at=int(snap["t_ms"][i]),
                            burst=int(snap["burst"][i]),
                            update=pb.RateLimitResp(
                                status=(meta >> 1) & 1,
                                limit=int(snap["limit"][i]),
                                # RAW internal value — for leaky that is
                                # td fixed point; the receiver detects
                                # eff_ms>0 and skips the rescale, so the
                                # transfer is lossless
                                remaining=int(snap["remaining"][i]),
                                reset_time=int(snap["expire_at"][i]))))
                    delivered = False
                    for attempt in range(3):
                        try:
                            peer.update_peer_globals(batch)
                            delivered = True
                            break
                        except Exception as e:  # noqa: BLE001
                            # a first RPC to a just-joined peer can
                            # exceed its deadline while that daemon
                            # compiles its upsert program; the upsert is
                            # idempotent, so retrying is safe.
                            # exc_text: a deadline error str()s empty
                            log.warning("handover to %s failed "
                                        "(attempt %d/3): %s", addr,
                                        attempt + 1, exc_text(e))
                            self.recorder.record_error(
                                "handover_error", e, peer=addr,
                                attempt=attempt + 1)
                            time.sleep(0.5 * (attempt + 1))
                    if not delivered:
                        continue  # row stays: reset-on-rehome fallback
                    with self._engine_mu:
                        self.engine.remove_rows(
                            np.asarray([int(keys[i]) for i in chunk],
                                       np.uint64))
                    sent += len(chunk)
            log.info("handover: moved %d rows to %d peers", sent,
                     len(moved))
            self.recorder.record("handover", rows=sent,
                                 peers=len(moved))

    @property
    def analytics(self):
        """The key-analytics subsystem (None when disabled).  Lives on
        the dispatcher so bench A/B detaches ONE reference and every
        tap — dispatcher waves and fused instance lanes — goes dark."""
        return self.dispatcher.analytics

    def _obs_phase(self, phase: str, seconds: float) -> None:
        """Phase attribution outside the dispatcher's waves (wire
        ingest, response build); no-op when analytics is off."""
        ana = self.dispatcher.analytics
        if ana is not None:
            ana.observe_phase(phase, seconds)

    def owner_addr_by_khash(self, khash: int) -> Optional[str]:
        """Owner peer address for a MIXED table key hash (the heavy-
        hitter ledger's key space) — /debug/topkeys' owner column.
        None when solo, on a custom picker hash (hash-level routing
        would be wrong there), or for an emptied ring."""
        with self._peer_mu:
            picker = self._picker
            if not picker.peers():
                return None
        if not self._uses_default_hash(picker):
            return None
        try:
            return picker.get_by_hash(int(khash)).info.grpc_address
        except RuntimeError:  # ring emptied concurrently
            return None

    def peers(self) -> List[PeerClient]:
        with self._peer_mu:
            return self._picker.peers()

    def owner_of(self, key: str) -> Optional[PeerClient]:
        with self._peer_mu:
            if not self._picker.peers():
                return None
            return self._picker.get(key)

    def default_hash_routing(self) -> bool:
        """True when the picker runs the default mixed_fnv1a64 pipeline,
        i.e. raw-khash owner lookups (owner_by_raw_khash) are valid."""
        with self._peer_mu:
            picker = self._picker
        return self._uses_default_hash(picker)

    def owner_by_raw_khash(self, khash_raw: int) -> Optional[PeerClient]:
        """Owner peer for a RAW (unmixed) FNV-1a64 key hash — the wire
        lanes' async-queue key space.  Callers gate on
        ``default_hash_routing()`` first."""
        with self._peer_mu:
            if not self._picker.peers():
                return None
            return self._picker.get_by_raw_hash(khash_raw)

    def is_self(self, peer: PeerClient) -> bool:
        return peer.info.grpc_address == self._self_addr

    # ---- health-gated routing ring (ISSUE 5) ---------------------------

    def _routing_picker(self):
        """The picker requests ROUTE by: the membership picker with
        long-unhealthy peers ejected (their keys deterministically
        rehome to the next ring point — exactly the picker that would
        exist without them) and readmitted after the hysteresis window.
        The membership picker itself stays authoritative for reconcile
        targets (owner_of / owner_by_raw_khash), so degraded hits always
        flush to the TRUE owner once it is reachable.

        Healthy cluster fast path: one lock + one health read per peer,
        returning the membership picker itself."""
        b = self.config.behaviors
        if not getattr(b, "peer_health_gate", True):
            with self._peer_mu:
                return self._picker
        eject_s = max(int(getattr(b, "peer_eject_after_ms", 3000)),
                      0) / 1e3
        readmit_s = max(int(getattr(b, "peer_readmit_after_ms", 3000)),
                        0) / 1e3
        with self._peer_mu:
            picker = self._picker
            peers = picker.peers()
            if not peers:
                return picker
            bad = frozenset(
                p.info.grpc_address for p in peers
                if not self.is_self(p) and hasattr(p, "route_healthy")
                and not p.route_healthy(eject_s, readmit_s))
            if len(bad) >= len(peers):
                # never empty the ring: with every peer unhealthy the
                # membership ring is the least-wrong answer
                bad = frozenset()
            if bad == self._gate_bad:
                return (self._gate_picker
                        if self._gate_picker is not None else picker)
            old_bad = self._gate_bad
            old_routing = (self._gate_picker
                           if self._gate_picker is not None else picker)
            gated = None
            if bad:
                gated = picker.new()
                for p in peers:
                    if p.info.grpc_address not in bad:
                        gated.add(p)
            self._gate_bad = bad
            self._gate_picker = gated
            self._ring_gen += 1
            gen = self._ring_gen
        # emission + probe/handover management OFF the lock
        self.metrics.ring_generation.set(gen)
        self.metrics.ring_ejected_peers.set(len(bad))
        for addr in sorted(bad - old_bad):
            log.warning("ring: peer %s EJECTED from routing (circuit "
                        "open > %.1fs); its keys rehome until readmit",
                        addr, eject_s)
            self.recorder.record("ring_ejected", peer=addr,
                                 generation=gen)
        for addr in sorted(old_bad - bad):
            log.info("ring: peer %s readmitted to routing "
                     "(recovered > %.1fs)", addr, readmit_s)
            self.recorder.record("ring_readmitted", peer=addr,
                                 generation=gen)
        if bad:
            self._ensure_probe_loop()
        if self.config.handover_on_reshard:
            # keys moved between live daemons: reuse the stateful
            # rehome machinery so consumption follows them (best
            # effort — an ejected target just keeps its rows)
            with self._handover_gen_mu:
                self._handover_gen += 1
                hgen = self._handover_gen
            threading.Thread(target=self._handover_moved_rows,
                             args=(old_routing, hgen),
                             daemon=True, name="handover-rehome").start()
        return gated if gated is not None else picker

    def _route_owner_of(self, key: str) -> Optional[PeerClient]:
        """owner_of through the health-gated ring (the forward path's
        view); reconcile/broadcast targets keep using owner_of."""
        picker = self._routing_picker()
        if not picker.peers():
            return None
        return picker.get(key)

    def _ensure_probe_loop(self) -> None:
        with self._gm_mu:
            if self._probe_loop is None and not self._closed:
                from .interval import IntervalLoop

                iv = max(int(getattr(self.config.behaviors,
                                     "peer_circuit_cooldown_ms", 2000)),
                         100)
                self._probe_loop = IntervalLoop(
                    iv, self._probe_ejected, name="ring-health-probe")

    def _probe_ejected(self) -> None:
        """Probe every EJECTED peer with one empty flush so a recovered
        peer's circuit can close (rehomed keys generate no organic
        traffic toward it).  Failures keep the circuit open — that is
        the point."""
        with self._peer_mu:
            bad = self._gate_bad
            peers = list(self._picker.peers())
        if not bad:
            return
        for p in peers:
            if p.info.grpc_address in bad and hasattr(p, "probe"):
                try:
                    p.probe()
                except Exception:  # noqa: BLE001 - probe is best-effort
                    pass

    def _ensure_global_manager(self) -> GlobalManager:
        with self._gm_mu:
            if self.global_manager is None:
                self.global_manager = GlobalManager(
                    self, self.config.behaviors, self.metrics)
            return self.global_manager

    def _ensure_mr_manager(self) -> MultiRegionManager:
        with self._gm_mu:
            if self.mr_manager is None:
                self.mr_manager = MultiRegionManager(
                    self, self.config.behaviors)
            return self.mr_manager

    def region_pickers(self) -> dict:
        """Per-datacenter pickers (region_picker.go); single-region
        deployments expose their one ring under their own name."""
        with self._peer_mu:
            if isinstance(self._picker, RegionPeerPicker):
                return dict(self._picker.regions)
            return {self.config.data_center: self._picker}

    # ---- the public API ------------------------------------------------

    def get_rate_limits(self, reqs: Sequence[RateLimitRequest],
                        now_ms: Optional[int] = None
                        ) -> List[RateLimitResponse]:
        """Batch entry point (gubernator.go › GetRateLimits): split by
        ownership, serve owned + GLOBAL keys in one device step, forward
        the rest to their owners (batched per peer)."""
        if len(reqs) > MAX_BATCH_SIZE:
            raise ValueError(
                f"Requests.RateLimits list too large; max size is "
                f"{MAX_BATCH_SIZE}")
        # overload admission (ISSUE 5): shed cheaply at ingest, before
        # any engine work (raises ResourceExhausted → RESOURCE_EXHAUSTED)
        self.dispatcher.admit(
            len(reqs), tenant_cb=lambda: self._tenant_of_reqs(reqs))
        now = clock_ms() if now_ms is None else now_ms  # clock-domain: caller
        self.metrics.getratelimit_counter.labels(calltype="api").inc(len(reqs))
        self.metrics.concurrent_checks.inc()
        try:
            with self.metrics.time_func("GetRateLimits"):
                return self._get_rate_limits(reqs, now)
        finally:
            self.metrics.concurrent_checks.dec()

    def get_rate_limits_wire(self, data: bytes,
                             now_ms: Optional[int] = None) -> bytes:
        """Wire-to-wire GetRateLimits: serialized GetRateLimitsReq in,
        serialized GetRateLimitsResp out.

        Takes the C++ columnar fast lane (ops/_native.cpp: wire bytes →
        packed arrays → one device step → wire bytes, zero per-request
        Python objects) when the batch qualifies: extension built, no
        Store hooks, no metadata, non-empty names/keys.  Solo (no peers
        beyond self): GLOBAL batches ride a columnar hot-set flow
        (pinned keys → replica step, the rest → sharded step +
        vectorized promotion counting).  Clustered: ALL batches ride
        the clustered columnar lane — non-GLOBAL rows are ring-split by
        owner (owned keys stepped locally, the rest forwarded as raw
        TLV slices over the peer wire and spliced back in order);
        GLOBAL rows are answered from the local replica with async
        reconcile queued as raw TLV prototypes (_wire_check_clustered).
        MULTI_REGION rows decided locally queue cross-region
        replication the same way (multiregion.queue_hits_raw, after
        the step).  Anything the lanes can't model falls back to the
        pb2 object path with identical semantics.  Raises ValueError
        on oversize batches (mirroring ``get_rate_limits``).
        """
        self._fault_point("wire_ingest")
        parsed = None
        is_global = False
        clustered = False
        if _wire_native is not None and self.store is None:
            peer_list = self.peers()
            if not peer_list or all(self.is_self(p) for p in peer_list):
                # solo fused lane: bytes → leased packed wave → device
                # → bytes in one C++ ingest pass (no parse/pack numpy
                # columns at all); returns None for anything it can't
                # model (GLOBAL/MR rows, Gregorian, pb2 framing,
                # busy-path gates) and the classic lanes below take
                # over with identical semantics
                out = self._wire_client_fused(data, now_ms)
                if out is not None:
                    return out
            t_ing = time.perf_counter()
            parsed = _wire_native.parse_get_rate_limits(data)
            if parsed is not None:
                self._obs_phase("ingest", time.perf_counter() - t_ing)
            if parsed is not None:
                is_global = bool(parsed["behavior_or"]
                                 & int(Behavior.GLOBAL))
                peer_list = self.peers()
                solo = not peer_list or all(
                    self.is_self(p) for p in peer_list)
                if not solo:
                    # clustered GLOBAL rides the same columnar lane:
                    # GLOBAL rows are answered from the local replica
                    # and their reconcile queues take raw TLV slices
                    # (global_manager.queue_*_raw), so no per-request
                    # objects are needed
                    clustered = True
                # solo GLOBAL rides the columnar hot-set flow; the
                # object path's queue_update is a no-op with no peers
                # (nothing to broadcast to)
        if parsed is not None:
            n = parsed["n"]
            if n > MAX_BATCH_SIZE:
                raise ValueError(
                    f"Requests.RateLimits list too large; max size is "
                    f"{MAX_BATCH_SIZE}")
            now = clock_ms() if now_ms is None else now_ms  # clock-domain: caller
            # all gating happens before metrics or state are touched:
            # a None runner falls through to the object path untouched
            if clustered:
                lane = "wire_clustered"
                runner = lambda: self._wire_check_clustered(  # noqa: E731
                    parsed, data, now)
            else:
                # MULTI_REGION rows decided locally replicate
                # cross-region asynchronously; GLOBAL takes precedence
                # (the object path never MR-queues a GLOBAL row).
                # Solo: every row is local.  (The clustered lane
                # derives its own owned-rows mask.)  behavior_or gates
                # the column scans: MR-free traffic pays nothing.
                if parsed["behavior_or"] & int(Behavior.MULTI_REGION):
                    mr_mask = ((parsed["behavior"]
                                & int(Behavior.MULTI_REGION)) != 0) & \
                        ((parsed["behavior"]
                          & int(Behavior.GLOBAL)) == 0)
                else:
                    mr_mask = _NO_ROWS
                if is_global:
                    lane = "wire_hotset"
                    inner = self._wire_global_runner(parsed, now)
                else:
                    lane = "wire_local"
                    inner = lambda: self._wire_check_columns(  # noqa: E731
                        parsed, now)
                if inner is not None and mr_mask.any():
                    def runner(inner=inner):
                        out = inner()
                        # after the step: rows exist, replicate async
                        self._queue_mr_raw(parsed, data, mr_mask,
                                           stamp_ms=now)
                        return out
                else:
                    runner = inner
            if runner is not None:
                self.dispatcher.admit(
                    n, tenant_cb=lambda: self._tenant_of_wire(data))
                ana = self.dispatcher.analytics
                if ana is not None:
                    # tenant learn tap: khash_raw rides zero-copy; the
                    # worker skips the TLV parse once every key is known
                    ana.tap_wire_names(data, parsed["khash_raw"],
                                       raw=True)
                self.metrics.getratelimit_counter.labels(
                    calltype="api").inc(n)
                self.metrics.wire_lane_counter.labels(lane=lane).inc(n)
                self.metrics.concurrent_checks.inc()
                try:
                    with self.metrics.time_func("GetRateLimits"):
                        out_bytes = runner()
                        self._maybe_sweep(now)
                        return out_bytes
                finally:
                    self.metrics.concurrent_checks.dec()
        # pb2 object path: everything the columnar lanes can't model
        from google.protobuf.message import DecodeError

        from .wire import req_from_pb, resp_to_pb

        try:
            msg = pb.GetRateLimitsReq.FromString(data)
        except DecodeError as e:
            # surfaced as INVALID_ARGUMENT by the servicer, matching
            # what a grpc-layer deserializer failure produced before
            # the raw-bytes handler existed
            raise ValueError(f"invalid GetRateLimitsReq: {e}") from e
        reqs = [req_from_pb(m) for m in msg.requests]
        self.metrics.wire_lane_counter.labels(
            lane="pb2_fallback").inc(len(reqs))
        resps = self.get_rate_limits(reqs, now_ms=now_ms)
        out = pb.GetRateLimitsResp()
        out.responses.extend(resp_to_pb(r) for r in resps)
        return out.SerializeToString()

    # ---- fused wire lane (ops/_native.cpp › pack_wire_wave) ------------

    #: behaviors whose async side effects (hot-set routing, GLOBAL
    #: reconcile queues, cross-region replication) need the parsed
    #: columns — the fused lane hands them to the classic lanes
    _FUSED_EXCLUDED = Behavior.GLOBAL | Behavior.MULTI_REGION

    def _wire_client_fused(self, data: bytes,
                           now_ms: Optional[int]) -> Optional[bytes]:
        """Solo client twin of ``_wire_peer_fused``: the GetRateLimits
        front door when this daemon owns every key.  Returns None when
        the fused lane can't serve the batch (caller falls back)."""
        prepack = getattr(self.engine, "prepack_wire", None)
        if prepack is None:
            return None
        now = clock_ms() if now_ms is None else now_ms  # clock-domain: caller
        t_ing = time.perf_counter()
        pre = prepack(data, now)
        if pre is None:
            return None
        self._obs_phase("ingest", time.perf_counter() - t_ing)
        if pre.behavior_or & int(self._FUSED_EXCLUDED):
            # GLOBAL rides the hot-set flow, MULTI_REGION queues async
            # replication — both need the parsed columns; the classic
            # lanes keep those semantics in one place
            pre.lease.release()
            return None
        if pre.n > MAX_BATCH_SIZE:
            pre.lease.release()
            raise ValueError(
                f"Requests.RateLimits list too large; max size is "
                f"{MAX_BATCH_SIZE}")
        try:
            self.dispatcher.admit(
                pre.n, tenant_cb=lambda: self._tenant_of_wire(data))
        except BaseException:
            pre.lease.release()
            raise
        ana = self.dispatcher.analytics
        if ana is not None:
            ana.tap_wire_names(data, pre.khash)
        self.metrics.getratelimit_counter.labels(calltype="api").inc(
            pre.n)
        self.metrics.wire_lane_counter.labels(lane="wire_local").inc(
            pre.n)
        self.metrics.concurrent_checks.inc()
        try:
            with self.metrics.time_func("GetRateLimits"):
                out = self._run_fused(pre, now)
                self._maybe_sweep(now)
                return out
        finally:
            self.metrics.concurrent_checks.dec()

    def _wire_peer_fused(self, data: bytes,
                         now_ms: Optional[int]) -> Optional[bytes]:
        """Fused owner side of the forward hop: received TLV bytes go
        straight into a leased packed wave (C++ parse+clamp+hash+fill,
        zero numpy column passes) and responses serialize from the
        wave's result columns — a forwarded batch costs the same as a
        local wire call.  None → classic lane (GLOBAL/MR rows whose
        async queues need parsed columns, Gregorian, pb2 framing)."""
        prepack = getattr(self.engine, "prepack_wire", None)
        if prepack is None:
            return None
        now = clock_ms() if now_ms is None else now_ms  # clock-domain: caller
        t_ing = time.perf_counter()
        pre = prepack(data, now)
        if pre is None:
            return None
        self._obs_phase("ingest", time.perf_counter() - t_ing)
        if pre.behavior_or & int(self._FUSED_EXCLUDED):
            pre.lease.release()
            return None
        if pre.n > self.config.behaviors.batch_limit:
            pre.lease.release()
            raise ValueError(
                "'PeerRequest.rate_limits' list too large; max size is "
                f"{self.config.behaviors.batch_limit}")
        ana = self.dispatcher.analytics
        if ana is not None:
            ana.tap_wire_names(data, pre.khash)
        self.metrics.getratelimit_counter.labels(calltype="peer").inc(
            pre.n)
        self.metrics.wire_lane_counter.labels(lane="peer_wire").inc(
            pre.n)
        return self._run_fused(pre, now)

    def _run_fused(self, pre, now: int) -> bytes:
        """Execute a prepacked wave and serialize its responses.  Idle:
        one inline wave in this thread (block order == request order,
        so results serialize straight from the engine columns).  Busy:
        the lease's rows rebuild into a RequestBatch and ride the
        normal coalescing submit path."""
        disp = self.dispatcher
        eng = self.engine
        n = pre.n
        ana = disp.analytics
        # the hits column lives in the LEASED matrices, which the next
        # wave reuses once check_prepacked releases them — snapshot it
        # up front when the tap will need it (khash is lease-free).
        # Fused engines (ISSUE 8) emit the tap ON DEVICE inside the
        # wave — this host copy is exactly what the fusion deletes.
        hits_tap = (np.array(pre.lease.a64[1][:n])
                    if ana is not None and not disp._fused_tap
                    else None)
        out = disp.run_inline_wave(
            "inline_wire", n, lambda: eng.check_prepacked(pre, now))
        if out is not disp._BUSY:
            status, lim, rem, rst, full = out
            self.metrics.over_limit_counter.inc(
                int((status == 1).sum()))
            errors = None
            if full.any():
                errors = [None] * n
                for i in np.nonzero(full)[0]:
                    errors[int(i)] = "rate limit table full"
                    if ana is not None:
                        ana.tap_flag("errors", 1,
                                     khash=int(pre.khash[int(i)]))
            t_b = time.perf_counter()
            resp = _wire_native.build_responses_from_columns(
                (status, lim, rem, rst, full), 0, n, errors)
            self._obs_phase("build", time.perf_counter() - t_b)
            if ana is not None:
                disp._tap_packed(pre.khash, hits_tap, status)
            return resp
        # contended: copy the rows out of the lease (the queued job
        # outlives it) and coalesce with the other callers' waves
        from .core.batch import RequestBatch

        a64, a32 = pre.lease.a64, pre.lease.a32
        batch = RequestBatch(
            key=a64[0][:n].astype(np.int64).view(np.uint64),
            hits=a64[1][:n].copy(), limit=a64[2][:n].copy(),
            duration=a64[3][:n].copy(), eff_ms=a64[4][:n].copy(),
            greg_end=a64[5][:n].copy(), behavior=a32[0][:n].copy(),
            algorithm=a32[1][:n].copy(), burst=a64[6][:n].copy(),
            valid=a32[2][:n] != 0, now=a64[7][:n].copy())
        kh = pre.khash
        pre.lease.release()
        view = disp.check_packed_view(batch, kh, now)
        status = view.cols[0][view.lo:view.hi]
        full = view.cols[4][view.lo:view.hi]
        self.metrics.over_limit_counter.inc(int((status == 1).sum()))
        errors = None
        if full.any():
            errors = [None] * n
            for i in np.nonzero(full)[0]:
                errors[int(i)] = "rate limit table full"
                if ana is not None:
                    ana.tap_flag("errors", 1, khash=int(kh[int(i)]))
        t_b = time.perf_counter()
        resp = _wire_native.build_responses_from_columns(
            view.cols, view.lo, view.hi, errors)
        self._obs_phase("build", time.perf_counter() - t_b)
        return resp

    # ---- tenant attribution helpers (ISSUE 11) -------------------------

    def _tenant_of_reqs(self, reqs) -> Optional[str]:
        """Shed-attribution hint for the object lane.  Only invoked on
        the exceptional path (admission rejected the batch), so the
        per-call cost never touches admitted traffic."""
        ana = self.dispatcher.analytics
        if ana is None or not reqs:
            return None
        try:
            return ana.tenant_hint(name=reqs[0].name)
        except Exception:
            return None

    def _tenant_of_wire(self, data: bytes) -> Optional[str]:
        """Shed-attribution hint for the wire lanes: tolerant
        pure-Python TLV walk to the first request's name.  Like
        ``_tenant_of_reqs`` this only runs when a shed actually fires;
        admitted wire batches never pay for it."""
        ana = self.dispatcher.analytics
        if ana is None:
            return None
        try:
            from .analytics import iter_wire_names

            pairs = iter_wire_names(data)
            if not pairs:
                return None
            return ana.tenant_hint(name=pairs[0][0])
        except Exception:
            return None

    def get_peer_rate_limits_wire(self, data: bytes,
                                  now_ms: Optional[int] = None) -> bytes:
        """Wire-to-wire GetPeerRateLimits — the owner side of request
        forwarding (peers.proto uses the same RateLimitReq/RateLimitResp
        submessages on field 1, so the C++ codec applies verbatim).
        Forwarded batches always apply locally, so peer membership does
        not gate the fast lane.  GLOBAL rows mark their keys changed
        for the next broadcast tick (queue_update_raw — this is the
        owner applying reconciled hits) and MULTI_REGION rows queue
        cross-region replication (queue_hits_raw), both AFTER the step,
        aggregated per unique key with raw TLV prototypes — the
        columnar twins of the per-request queueing the object path
        does."""
        self._fault_point("wire_ingest")
        parsed = None
        # rehome-target duty (ISSUE 5): while OUR health gate has peers
        # ejected, a forwarded row whose membership owner is ejected is
        # a rehomed row another daemon routed here — it must serve
        # DEGRADED (flag + reconcile queue), which needs parsed columns;
        # healthy gate (the steady state) costs one attribute read
        gate_rehome = bool(self._gate_bad) and getattr(
            self.config.behaviors, "peer_degraded_fallback", True)
        if _wire_native is not None and self.store is None:
            if not gate_rehome:
                out = self._wire_peer_fused(data, now_ms)
                if out is not None:
                    return out
            t_ing = time.perf_counter()
            parsed = _wire_native.parse_get_rate_limits(data)
            if parsed is not None:
                self._obs_phase("ingest", time.perf_counter() - t_ing)
        if parsed is None:
            from google.protobuf.message import DecodeError

            from .wire import req_from_pb, resp_to_pb

            try:
                msg = peers_pb.GetPeerRateLimitsReq.FromString(data)
            except DecodeError as e:
                raise ValueError(
                    f"invalid GetPeerRateLimitsReq: {e}") from e
            reqs = [req_from_pb(m) for m in msg.requests]
            self.metrics.wire_lane_counter.labels(
                lane="peer_pb2_fallback").inc(len(reqs))
            resps = self.get_peer_rate_limits(reqs, now_ms=now_ms)
            out = peers_pb.GetPeerRateLimitsResp()
            out.rate_limits.extend(resp_to_pb(r) for r in resps)
            return out.SerializeToString()
        if parsed["n"] > self.config.behaviors.batch_limit:
            raise ValueError(
                "'PeerRequest.rate_limits' list too large; max size is "
                f"{self.config.behaviors.batch_limit}")
        now = clock_ms() if now_ms is None else now_ms  # clock-domain: owner
        self.metrics.getratelimit_counter.labels(calltype="peer").inc(
            parsed["n"])
        self.metrics.wire_lane_counter.labels(lane="peer_wire").inc(
            parsed["n"])
        out = self._wire_check_columns(parsed, now)
        # behavior_or gates the column scans: plain forwarded traffic
        # pays nothing here
        if parsed["behavior_or"] & int(Behavior.GLOBAL):
            glob = (parsed["behavior"] & int(Behavior.GLOBAL)) != 0
            self._queue_global_updates_raw(parsed, data, glob)
        # NO GLOBAL precedence here: the object path's peer handler
        # queues BOTH for a GLOBAL|MULTI_REGION row (two independent
        # per-request ifs), unlike the client path
        if parsed["behavior_or"] & int(Behavior.MULTI_REGION):
            mr = (parsed["behavior"]
                  & int(Behavior.MULTI_REGION)) != 0
            # clock-ok: first-hop-wins — stamp_ms only fills rows missing a created_at TLV; stamped rows keep the caller's time base
            self._queue_mr_raw(parsed, data, mr, stamp_ms=now)
        if gate_rehome:
            # clock-ok: first-hop-wins fallback, same as _queue_mr_raw above
            out = self._peer_degraded_rewrite(parsed, data, out,
                                              stamp_ms=now)
        return out

    def _peer_degraded_rewrite(self, parsed: dict, data: bytes,
                               out: bytes,
                               stamp_ms: Optional[int] = None) -> bytes:
        """Rehome-target side of degraded mode (ISSUE 5): a forwarded
        row whose MEMBERSHIP owner is ejected from our health gate was
        routed here by another daemon's gated ring.  Its local apply
        (already done by the caller) is a DEGRADED serve: flag the
        response row and queue the hits for reconcile to the true
        owner, exactly like a rehomed row on the client path — without
        this, hits forwarded to a rehome target would be silently
        absorbed into its shard and conservation would break.  Only
        runs while our gate has ejected peers (``gate_rehome``)."""
        bad = self._gate_bad
        with self._peer_mu:
            mpick = self._picker
        if not bad or not mpick.peers() \
                or not self._uses_default_hash(mpick):
            return out
        peers_l = mpick.owner_peers()
        bad_pi = [pi for pi, p in enumerate(peers_l)
                  if p.info.grpc_address in bad]
        if not bad_pi:
            return out
        from .hashing import mix64_np

        raw = mix64_np(parsed["khash_raw"])
        owners = mpick.owner_indices(raw)
        # GLOBAL rows excluded alongside the state-mutating behaviors:
        # as acting owner we queue their broadcast state already —
        # degrading them too would double-queue the hits
        mask = (np.isin(owners, bad_pi)
                & ((parsed["behavior"]
                    & int(self._DEGRADED_EXCLUDED
                          | Behavior.GLOBAL)) == 0))
        if not mask.any():
            return out
        gm = self._ensure_global_manager()
        for k, tlv, a, _i in self._raw_queue_groups(parsed, data, mask,
                                                    stamp_ms=stamp_ms):
            gm.queue_hits_raw(k, tlv, a, degraded=True)
        # flag the masked rows: re-serialize just those items with the
        # degraded metadata (pb2 — metadata has no C++ lane; this path
        # only runs mid-outage)
        ro, rl, _rs = _wire_native.split_resp_items(out)
        items: List[bytes] = []
        by_addr: Dict[str, int] = {}
        for j in range(parsed["n"]):
            tlv = out[int(ro[j]):int(ro[j] + rl[j])]
            if mask[j]:
                m = pb.GetRateLimitsResp.FromString(tlv)
                r = m.responses[0]
                if not r.error:
                    addr = peers_l[int(owners[j])].info.grpc_address
                    r.metadata["degraded"] = "true"
                    r.metadata["degraded_peer"] = addr
                    by_addr[addr] = by_addr.get(addr, 0) + 1
                    tlv = m.SerializeToString()
            items.append(tlv)
        for addr, cnt in by_addr.items():
            self.metrics.degraded_served.labels(peer_addr=addr).inc(cnt)
        if by_addr:
            rows = sum(by_addr.values())
            ana = self.dispatcher.analytics
            tenant = None
            if ana is not None:
                kh0 = int(raw[mask][0])
                tenant = ana.tenant_hint(khash=kh0)
                ana.tap_flag("degraded", rows, khash=kh0)
            from .tracing import current_span_id, force_sample

            force_sample("degraded")
            ev = {"peer": min(by_addr), "rows": rows, "rehomed": True}
            if tenant is not None:
                ev["tenant"] = tenant
            sid = current_span_id()
            if sid is not None:
                ev["span_id"] = sid
            self.recorder.record("degraded", **ev)
        return b"".join(items)

    @staticmethod
    def _raw_queue_groups(parsed: dict, data: bytes, mask: np.ndarray,
                          stamp_ms: Optional[int] = None):
        """(khash, last-occurrence TLV, summed hits, last row index)
        per unique masked key — the shared aggregation for the raw
        async queues (LAST occurrence: a mid-batch config change must
        win, matching the object-path producers).

        ``stamp_ms`` stamps ``created_at`` (field 10) onto yielded TLVs
        that don't already carry one: hit-queue prototypes apply at the
        owner LATER (flush/reconcile cadence), and applying them at the
        owner's then-clock on a row living on the request's time base
        reads as expired → bucket reset → the reconciled hits silently
        vanish (the cold-key conservation loss, reconcile edition)."""
        idx = np.nonzero(mask)[0]
        if not idx.size:
            return
        from .wire import tlv_with_created

        toff, tlen = parsed["tlv_off"], parsed["tlv_len"]
        created = parsed["created_at"]
        w = np.maximum(parsed["hits"][idx], 0)
        uniq, inv = np.unique(parsed["khash_raw"][idx],
                              return_inverse=True)
        # exact int64 accumulation (bincount's float64 weights would
        # round sums past 2^53 — the object-path producers are exact
        # Python ints, and conservation must match across lanes)
        acc = np.zeros(uniq.size, np.int64)
        np.add.at(acc, inv, w)
        last = np.zeros(uniq.size, np.int64)
        last[inv] = np.arange(inv.size)
        stamping = _created_at_fwd_enabled()
        for k, f, a in zip(uniq, last, acc):
            i = int(idx[int(f)])
            tlv = bytes(data[int(toff[i]):int(toff[i] + tlen[i])])
            if stamping and stamp_ms is not None \
                    and not int(created[i]):
                tlv = tlv_with_created(tlv, stamp_ms)
            yield (int(k), tlv, int(a), i)

    def _queue_mr_raw(self, parsed: dict, data: bytes,
                      mask: np.ndarray,
                      stamp_ms: Optional[int] = None) -> None:
        """Queue cross-region replication for locally-decided
        MULTI_REGION rows, zero per-request objects (the wire-lane twin
        of the object path's mr.queue_hits calls)."""
        mr = self._ensure_mr_manager()
        for k, tlv, a, _i in self._raw_queue_groups(parsed, data, mask,
                                                    stamp_ms=stamp_ms):
            mr.queue_hits_raw(k, tlv, a)

    def _queue_global_updates_raw(self, parsed: dict, data: bytes,
                                  mask: np.ndarray) -> None:
        """Owner side of forwarded GLOBAL rows: mark each unique key
        changed for the next broadcast tick (queue_update_raw), as
        get_peer_rate_limits does per request on the object path."""
        gm = self._ensure_global_manager()
        # clock-ok: broadcast marking only — queue_update_raw records WHICH keys changed, applies no hits, needs no created_at stamp
        for k, tlv, _a, _i in self._raw_queue_groups(parsed, data, mask):
            gm.queue_update_raw(k, tlv)

    def _wire_global_runner(self, parsed: dict, now: int):
        """Columnar solo-GLOBAL flow (the wire-lane twin of
        ``_hot_route``): pinned keys take the replicated hot-set step,
        everything else the sharded step, with vectorized promotion
        counting.  Returns a zero-argument executor, or None when a
        per-request case needs the object path (a pinned key whose
        config changed or that received excluded flags — those demote).

        All gating runs here, before any state mutation, so a None
        return leaves the instance untouched for the fallback.
        """
        if self._global_mode == "mesh":
            # mesh backend (ISSUE 7): qualifying rows ride the mesh
            # tier; degraded/stood-down (or anything the columnar
            # mesh runner can't model) serves owner-sharded — always
            # correct, reconciled by the gRPC queues
            if self._mesh_routable():
                runner = self._wire_mesh_runner(parsed, now)
                if runner is not None:
                    return runner
                return None  # pinned-key demote case: object path
            return lambda: self._wire_check_columns(parsed, now)
        if self.config.hot_set_capacity <= 0:
            # tier disabled: solo GLOBAL is just the local path (the
            # object path's queue_update broadcasts to no one)
            return lambda: self._wire_check_columns(parsed, now)
        from .core.batch import pack_columns
        from .hashing import mix64_np

        n = parsed["n"]
        kh = mix64_np(parsed["khash_raw"])
        kh = np.where(kh == 0, np.uint64(1), kh)
        batch, errs = pack_columns(
            kh, parsed["hits"], parsed["limit"], parsed["duration"],
            parsed["algorithm"], parsed["behavior"], parsed["burst"], now,
            created_at=parsed.get("created_at"))
        beh = np.asarray(batch.behavior)
        glob_mask = (beh & int(Behavior.GLOBAL)) != 0
        excluded = (beh & int(self._HOT_EXCLUDED)) != 0
        hs = self._hotset
        hot_mask = np.zeros(n, bool)
        if hs is not None and hs.slots:
            with hs._mu:
                pinned_keys = np.fromiter(hs.slots.keys(), np.uint64,
                                          len(hs.slots))
            pinned_mask = glob_mask & np.isin(kh, pinned_keys)
            if pinned_mask.any():
                if (pinned_mask & excluded).any():
                    return None  # flagged request on a pinned key
                # config match, vectorized over the few unique hot keys
                # (duration compares unfloored, exactly as clamp_config
                # and pack_columns store it)
                alg = np.asarray(batch.algorithm)
                lim = np.asarray(batch.limit)
                dur = np.asarray(batch.duration)
                bur = np.asarray(batch.burst)
                for k in np.unique(kh[pinned_mask]):
                    cfg = hs.pinned_cfg.get(int(k))
                    m = pinned_mask & (kh == k)
                    if cfg is None or not (
                            (alg[m] == cfg[0]).all()
                            and (lim[m] == cfg[1]).all()
                            and (dur[m] == cfg[2]).all()
                            and (bur[m] == cfg[3]).all()):
                        return None  # config changed → demote path
                hot_mask = pinned_mask
        # promotion counting for unpinned qualifying GLOBAL keys
        promo_mask = glob_mask & ~hot_mask & ~excluded & \
            np.asarray(batch.valid)

        def run() -> bytes:
            status = np.zeros(n, np.int64)
            rem = np.zeros(n, np.int64)
            rst = np.zeros(n, np.int64)
            lim_o = np.zeros(n, np.int64)
            errors: Optional[list] = None
            if promo_mask.any():
                pidx = np.nonzero(promo_mask)[0]
                w = np.maximum(np.asarray(batch.hits)[pidx], 1)
                uniq, first, inv = np.unique(
                    kh[pidx], return_index=True, return_inverse=True)
                weights = np.bincount(inv, weights=w).astype(np.int64)
                hits_col = np.asarray(batch.hits)
                for k, f, wt in zip(uniq, first, weights):
                    i = int(pidx[f])  # first occurrence in the batch
                    self._count_toward_promotion(
                        int(k), int(wt), RateLimitRequest(
                            name="", unique_key="",
                            hits=int(hits_col[i]),
                            limit=int(np.asarray(batch.limit)[i]),
                            duration=int(np.asarray(batch.duration)[i]),
                            algorithm=int(np.asarray(
                                batch.algorithm)[i]),
                            behavior=int(beh[i]),
                            burst=int(np.asarray(batch.burst)[i])))
            shard_mask = ~hot_mask
            if shard_mask.any():
                idx = np.nonzero(shard_mask)[0]
                sub = type(batch)(*[np.asarray(c)[idx] for c in batch])
                s_st, s_lim, s_rem, s_rst, s_full = \
                    self.dispatcher.check_packed(sub, kh[idx], now)
                status[idx] = s_st
                lim_o[idx] = s_lim
                rem[idx] = s_rem
                rst[idx] = s_rst
                if s_full.any():
                    errors = [None] * n
                    for j in np.nonzero(s_full)[0]:
                        errors[int(idx[j])] = "rate limit table full"
            if hot_mask.any():
                idx = np.nonzero(hot_mask)[0]
                sub = type(batch)(*[np.asarray(c)[idx] for c in batch])
                h_st, h_rem, h_rst, h_lim, h_lost = hs.check_columns(
                    sub, kh[idx], now)
                status[idx] = h_st
                rem[idx] = h_rem
                rst[idx] = h_rst
                lim_o[idx] = h_lim
                if h_lost.any():
                    errors = errors or [None] * n
                    for j in np.nonzero(h_lost)[0]:
                        errors[int(idx[j])] = "hot-set row lost"
            if errs:
                errors = errors or [None] * n
                for i, emsg in errs.items():
                    errors[i] = emsg
            self.metrics.over_limit_counter.inc(int((status == 1).sum()))
            if self._promote_pending:
                self._drain_promotions(now)
            return _wire_native.build_rate_limit_resps(
                status, lim_o, rem, rst, errors)

        return run

    def _wire_mesh_runner(self, parsed: dict, now: int):
        """Columnar mesh-GLOBAL flow (ISSUE 7; the wire-lane twin of
        ``_mesh_route``): qualifying GLOBAL rows serve on the
        mesh-resident replica tier — pinned on first touch, in ONE
        batched upload — everything else rides the sharded step.
        Returns a zero-argument executor, or None when a pinned key's
        config changed (the object path demotes it with state intact,
        exactly the hot set's fallback contract)."""
        from .core.batch import pack_columns
        from .hashing import mix64_np

        n = parsed["n"]
        kh = mix64_np(parsed["khash_raw"])
        kh = np.where(kh == 0, np.uint64(1), kh)
        batch, errs = pack_columns(
            kh, parsed["hits"], parsed["limit"], parsed["duration"],
            parsed["algorithm"], parsed["behavior"], parsed["burst"],
            now, created_at=parsed.get("created_at"))
        beh = np.asarray(batch.behavior)
        glob_mask = (beh & int(Behavior.GLOBAL)) != 0
        excluded = (beh & int(self._HOT_EXCLUDED)) != 0
        mesh_mask = glob_mask & ~excluded & np.asarray(batch.valid)
        mge = self._ensure_meshglobal()
        if mesh_mask.any():
            alg = np.asarray(batch.algorithm)
            lim = np.asarray(batch.limit)
            dur = np.asarray(batch.duration)
            bur = np.asarray(batch.burst)
            hits_col = np.asarray(batch.hits)
            pins: List[tuple] = []
            for k in np.unique(kh[mesh_mask]):
                ik = int(k)
                m = mesh_mask & (kh == k)
                i = int(np.nonzero(m)[0][0])
                # one config per key per batch (pinned OR to-pin): a
                # mid-batch config change takes the object path, which
                # demotes/serves it per request with exact semantics
                if not ((alg[m] == alg[i]).all()
                        and (lim[m] == lim[i]).all()
                        and (dur[m] == dur[i]).all()
                        and (bur[m] == bur[i]).all()):
                    return None
                proto = RateLimitRequest(
                    name="", unique_key="", hits=int(hits_col[i]),
                    limit=int(lim[i]), duration=int(dur[i]),
                    algorithm=int(alg[i]), behavior=int(beh[i]),
                    burst=int(bur[i]))
                if mge.is_pinned(ik):
                    if not mge.matches_pinned(ik, proto):
                        return None  # config changed → demote path
                else:
                    pins.append((proto, ik, self._seed_row(ik)))
            if pins:
                ok = mge.pin_many(pins, now)
                for (proto, ik, _s), good in zip(pins, ok):
                    if good:
                        self._seed_commit(ik)
                    elif not self._mesh_admit(proto, ik, now):
                        # window full, nothing colder → sharded path
                        mesh_mask = mesh_mask & (kh != np.uint64(ik))

        # Fused single-launch path (ISSUE 8): a fused engine serves the
        # WHOLE batch — mesh rows on the home replica + accumulator,
        # sharded rows on the serving kernel — in ONE device program,
        # deleting the second (meshglobal.check_columns) dispatch this
        # runner otherwise pays per batch.  The mslot column carries
        # each mesh row's pinned replica slot; -1 = sharded lane.
        mslot_col = None
        if getattr(self.engine, "mesh_bound", False) and mesh_mask.any():
            mslot_col = np.full(n, -1, np.int32)
            with mge._mu:
                smap = dict(mge.slots)
            for k in np.unique(kh[mesh_mask]):
                s = smap.get(int(k))
                if s is not None:
                    mslot_col[mesh_mask & (kh == k)] = s
                else:  # unpinned underneath us: sharded path is correct
                    mesh_mask = mesh_mask & (kh != k)
            if not (mslot_col >= 0).any():
                mslot_col = None

        def run_fused() -> bytes:
            st, lim_o, rem, rst, full = self.dispatcher.check_packed(
                batch, kh, now, mslot=mslot_col)
            errors: Optional[list] = None
            if full.any():
                errors = [None] * n
                for j in np.nonzero(full)[0]:
                    errors[int(j)] = ("mesh-global row lost"
                                      if mslot_col[int(j)] >= 0
                                      else "rate limit table full")
            if errs:
                errors = errors or [None] * n
                for i, emsg in errs.items():
                    errors[i] = emsg
            self.metrics.over_limit_counter.inc(int((st == 1).sum()))
            return _wire_native.build_rate_limit_resps(
                np.asarray(st, np.int64), lim_o, rem, rst, errors)

        if mslot_col is not None:
            return run_fused

        def run() -> bytes:
            status = np.zeros(n, np.int64)
            rem = np.zeros(n, np.int64)
            rst = np.zeros(n, np.int64)
            lim_o = np.zeros(n, np.int64)
            errors: Optional[list] = None
            shard_mask = ~mesh_mask
            if shard_mask.any():
                idx = np.nonzero(shard_mask)[0]
                sub = type(batch)(*[np.asarray(c)[idx] for c in batch])
                s_st, s_lim, s_rem, s_rst, s_full = \
                    self.dispatcher.check_packed(sub, kh[idx], now)
                status[idx] = s_st
                lim_o[idx] = s_lim
                rem[idx] = s_rem
                rst[idx] = s_rst
                if s_full.any():
                    errors = [None] * n
                    for j in np.nonzero(s_full)[0]:
                        errors[int(idx[j])] = "rate limit table full"
            if mesh_mask.any():
                idx = np.nonzero(mesh_mask)[0]
                sub = type(batch)(*[np.asarray(c)[idx] for c in batch])
                m_st, m_rem, m_rst, m_lim, m_lost = mge.check_columns(
                    sub, kh[idx], now)
                status[idx] = m_st
                rem[idx] = m_rem
                rst[idx] = m_rst
                lim_o[idx] = m_lim
                if m_lost.any():
                    errors = errors or [None] * n
                    for j in np.nonzero(m_lost)[0]:
                        errors[int(idx[j])] = "mesh-global row lost"
            if errs:
                errors = errors or [None] * n
                for i, emsg in errs.items():
                    errors[i] = emsg
            self.metrics.over_limit_counter.inc(int((status == 1).sum()))
            return _wire_native.build_rate_limit_resps(
                status, lim_o, rem, rst, errors)

        return run

    def _packed_check_to_bytes(self, kh: np.ndarray, hits, limit, duration,
                               algorithm, behavior, burst, now: int,
                               created=None) -> bytes:
        """Columns → pack → device step → response wire bytes: the
        shared fast-lane body (solo client wire, peer wire, and the
        clustered lane's local sub-batch all end here).  Resolves from
        the dispatcher's ResultView — row bounds into the wave's shared
        downloaded result columns — and serializes straight from them
        in THIS caller's thread (ops/_native.cpp ›
        build_responses_from_columns), so response build never runs on
        the dispatch worker and materializes no per-job column
        tuples."""
        from .core.batch import pack_columns

        batch, errs = pack_columns(kh, hits, limit, duration, algorithm,
                                   behavior, burst, now,
                                   created_at=created)
        view = self.dispatcher.check_packed_view(batch, kh, now)
        status = view.cols[0][view.lo:view.hi]
        full = view.cols[4][view.lo:view.hi]
        self.metrics.over_limit_counter.inc(int((status == 1).sum()))
        errors = None
        if errs or full.any():
            # errored rows already come back zeroed from the device
            # (invalid/overfull rows are masked out)
            errors = [None] * len(kh)
            for i, emsg in errs.items():
                errors[i] = emsg
            for i in np.nonzero(full)[0]:
                if errors[int(i)] is None:
                    errors[int(i)] = "rate limit table full"
        t_b = time.perf_counter()
        resp = _wire_native.build_responses_from_columns(
            view.cols, view.lo, view.hi, errors)
        self._obs_phase("build", time.perf_counter() - t_b)
        return resp

    def _wire_check_columns(self, parsed: dict, now: int) -> bytes:
        """Parsed wire columns → device step → serialized responses
        (identical for the client and peer wire)."""
        from .hashing import mix64_np

        kh = mix64_np(parsed["khash_raw"])
        kh = np.where(kh == 0, np.uint64(1), kh)
        return self._packed_check_to_bytes(
            kh, parsed["hits"], parsed["limit"], parsed["duration"],
            parsed["algorithm"], parsed["behavior"], parsed["burst"], now,
            created=parsed.get("created_at"))

    def _wire_check_clustered(self, parsed: dict, data: bytes, now: int
                              ) -> bytes:
        """Clustered wire fast lane (the cluster twin of
        ``_wire_check_columns``): C++ parse → batch hash → vectorized
        ring split by owner → forward each remote owner's sub-batch as
        verbatim request-TLV slices over the peer wire (framing is
        byte-compatible: GetRateLimitsReq.requests and
        GetPeerRateLimitsReq.requests are both field 1) → device step
        for owned keys, overlapped with the forward RPCs → splice
        response TLVs back together in request order.

        Zero per-request Python objects end to end; the owner side rides
        get_peer_rate_limits_wire's columnar lane.  A failed forward
        degrades to per-request error responses for that sub-batch only,
        mirroring the object path's per-request forward errors.

        GLOBAL rows (global.go semantics, SURVEY §3.3): answered from
        the LOCAL replica — never forwarded — with hits queued for async
        reconcile to the owner (raw TLV prototypes, aggregated per
        unique key; global_manager.queue_hits_raw/queue_update_raw)."""
        from .hashing import mix64_np

        n = parsed["n"]
        raw = mix64_np(parsed["khash_raw"])
        with self._peer_mu:
            membership = self._picker
        # route by the health-gated ring (ISSUE 5): long-dead owners
        # are ejected and their keys rehome; healthy clusters get the
        # membership picker itself back (pickers are immutable, so the
        # lookups below run lock-free)
        picker = self._routing_picker()
        peer_list = picker.owner_peers()
        # pre-zero-remap, matching picker.get(key)'s hash pipeline
        owners = picker.owner_indices(raw)
        kh = np.where(raw == 0, np.uint64(1), raw)
        toff, tlen = parsed["tlv_off"], parsed["tlv_len"]

        self_pi = [pi for pi, p in enumerate(peer_list) if self.is_self(p)]
        local_mask = np.isin(owners, self_pi)
        # rows rehomed to us by an ejection are DEGRADED serves: answer
        # locally, flag the response, and queue the hits for reconcile
        # to the membership owner — never silently authoritative
        deg_mask = _NO_ROWS
        m_owners = m_peers = None
        if (picker is not membership and membership.peers()
                and getattr(self.config.behaviors,
                            "peer_degraded_fallback", True)):
            m_peers = membership.owner_peers()
            m_owners = membership.owner_indices(raw)
            m_self = [pi for pi, p in enumerate(m_peers)
                      if self.is_self(p)]
            deg_mask = (local_mask & ~np.isin(m_owners, m_self)
                        & ((parsed["behavior"]
                            & int(self._DEGRADED_EXCLUDED)) == 0))
        # behavior_or gates the column scan: GLOBAL-free batches (the
        # common clustered shape) pay nothing here
        if parsed["behavior_or"] & int(Behavior.GLOBAL):
            glob_mask = (parsed["behavior"] & int(Behavior.GLOBAL)) != 0
        else:
            glob_mask = _NO_ROWS
        glob_queue: List[tuple] = []
        if glob_mask.any():
            # every GLOBAL row is served locally; collect the reconcile
            # work per UNIQUE key (hot keys repeat, so this loop is
            # short even for big batches).  The owner-side queue_update
            # entries are ENQUEUED ONLY AFTER the local step below: a
            # broadcast tick firing in between would gather a row that
            # doesn't exist yet and silently drop the update (observed
            # as a cold-compile-window flake).
            # shared aggregation (_raw_queue_groups): unmixed-khash
            # queue keys — the same key space as the peer-wire
            # producers — with last-occurrence TLV prototypes
            for k, tlv, a, i in self._raw_queue_groups(
                    parsed, data, glob_mask, stamp_ms=now):
                glob_queue.append(
                    (k, tlv, a, int(owners[i]) in self_pi))
            local_mask = local_mask | glob_mask
            if deg_mask.size:
                # GLOBAL rows already answer from the replica with
                # their own reconcile queues — degrading them too would
                # double-queue the hits
                deg_mask = deg_mask & ~glob_mask
        item_tlvs: List[Optional[bytes]] = [None] * n

        # fire remote forwards first so the local device step overlaps:
        # each owner's sub-batch enters its peer's pooled send buffer
        # (peer_client.py › forward_raw) — concurrent callers
        # forwarding to the same owner share flush RPCs, with depth-K
        # in flight; a dead peer fails fast via ErrCircuitOpen instead
        # of queuing every caller behind its timeouts.  The TLV slices
        # join through ONE memoryview (no per-slice bytes copies).
        created = parsed["created_at"]
        groups = []
        for pi in np.unique(owners[~local_mask]):
            # ~local_mask also excludes GLOBAL rows that share an owner
            # with forwarded rows: they were answered locally above and
            # reconcile asynchronously — forwarding them too would
            # double-debit the owner
            idxs = np.nonzero((owners == pi) & ~local_mask)[0]
            # stamp OUR accepted-at clock (field 10) onto each slice
            # that doesn't already carry one: the owner applies the
            # rows at this caller's time base instead of its own wall
            # clock — mixing bases resets cold bucket rows and loses
            # their debits (types.RateLimitRequest.created_at)
            if _created_at_fwd_enabled():
                sub = _wire_native.stamp_req_tlvs(
                    data, toff[idxs], tlen[idxs], created[idxs], now)
            else:  # pre-fix behavior (racer/regression demos only)
                sub = b"".join(
                    bytes(data[int(toff[i]):int(toff[i] + tlen[i])])
                    for i in idxs)
            fut = send_err = None
            try:
                fut = peer_list[int(pi)].forward_raw(sub, int(idxs.size))
            except Exception as e:  # noqa: BLE001 - incl. ErrClosing /
                # ErrCircuitOpen (fail-fast: degraded local answers —
                # or per-request error rows — for this sub-batch only)
                send_err = e
            groups.append((idxs, fut, send_err,
                           peer_list[int(pi)].info.grpc_address))

        over = 0  # remote OVER_LIMITs (the local step counts its own)
        # rehomed rows serve DEGRADED (flag + reconcile queue), apart
        # from the normal local step
        if deg_mask.size and deg_mask.any():
            for pi in np.unique(m_owners[deg_mask]):
                didx = np.nonzero(deg_mask & (m_owners == pi))[0]
                addr = m_peers[int(pi)].info.grpc_address
                try:
                    tlvs = self._serve_degraded_wire(
                        parsed, data, didx, kh, now, addr)
                    for j, i in enumerate(didx):
                        item_tlvs[int(i)] = tlvs[j]
                except Exception as e:  # noqa: BLE001 - degraded serve
                    # must never take the whole batch down
                    log.warning("degraded serve for %d rehomed rows "
                                "(owner %s) failed: %s", didx.size,
                                addr, exc_text(e))
            local_mask = local_mask & ~deg_mask
        local_idx = np.nonzero(local_mask)[0]
        if local_idx.size:
            lbytes = self._packed_check_to_bytes(
                kh[local_idx], parsed["hits"][local_idx],
                parsed["limit"][local_idx], parsed["duration"][local_idx],
                parsed["algorithm"][local_idx],
                parsed["behavior"][local_idx],
                parsed["burst"][local_idx], now,
                created=created[local_idx])
            lo, ll, _ = _wire_native.split_resp_items(lbytes)
            for j, i in enumerate(local_idx):
                item_tlvs[int(i)] = lbytes[int(lo[j]):int(lo[j] + ll[j])]
        if glob_queue:
            # rows exist now (the step above wrote them): safe to queue
            # owner-side updates for the next broadcast tick
            gm = self._ensure_global_manager()
            for k, tlv, a, own in glob_queue:
                if own:
                    gm.queue_update_raw(k, tlv)
                else:
                    gm.queue_hits_raw(k, tlv, a)
        # locally-OWNED MULTI_REGION rows replicate cross-region async
        # (forwarded MR rows are queued by their owner; GLOBAL rows
        # never MR-queue — object-path precedence).  behavior_or-gated
        # like the GLOBAL scan above.
        if parsed["behavior_or"] & int(Behavior.MULTI_REGION):
            not_glob = ~glob_mask if glob_mask.size else True
            mr_mask = (np.isin(owners, self_pi) & not_glob
                       & ((parsed["behavior"]
                           & int(Behavior.MULTI_REGION)) != 0))
            if mr_mask.any():
                self._queue_mr_raw(parsed, data, mr_mask,
                                   stamp_ms=now)

        # lane futures always resolve (RPC deadline + bounded retries +
        # explicit failure paths); the wait bound below is that worst
        # case plus slack, a belt against a lane bug parking a caller
        b = self.config.behaviors
        fwd_wait = ((b.peer_retry_limit + 1)
                    * (b.batch_timeout_ms / 1000.0 + 60.0)
                    + b.peer_retry_limit * b.peer_retry_backoff_ms
                    / 1000.0 + 5.0)
        for idxs, fut, send_err, addr in groups:
            rbytes, err, sp = None, send_err, None
            if fut is not None:
                try:
                    rbytes = fut.result(timeout=fwd_wait)
                except Exception as e:  # noqa: BLE001
                    err = e
            if rbytes is not None:
                sp = _wire_native.split_resp_items(rbytes)
                if sp is None or sp[0].size != idxs.size:
                    err = RuntimeError(
                        "malformed or short peer response batch")
                    sp = None
            if sp is None:
                self.metrics.check_error_counter.labels(
                    error="peer_forward").inc(int(idxs.size))
                self.metrics.forward_failed.labels(
                    peer_addr=addr,
                    reason=_forward_fail_reason(err)).inc(int(idxs.size))
                served = self._degrade_failed_forward(
                    parsed, data, idxs, kh, now, addr, item_tlvs)
                rest = idxs[~served]
                if rest.size:
                    z32 = np.zeros(rest.size, np.int32)
                    z64 = np.zeros(rest.size, np.int64)
                    # exc_text: a grpc deadline/TimeoutError str()s
                    # empty — the row must stay diagnosable; the peer
                    # address attributes WHICH owner failed
                    ebytes = _wire_native.build_rate_limit_resps(
                        z32, z64, z64, z64,
                        [f"while fetching rate limit from peer {addr}: "
                         f"{exc_text(err)}"] * int(rest.size))
                    eo, el, _ = _wire_native.split_resp_items(ebytes)
                    for j, i in enumerate(rest):
                        item_tlvs[int(i)] = \
                            ebytes[int(eo[j]):int(eo[j] + el[j])]
                continue
            ro, rl, rs = sp
            over += int((rs == 1).sum())
            for j, i in enumerate(idxs):
                item_tlvs[int(i)] = rbytes[int(ro[j]):int(ro[j] + rl[j])]

        self.metrics.over_limit_counter.inc(over)
        if any(t is None for t in item_tlvs):
            # belt: a failed degraded serve must still answer its rows
            miss = [i for i, t in enumerate(item_tlvs) if t is None]
            z32 = np.zeros(len(miss), np.int32)
            z64 = np.zeros(len(miss), np.int64)
            ebytes = _wire_native.build_rate_limit_resps(
                z32, z64, z64, z64,
                ["degraded-mode serve failed"] * len(miss))
            eo, el, _ = _wire_native.split_resp_items(ebytes)
            for j, i in enumerate(miss):
                item_tlvs[i] = ebytes[int(eo[j]):int(eo[j] + el[j])]
        return b"".join(item_tlvs)

    # ---- degraded-mode owner fallback (ISSUE 5) ------------------------

    #: behaviors that must NOT be served from a non-authoritative row:
    #: RESET/DRAIN mutate state the reconcile queue cannot carry, and
    #: MULTI_REGION replication must originate from the region owner
    _DEGRADED_EXCLUDED = (Behavior.RESET_REMAINING
                          | Behavior.DRAIN_OVER_LIMIT
                          | Behavior.MULTI_REGION)

    def _serve_degraded_wire(self, parsed: dict, data: bytes,
                             idxs: np.ndarray, kh: np.ndarray, now: int,
                             peer_addr: str) -> List[bytes]:
        """Answer ``idxs`` from the LOCAL shard in degraded mode: one
        device step over the sub-batch, responses flagged with
        ``metadata.degraded`` (pb2-built — the C++ response builder has
        no metadata lane, and degraded serving is off the happy path by
        definition), and the hits queued per unique key into the GLOBAL
        hit-flush queues for reconcile to the owner — bounded staleness
        instead of unavailability.  Returns one response TLV per row of
        ``idxs``."""
        from .core.batch import pack_columns
        from .wire import _varint

        m = int(idxs.size)
        batch, errs = pack_columns(
            kh[idxs], parsed["hits"][idxs], parsed["limit"][idxs],
            parsed["duration"][idxs], parsed["algorithm"][idxs],
            parsed["behavior"][idxs], parsed["burst"][idxs], now,
            created_at=parsed["created_at"][idxs])
        view = self.dispatcher.check_packed_view(batch, kh[idxs], now)
        st, lim, rem, rst, full = view.sliced()
        self.metrics.over_limit_counter.inc(int((st == 1).sum()))
        out: List[bytes] = []
        for j in range(m):
            msg = pb.RateLimitResp(
                status=int(st[j]), limit=int(lim[j]),
                remaining=int(rem[j]), reset_time=int(rst[j]))
            if errs and j in errs:
                msg.error = errs[j]
            elif bool(full[j]):
                msg.error = "rate limit table full"
            else:
                msg.metadata["degraded"] = "true"
                msg.metadata["degraded_peer"] = peer_addr
            payload = msg.SerializeToString()
            out.append(b"\x0a" + _varint(len(payload)) + payload)
        # reconcile-on-recovery: aggregate this sub-batch's hits per
        # unique key into the raw hit queue (the owner applies them
        # once reachable; failed flushes requeue — global_manager.py)
        mask = np.zeros(parsed["n"], bool)
        mask[idxs] = True
        gm = self._ensure_global_manager()
        for k, tlv, a, _i in self._raw_queue_groups(parsed, data, mask,
                                                    stamp_ms=now):
            gm.queue_hits_raw(k, tlv, a, degraded=True)
        self.metrics.degraded_served.labels(peer_addr=peer_addr).inc(m)
        ana = self.dispatcher.analytics
        tenant = None
        if ana is not None and idxs.size:
            kh0 = int(kh[idxs][0])
            tenant = ana.tenant_hint(khash=kh0)
            ana.tap_flag("degraded", m, khash=kh0)
        from .tracing import current_span_id, force_sample

        force_sample("degraded")
        ev = {"peer": peer_addr, "rows": m}
        if tenant is not None:
            ev["tenant"] = tenant
        sid = current_span_id()
        if sid is not None:
            ev["span_id"] = sid
        self.recorder.record("degraded", **ev)
        return out

    def _degrade_failed_forward(self, parsed: dict, data: bytes,
                                idxs: np.ndarray, kh: np.ndarray,
                                now: int, addr: str,
                                item_tlvs: List[Optional[bytes]]
                                ) -> np.ndarray:
        """Failed-forward fallback: serve the eligible rows of a failed
        sub-batch degraded (writes into ``item_tlvs``); returns the
        boolean mask (aligned with ``idxs``) of rows served.  Rows with
        excluded behaviors — or everything, when the fallback is
        disabled — stay unserved for the caller's error rows."""
        served = np.zeros(int(idxs.size), bool)
        if not getattr(self.config.behaviors,
                       "peer_degraded_fallback", True):
            return served
        elig = (parsed["behavior"][idxs]
                & int(self._DEGRADED_EXCLUDED)) == 0
        if not elig.any():
            return served
        sub = idxs[elig]
        try:
            tlvs = self._serve_degraded_wire(parsed, data, sub, kh,
                                             now, addr)
        except Exception as e:  # noqa: BLE001 - fall back to error rows
            log.warning("degraded serve for %d rows (owner %s) "
                        "failed: %s", sub.size, addr, exc_text(e))
            return served
        for j, i in enumerate(sub):
            item_tlvs[int(i)] = tlvs[j]
        served[elig] = True
        return served

    @staticmethod
    def _req_stamped(req: RateLimitRequest, now: int) -> RateLimitRequest:
        """The request with ``created_at`` defaulted to its serving
        time base — REQUIRED before queueing it for deferred hit
        application (GLOBAL reconcile, cross-region replication): the
        flush applies at the owner later, and without the stamp the
        owner's then-clock reads a row living on the request's base as
        expired → bucket reset → the deferred hits silently vanish."""
        if req.created_at or not _created_at_fwd_enabled():
            return req
        return replace(req, created_at=now)

    def _get_rate_limits(self, reqs, now) -> List[RateLimitResponse]:
        n = len(reqs)
        responses: List[Optional[RateLimitResponse]] = [None] * n
        local_idx: List[int] = []
        hot: List[tuple[int, int]] = []  # (request idx, key hash)
        meshl: List[tuple[int, int]] = []  # mesh-GLOBAL (idx, key hash)
        solo = None  # lazily: are we the only daemon (hot tier eligible)?
        fwd: List[tuple[int, PeerClient, RateLimitRequest]] = []

        have_peers = bool(self.peers())
        glob_q: List[tuple] = []  # (req, we_are_owner), queued post-step
        # routing picker hoisted out of the hot loop (health-gated
        # ring, ISSUE 5); membership picker alongside so rehomed rows
        # are recognized as DEGRADED serves, not silently authoritative
        rpick = self._routing_picker() if have_peers else None
        with self._peer_mu:
            mpick = self._picker
        gate_active = have_peers and rpick is not mpick
        deg_local: List[tuple] = []  # (idx, membership owner addr)
        # hot loop: plain-int flag tests (IntFlag.__and__ costs ~µs each
        # and this loop runs per request)
        GLOBAL = int(Behavior.GLOBAL)
        MULTI_REGION = int(Behavior.MULTI_REGION)
        NO_BATCHING = int(Behavior.NO_BATCHING)
        DEGRADED_EXCL = int(self._DEGRADED_EXCLUDED)
        for i, req in enumerate(reqs):
            if not req.unique_key:
                responses[i] = RateLimitResponse(
                    error="field 'unique_key' cannot be empty")
                continue
            if not req.name:
                responses[i] = RateLimitResponse(
                    error="field 'name' cannot be empty")
                continue
            behavior = int(req.behavior)
            if behavior & GLOBAL:
                # Pod-local hot keys take the psum tier: replica-local
                # decision, consumption folded by one collective per
                # sync tick (parallel/hotset.py) — no queues at all.
                # "Pod-local" = no peers other than ourselves.
                if solo is None:
                    solo = not have_peers or all(
                        self.is_self(p) for p in self.peers())
                if solo and self._global_mode == "mesh":
                    # mesh backend (ISSUE 7): ALL qualifying GLOBAL
                    # keys ride the mesh-resident replica tier; the
                    # hot set stays out of the picture (two replica
                    # tiers for one key would double-count).  A False
                    # return (excluded flags, window full, degraded
                    # stand-down) takes the owner-sharded path below.
                    if self._mesh_routable() and \
                            self._mesh_route(req, meshl, i, now):
                        continue
                elif solo and self._hot_route(req, hot, i):
                    continue
                # Otherwise: answer from the local replica now, reconcile
                # hits to the owner asynchronously (global.go semantics).
                # Owner-side queue_update is deferred until AFTER the
                # local step below — a broadcast tick firing first would
                # gather a not-yet-written row and drop the update.
                local_idx.append(i)
                owner = self.owner_of(req.key) if have_peers else None
                glob_q.append(
                    (req, owner is None or self.is_self(owner)))
                continue
            if not have_peers:
                local_idx.append(i)
                if behavior & MULTI_REGION:
                    self._ensure_mr_manager().queue_hits(
                        self._req_stamped(req, now))
                continue
            try:
                owner = rpick.get(req.key) if rpick.peers() else None
            except RuntimeError:
                owner = None
            if owner is None or self.is_self(owner):
                local_idx.append(i)
                if gate_active and not (behavior & DEGRADED_EXCL):
                    # rehomed to us by an ejection? serve DEGRADED:
                    # flag the response and reconcile the hits to the
                    # membership owner once it is back
                    try:
                        mowner = (mpick.get(req.key)
                                  if mpick.peers() else None)
                    except RuntimeError:
                        mowner = None
                    if mowner is not None and not self.is_self(mowner):
                        deg_local.append((i, mowner.info.grpc_address))
                # local-region owner replicates cross-DC asynchronously
                if behavior & MULTI_REGION:
                    self._ensure_mr_manager().queue_hits(
                        self._req_stamped(req, now))
            else:
                fwd.append((i, owner, req))

        # forwards first (async futures), so the device step overlaps RPCs
        futures: List[tuple] = []
        for i, peer, req in fwd:
            if not req.created_at and _created_at_fwd_enabled():
                # stamp OUR accepted-at clock so the owner applies the
                # request at this caller's time base (first hop wins;
                # rides the TLV as field 10 — wire.req_to_tlv)
                req = replace(req, created_at=now)
            if int(req.behavior) & NO_BATCHING:
                f: Future = Future()

                def _go(peer=peer, req=req, f=f):
                    try:
                        f.set_result(peer.get_peer_rate_limit(req))
                    except Exception as e:  # noqa: BLE001
                        f.set_exception(e)

                threading.Thread(target=_go, daemon=True,
                                 name="peer-forward-nobatch").start()
            else:
                try:
                    f = peer.enqueue(req)
                except Exception as e:  # noqa: BLE001 - incl. ErrClosing
                    f = Future()
                    f.set_exception(e)
            futures.append((i, f, peer.info.grpc_address, req))

        if meshl:
            m_reqs = [reqs[i] for i, _ in meshl]
            m_resps = self._meshglobal.check_batch(
                m_reqs, [h for _, h in meshl], now)
            for (i, _), resp in zip(meshl, m_resps):
                responses[i] = resp
                if resp.status == Status.OVER_LIMIT:
                    self.metrics.over_limit_counter.inc()
            # Store write-through covers mesh keys too (home-replica
            # values are exact; the fold converges the other replicas)
            self._after_local(m_reqs, m_resps)

        if hot:
            hot_reqs = [reqs[i] for i, _ in hot]
            hot_resps = self._hotset.check_batch(
                hot_reqs, [h for _, h in hot], now)
            for (i, _), resp in zip(hot, hot_resps):
                responses[i] = resp
                if resp.status == Status.OVER_LIMIT:
                    self.metrics.over_limit_counter.inc()
            # Store write-through covers hot keys too (replica-local
            # values; the post-sync merge supersedes them next tick)
            self._after_local(hot_reqs, hot_resps)

        if local_idx:
            local_reqs = [reqs[i] for i in local_idx]
            self._read_through(local_reqs)
            local_resps = self.dispatcher.check_batch(local_reqs, now)
            for i, resp in zip(local_idx, local_resps):
                responses[i] = resp
                if resp.status == Status.OVER_LIMIT:
                    self.metrics.over_limit_counter.inc()
            self._after_local(
                [reqs[i] for i in local_idx],
                [responses[i] for i in local_idx])
        if deg_local:
            gm = self._ensure_global_manager()
            for i, addr in deg_local:
                resp = responses[i]
                if resp is None or resp.error:
                    continue
                resp.metadata["degraded"] = "true"
                resp.metadata["degraded_peer"] = addr
                gm.queue_hits(self._req_stamped(reqs[i], now),
                              degraded=True)
                self.metrics.degraded_served.labels(
                    peer_addr=addr).inc()
        if glob_q:
            gm = self._ensure_global_manager()
            for req, own in glob_q:
                if own:
                    gm.queue_update(req)  # row written by the step above
                else:
                    gm.queue_hits(self._req_stamped(req, now))
        if self._promote_pending:
            self._drain_promotions(now)

        timeout = (self.config.behaviors.batch_timeout_ms
                   + self.config.behaviors.batch_wait_ms) / 1000.0 + 30.0
        deg_ok = getattr(self.config.behaviors,
                         "peer_degraded_fallback", True)
        deg_failed: List[tuple] = []  # (idx, req, owner addr)
        for i, f, addr, req in futures:
            try:
                responses[i] = f.result(timeout=timeout)
                if responses[i].status == Status.OVER_LIMIT:
                    self.metrics.over_limit_counter.inc()
            except Exception as e:  # noqa: BLE001
                self.metrics.check_error_counter.labels(
                    error="peer_forward").inc()
                self.metrics.forward_failed.labels(
                    peer_addr=addr,
                    reason=_forward_fail_reason(e)).inc()
                if deg_ok and not (int(req.behavior) & DEGRADED_EXCL):
                    deg_failed.append((i, req, addr))
                else:
                    responses[i] = RateLimitResponse(
                        error=f"while fetching rate limit from peer "
                              f"{addr}: {exc_text(e)}")
        if deg_failed:
            # degraded-mode owner fallback (ISSUE 5): answer the failed
            # forwards from the local shard, flag them, and reconcile
            # the hits through the GLOBAL hit-flush queues
            try:
                dresps = self.dispatcher.check_batch(
                    [req for _, req, _ in deg_failed], now)
                gm = self._ensure_global_manager()
                for (i, req, addr), resp in zip(deg_failed, dresps):
                    if not resp.error:
                        resp.metadata["degraded"] = "true"
                        resp.metadata["degraded_peer"] = addr
                        gm.queue_hits(
                            self._req_stamped(req, now), degraded=True)
                        self.metrics.degraded_served.labels(
                            peer_addr=addr).inc()
                        if resp.status == Status.OVER_LIMIT:
                            self.metrics.over_limit_counter.inc()
                    responses[i] = resp
                ana = self.dispatcher.analytics
                tenant = None
                if ana is not None:
                    tenant = ana.tenant_hint(
                        name=deg_failed[0][1].name)
                    ana.tap_flag("degraded", len(deg_failed),
                                 tenant=tenant)
                from .tracing import current_span_id, force_sample

                force_sample("degraded")
                ev = {"peer": deg_failed[0][2],
                      "rows": len(deg_failed)}
                if tenant is not None:
                    ev["tenant"] = tenant
                sid = current_span_id()
                if sid is not None:
                    ev["span_id"] = sid
                self.recorder.record("degraded", **ev)
            except Exception as e:  # noqa: BLE001 - degraded serve must
                # never take the batch down; fall back to error rows
                for i, req, addr in deg_failed:
                    if responses[i] is None:
                        responses[i] = RateLimitResponse(
                            error=f"while fetching rate limit from "
                                  f"peer {addr}: {exc_text(e)}")
        self._maybe_sweep(now)
        return responses  # type: ignore[return-value]

    # ---- hot-set (psum GLOBAL tier) ------------------------------------

    _HOT_EXCLUDED = (Behavior.RESET_REMAINING | Behavior.DRAIN_OVER_LIMIT
                     | Behavior.DURATION_IS_GREGORIAN | Behavior.MULTI_REGION)

    def _hot_route(self, req: RateLimitRequest, hot, i) -> bool:
        """Route a GLOBAL request to the replicated hot set if pinned;
        count toward promotion otherwise.  Returns True when routed."""
        if self.config.hot_set_capacity <= 0:
            return False
        # both algorithms qualify (hotset.py merges each natively); only
        # per-request flags that mutate config/state stay excluded
        qualifies = not int(req.behavior) & int(self._HOT_EXCLUDED)
        kh = hash_key(req.name, req.unique_key)
        hs = self._hotset
        if hs is not None and hs.is_pinned(kh):
            if not qualifies or not hs.matches_pinned(kh, req):
                # config changed or a flagged request (RESET/DRAIN/…)
                # arrived: migrate hot state back so the standard path
                # operates on the live values, not the promotion-time row.
                # Counted: one flagged request on a hot key silently
                # forfeits the psum tier for it — operators should see it
                self.metrics.hot_demotion_counter.labels(
                    reason="flagged" if not qualifies
                    else "config_change").inc()
                self._demote(kh)
                return False
            hot.append((i, kh))
            return True
        if not qualifies:
            return False
        self._count_toward_promotion(kh, max(int(req.hits), 1), req)
        return False

    def _count_toward_promotion(self, kh: int, weight: int,
                                req: RateLimitRequest) -> None:
        """Promotion bookkeeping, keyed by key hash (guarded: concurrent
        handlers must not double-promote or KeyError on the shared
        counter dict).  ``req`` carries the (limit, duration, algorithm,
        burst) the pin will adopt.

        The promotion SIGNAL is the Space-Saving heavy-hitter ledger
        (``/debug/topkeys``, analytics.py) when analytics is on — the
        PR-4 ROADMAP hook: the sketch sees every lane's resolved waves
        (including columnar wire traffic this counter never did), so a
        key hot through any path promotes.  The decayed ad-hoc counter
        stays as the floor: the sketch's paced async folds must never
        STARVE promotion (tap shedding under overload), only feed it."""
        ana = self.analytics
        with self._hot_mu:
            c = self._hot_counts.get(kh, 0) + weight
            self._hot_counts[kh] = c
            if ana is not None:
                # sketch count is an overestimate by ≤ its err bound —
                # promotion can only get more eager, never starved
                c = max(c, ana.sketch_count(kh))
            if c >= self.config.hot_promote_threshold:
                # promote AFTER this batch's device step so the seed
                # row includes this request's own hits
                self._promote_pending.append((req, kh))
                self._hot_counts.pop(kh, None)
            elif len(self._hot_counts) > 100_000:
                # decay inline too: _maybe_sweep may be disabled, and
                # the counter dict must stay bounded regardless
                self._decay_counts_locked()

    def _drain_promotions(self, now: int) -> None:
        """Pin newly-hot keys, seeding from their sharded-table rows so
        pre-promotion consumption carries over.  ``now`` is the batch's
        logical time — wall clock would break caller-driven time."""
        with self._hot_mu:
            pending, self._promote_pending = self._promote_pending, []
        for req, kh in pending:
            hs = self._ensure_hotset()
            # _seed_row also consults the cold tier: a key can be hot
            # by sketch rank while its row is still cold-resident
            if hs.pin(req, kh, now, seed=self._seed_row(kh)):
                self._seed_commit(kh)

    def _demote(self, key_hash: int) -> None:
        """Migrate one hot key's merged state back into the sharded
        table, then release its slot — consumption must survive the
        transition in both directions."""
        hs = self._hotset
        if hs is None:
            return
        hs.sync()  # fold all replicas so the row read is authoritative
        row = hs.row_state(key_hash)
        if row is not None:
            cols = {f: np.array([row[f]]) for f in row}
            with self._engine_mu:
                placed = self.engine.upsert_rows(
                    np.array([key_hash], np.uint64), cols)
                if not placed and self._tier is not None:
                    self._tier.put_row(key_hash,
                                       {f: int(row[f]) for f in row})
        hs.unpin(key_hash)

    def _demote_all(self) -> None:
        """Demote every hot key: ONE sync collective, one batched
        writeback (peer-join/shutdown latency must not scale with K
        collectives)."""
        hs = self._hotset
        if hs is None:
            return
        khs = list(hs.slots.keys())
        if not khs:
            return
        self.metrics.hot_demotion_counter.labels(
            reason="membership_change").inc(len(khs))
        hs.sync()
        rows = [(kh, hs.row_state(kh)) for kh in khs]
        rows = [(kh, r) for kh, r in rows if r is not None]
        if rows:
            karr = np.array([kh for kh, _ in rows], np.uint64)
            cols = {f: np.array([r[f] for _, r in rows])
                    for f in rows[0][1]}
            with self._engine_mu:
                placed = self.engine.upsert_rows(karr, cols)
                if placed < len(rows) and self._tier is not None:
                    found, _ = self.engine.gather_rows(karr)
                    for j, (kh, r) in enumerate(rows):
                        if not found[j]:
                            self._tier.put_row(
                                kh, {f: int(r[f]) for f in r})
        for kh in khs:
            hs.unpin(kh)

    # lock-free: caller holds self._hot_mu (the *_locked suffix contract)
    def _decay_counts_locked(self) -> None:
        """Halve promotion counters, drop zeros.  Caller holds _hot_mu."""
        self._hot_counts = {k: v // 2
                            for k, v in self._hot_counts.items()
                            if v // 2 > 0}

    def _hot_decay(self) -> None:
        """Counter decay on the sweep tick: bounds _hot_counts memory
        and ages out cold keys."""
        with self._hot_mu:
            self._decay_counts_locked()

    def _ensure_hotset(self):
        with self._gm_mu:
            if self._hotset is None:
                from .interval import IntervalLoop
                from .parallel.hotset import HotSetEngine

                cap = 1 << (self.config.hot_set_capacity - 1).bit_length()
                self._hotset = HotSetEngine(self.engine.mesh, capacity=cap)
                self._hot_sync_loop = IntervalLoop(
                    self.config.behaviors.global_sync_wait_ms,
                    self._hotset.sync, name="hotset-psum-sync")
                if self.memledger is not None:
                    self.memledger.enroll("hotset", self._probe_hotset,
                                          advisable=True)
            return self._hotset

    # ---- mesh-resident GLOBAL (ISSUE 7, parallel/meshglobal.py) --------

    def _mesh_mode(self) -> bool:
        """True when the mesh reconcile backend is selected AND the
        engine exposes a mesh (injected test engines may not)."""
        return (self._global_mode == "mesh"
                and getattr(self.engine, "mesh", None) is not None)

    def _mesh_routable(self) -> bool:
        """Mesh routing is pod-local (the hot set's rule: no non-self
        peers) and stands down while the fold is degraded — then the
        owner-sharded path + gRPC queues serve, which is always
        correct, just slower to cohere."""
        if not self._mesh_mode() or self._mesh_degraded:
            return False
        peers = self.peers()
        return not peers or all(self.is_self(p) for p in peers)

    def _ensure_meshglobal(self):
        with self._gm_mu:
            if self._meshglobal is None:
                from .parallel.meshglobal import MeshGlobalEngine

                raw = os.environ.get("GUBER_MESH_GLOBAL_CAP", "")
                try:
                    cap = int(raw) if raw else 4096
                except ValueError:
                    cap = 4096
                cap = 1 << max((cap - 1).bit_length(), 4)
                self._meshglobal = MeshGlobalEngine(
                    self.engine.mesh, capacity=cap,
                    batch_per_chip=self.config.batch_rows)
                if self.memledger is not None:
                    self.memledger.enroll("mesh_global",
                                          self._probe_meshglobal,
                                          advisable=True)
                # fused engines (ISSUE 8) fold the tier's home-replica
                # decide + accumulator scatter into the serving wave's
                # program — one launch per wave even in mesh mode.
                # Routing still gates on _mesh_routable(): a degraded
                # tier simply stops attaching mslot columns.
                if hasattr(self.engine, "bind_mesh"):
                    self.engine.bind_mesh(self._meshglobal)
            return self._meshglobal

    @staticmethod
    def _mesh_fallback_after() -> int:
        raw = os.environ.get("GUBER_MESH_FALLBACK_AFTER", "")
        try:
            return max(int(raw), 1) if raw else 3
        except ValueError:
            return 3

    def _seed_row(self, kh: int) -> Optional[dict]:
        """The key's sharded-table row, for pin seeding (promotion into
        the mesh tier must not forget hits already consumed).  With the
        tiered store the key may live in the COLD tier instead: seed
        from that row too.  Callers that pin successfully MUST follow
        with ``_seed_commit(kh)`` — a lingering cold copy would shadow
        the demoted row after the pin retires."""
        with self._engine_mu:
            found, cols = self.engine.gather_rows(
                np.array([kh], np.uint64))
            if not found[0] and self._tier is not None:
                cold = self._tier.peek_row(kh)
                if cold is not None:
                    return {f: cold[f]
                            for f in ("remaining", "t_ms", "expire_at",
                                      "meta")}
        if not found[0]:
            return None
        return {f: int(cols[f][0])
                for f in ("remaining", "t_ms", "expire_at", "meta")}

    def _seed_commit(self, kh: int) -> None:
        """Post-pin half of ``_seed_row``: the replica tier took
        ownership of the key's state, so drop the cold-tier copy (a
        no-op when the key wasn't cold-resident)."""
        if self._tier is not None:
            self._tier.pop_row(kh)

    def _mesh_route(self, req: RateLimitRequest, mesh_list, i,
                    now: int) -> bool:
        """Route a qualifying GLOBAL request to the mesh tier: pin on
        first touch (seeded from the sharded row), demote on config
        change or excluded flags.  Returns True when routed; False
        sends the request down the standard (owner-sharded) path."""
        qualifies = not int(req.behavior) & int(self._HOT_EXCLUDED)
        kh = hash_key(req.name, req.unique_key)
        mge = self._ensure_meshglobal()
        if mge.is_pinned(kh):
            if not qualifies or not mge.matches_pinned(kh, req):
                self._mesh_demote(kh)
                return False
            mesh_list.append((i, kh))
            return True
        if not qualifies:
            return False
        if not self._mesh_admit(req, kh, now):
            return False  # window full, nothing colder: sharded path
        mesh_list.append((i, kh))
        return True

    def _mesh_admit(self, req: RateLimitRequest, kh: int,
                    now: int) -> bool:
        """Pin ``kh`` into the mesh tier under the overflow admission
        policy: when the key's probe window is full, the coldest pinned
        occupant (by sketch rank) is demoted — through the exact
        stand-down migration path, so no hit is lost — and the pin
        retried, provided the newcomer ranks strictly hotter.  Cap
        overflow becomes a migration, not a silent fallback."""
        mge = self._ensure_meshglobal()
        if mge.pin(req, kh, now, seed=self._seed_row(kh)):
            self._seed_commit(kh)
            return True
        victim = self._mesh_overflow_victim(kh)
        if victim is None:
            return False
        self._mesh_demote(victim)
        self.recorder.record("mesh_overflow_demote", khash=victim,
                             admitted=kh)
        if not mge.pin(req, kh, now, seed=self._seed_row(kh)):
            return False  # window changed underneath us: sharded path
        self._seed_commit(kh)
        return True

    def _mesh_overflow_victim(self, kh: int) -> Optional[int]:
        """The coldest pinned occupant of ``kh``'s probe window, or
        None when the newcomer does not STRICTLY outrank anyone there
        (overflow then declines and the sharded/tiered path — always
        exact — keeps serving the key)."""
        if self.analytics is None or self._meshglobal is None:
            return None
        rank = self.analytics.sketch_count
        best = None
        best_rank = rank(kh)
        for k in self._meshglobal.probe_occupants(kh):
            if k == kh:
                continue
            r = rank(k)
            if r < best_rank:
                best, best_rank = k, r
        return best

    def _mesh_demote(self, key_hash: int) -> None:
        """Migrate one mesh key's HOME-replica row back into the
        sharded table (exact without any collective — home routing
        means only the home copy ever moved), then retire its slot."""
        mge = self._meshglobal
        if mge is None:
            return
        row = mge.row_state(key_hash)
        if row is not None:
            cols = {f: np.array([row[f]]) for f in row}
            with self._engine_mu:
                placed = self.engine.upsert_rows(
                    np.array([key_hash], np.uint64), cols)
                if not placed and self._tier is not None:
                    # device table full: the row lands in the cold tier
                    # instead of being silently dropped
                    self._tier.put_row(key_hash,
                                       {f: int(row[f]) for f in row})
        mge.unpin(key_hash)

    def _mesh_demote_all(self) -> None:
        """Demote every mesh-tier key in one batched writeback (peer
        join / stand-down / snapshot).  Exact: home-row reads need no
        collective, so this works even when the fold is the thing
        that broke."""
        mge = self._meshglobal
        if mge is None:
            return
        khs = mge.pinned_keys()
        if not khs:
            return
        rows = [(kh, mge.row_state(kh)) for kh in khs]
        rows = [(kh, r) for kh, r in rows if r is not None]
        if rows:
            karr = np.array([kh for kh, _ in rows], np.uint64)
            cols = {f: np.array([r[f] for _, r in rows])
                    for f in rows[0][1]}
            with self._engine_mu:
                placed = self.engine.upsert_rows(karr, cols)
                if placed < len(rows) and self._tier is not None:
                    # some rows found no device slot: adopt them into
                    # the cold tier (exact — nothing silently dropped)
                    found, _ = self.engine.gather_rows(karr)
                    for j, (kh, r) in enumerate(rows):
                        if not found[j]:
                            self._tier.put_row(
                                kh, {f: int(r[f]) for f in r})
        for kh in khs:
            mge.unpin(kh)

    def _mesh_reconcile_tick(self) -> None:
        """The GlobalManager mesh backend's tick: swap the accumulator
        double buffer, launch the reconcile collective, account
        staleness/generation, and run the degraded fallback.  Never
        raises (the hits loop must survive every failure mode)."""
        if not self._mesh_mode():
            return
        mge = self._meshglobal
        if mge is None:
            return
        t0 = time.perf_counter()
        retired = None
        try:
            self._fault_point("global_accum_swap")
            retired = mge.swap_accum()
            self._fault_point("global_psum")
            mge.fold(retired)
        except Exception as e:  # noqa: BLE001 - incl. FaultInjected
            if retired is not None:
                mge.swap_back()  # unfolded hits stay accumulating
            self.metrics.mesh_global_fold_errors.inc()
            self._mesh_fail_streak += 1
            log.warning("mesh-GLOBAL fold failed (streak %d): %s",
                        self._mesh_fail_streak, exc_text(e))
            if (self._mesh_fail_streak >= self._mesh_fallback_after()
                    and not self._mesh_degraded):
                self._mesh_stand_down()
            return
        dt = time.perf_counter() - t0
        self._mesh_fail_streak = 0
        self.metrics.mesh_global_folds.inc()
        self.metrics.mesh_global_staleness.set(mge.last_staleness_s)
        self.metrics.mesh_global_keys.set(len(mge.slots))
        # stamp the coherence epoch onto subsequent waves and attribute
        # the collective's time as its own phase (PhaseLedger)
        self.dispatcher.reconcile_gen = mge.generation
        self.dispatcher._obs_phase("global_fold", dt)
        self._mesh_last_fold_ok = time.monotonic()
        ana = self.dispatcher.analytics
        if ana is not None:
            # cost-model sample (ISSUE 11): the fold moves the
            # replicated value columns + accumulator across mge.n
            # devices — one (bytes, ndev, duration) observation
            ana.tap_cost("global_fold", mge.fold_nbytes, mge.n, dt)
        if (self._mesh_degraded
                and time.monotonic() >= self._mesh_down_until):
            # cooldown elapsed AND a clean fold: re-arm the tier
            self._mesh_degraded = False
            self.metrics.mesh_global_degraded.set(0)
            self.recorder.record("mesh_recovered",
                                 generation=mge.generation)

    def _mesh_stand_down(self) -> None:
        """Degraded fallback: demote every pinned key back to the
        owner-sharded path (exact) and route GLOBAL traffic the grpc
        way until the fold has recovered past the cooldown —
        bounded-staleness degradation, never unavailability."""
        cooldown = max(
            self.config.behaviors.global_sync_wait_ms, 100) * 10 / 1000.0
        self._mesh_down_until = time.monotonic() + cooldown
        self._mesh_degraded = True
        self.metrics.mesh_global_degraded.set(1)
        self.recorder.record("mesh_degraded",
                             streak=self._mesh_fail_streak,
                             cooldown_s=round(cooldown, 3))
        try:
            self._mesh_demote_all()
        except Exception:  # noqa: BLE001 - demotion is best-effort here
            log.exception("mesh-GLOBAL stand-down demotion")

    def _read_through(self, reqs) -> None:
        """Seed table misses from the write-through Store before the
        device step (store.go › Store.Get on cache miss).  One extra
        row-gather per batch, only when a Store is configured.

        The whole gather→get→upsert sequence holds the engine lock: a
        concurrent request inserting the same key between our miss and
        our overwrite-upsert would otherwise have its hits erased by the
        stale store copy."""
        if self.store is None or not reqs:
            return
        from .hashing import hash_request_keys
        from .store import arrays_from_items

        khash = hash_request_keys([r.name for r in reqs],
                                  [r.unique_key for r in reqs])
        with self._engine_mu:
            found, _ = self.engine.gather_rows(khash)
            items = []
            for j, req in enumerate(reqs):
                if found[j]:
                    continue
                item = self.store.get(req)
                if item is not None:
                    if not item.key and not item.key_hash:
                        item.key = req.key
                    items.append(item)
            if items:
                arrays = arrays_from_items(items)
                self.engine.upsert_rows(arrays.pop("key"), arrays)

    def _after_local(self, reqs, resps) -> None:
        """Post-step hooks: Store write-through for mutated keys."""
        if self.store is None:
            return
        for req, resp in zip(reqs, resps):
            if resp.error:
                continue
            self.store.on_change(req, CacheItem(
                key=req.key, algorithm=int(req.algorithm),
                limit=resp.limit, duration=int(req.duration),
                remaining=resp.remaining, expire_at=resp.reset_time,
                status=int(resp.status)))

    def _maybe_sweep(self, now: int) -> None:
        iv = self.config.sweep_interval_ms
        if iv > 0 and now - self._last_sweep >= iv:
            self._last_sweep = now
            with self._engine_mu:
                self.engine.sweep(now)
            self._hot_decay()

    # ---- peer service (owner side) -------------------------------------

    def get_peer_rate_limits(self, reqs: Sequence[RateLimitRequest],
                             now_ms: Optional[int] = None
                             ) -> List[RateLimitResponse]:
        """Apply a forwarded batch locally (gubernator.go ›
        GetPeerRateLimits).  GLOBAL keys get queued for broadcast."""
        if len(reqs) > self.config.behaviors.batch_limit:
            raise ValueError(
                "'PeerRequest.rate_limits' list too large; max size is "
                f"{self.config.behaviors.batch_limit}")
        now = clock_ms() if now_ms is None else now_ms  # clock-domain: owner
        self.metrics.getratelimit_counter.labels(calltype="peer").inc(len(reqs))
        reqs = list(reqs)
        self._read_through(reqs)
        resps = self.dispatcher.check_batch(reqs, now)
        gm = None
        for req in reqs:
            if req.behavior & Behavior.GLOBAL:
                gm = gm or self._ensure_global_manager()
                gm.queue_update(req)
            if req.behavior & Behavior.MULTI_REGION:
                # we are the local-region owner for this forwarded key
                self._ensure_mr_manager().queue_hits(
                    self._req_stamped(req, now))
        # rehome-target duty (ISSUE 5, object-path twin of
        # _peer_degraded_rewrite): rows whose membership owner is
        # ejected from OUR gate were rehomed here — flag + reconcile
        if self._gate_bad and getattr(self.config.behaviors,
                                      "peer_degraded_fallback", True):
            self._peer_degraded_objects(reqs, resps, now)
        self._after_local(reqs, resps)
        return resps

    def _peer_degraded_objects(self, reqs, resps, now: int) -> None:
        bad = self._gate_bad
        with self._peer_mu:
            mpick = self._picker
        if not bad or not mpick.peers():
            return
        gm = None
        excl = int(self._DEGRADED_EXCLUDED | Behavior.GLOBAL)
        for req, resp in zip(reqs, resps):
            if resp.error or (int(req.behavior) & excl):
                continue
            try:
                owner = mpick.get(req.key)
            except RuntimeError:
                return
            addr = owner.info.grpc_address
            if addr not in bad or self.is_self(owner):
                continue
            resp.metadata["degraded"] = "true"
            resp.metadata["degraded_peer"] = addr
            gm = gm or self._ensure_global_manager()
            gm.queue_hits(self._req_stamped(req, now),
                          degraded=True)
            self.metrics.degraded_served.labels(peer_addr=addr).inc()

    # ---- GLOBAL broadcast plumbing -------------------------------------

    def build_global_updates(self, reqs: Sequence[RateLimitRequest]
                             ) -> List[peers_pb.UpdatePeerGlobal]:
        """Owner side: read authoritative rows for changed GLOBAL keys
        and serialize them for UpdatePeerGlobals."""
        from .hashing import hash_request_keys

        khash = hash_request_keys([r.name for r in reqs],
                                  [r.unique_key for r in reqs])
        with self._engine_mu:
            found, cols = self.engine.gather_rows(khash)
        out: List[peers_pb.UpdatePeerGlobal] = []
        for j, req in enumerate(reqs):
            if not found[j]:
                continue
            meta = int(cols["meta"][j])
            alg = meta & 1
            eff = int(cols["eff_ms"][j])
            rem = int(cols["remaining"][j])
            if alg == int(Algorithm.LEAKY_BUCKET):
                rem_out = rem // max(eff, 1)
                reset = int(cols["t_ms"][j]) + (
                    eff // max(int(cols["limit"][j]), 1))
            else:
                rem_out = rem
                reset = int(cols["expire_at"][j])
            out.append(peers_pb.UpdatePeerGlobal(
                key=req.key,
                update=pb.RateLimitResp(
                    status=(meta >> 1) & 1, limit=int(cols["limit"][j]),
                    remaining=rem_out, reset_time=reset),
                algorithm=alg, duration=int(cols["duration"][j]),
                created_at=int(cols["t_ms"][j]),
                behavior=int(req.behavior), burst=int(cols["burst"][j])))
        return out

    def update_peer_globals(self, updates: Sequence[peers_pb.UpdatePeerGlobal]
                            ) -> None:
        """Replica side: overwrite local rows with the owner's
        authoritative state (gubernator.go › UpdatePeerGlobals)."""
        m = len(updates)
        if m == 0:
            return
        from .hashing import hash_keys

        # identity = hash(name + "_" + unique_key) and g.key IS that
        # joined string — one native batch hash instead of m scalar
        # ones.  Handover senders only hold the hash and send it in the
        # extension field (peers.proto › key_hash); it takes precedence.
        khash = hash_keys([g.key for g in updates])
        sent_kh = np.fromiter((g.key_hash for g in updates), np.uint64, m)
        khash = np.where(sent_kh != 0, sent_kh, khash)
        cols = {
            "meta": np.zeros(m, np.int32),
            "limit": np.zeros(m, np.int64),
            "duration": np.zeros(m, np.int64),
            "eff_ms": np.ones(m, np.int64),
            "burst": np.zeros(m, np.int64),
            "remaining": np.zeros(m, np.int64),
            "t_ms": np.zeros(m, np.int64),
            "expire_at": np.zeros(m, np.int64),
        }
        for j, g in enumerate(updates):
            alg = int(g.algorithm)
            if g.eff_ms > 0:
                # handover extension: the sender knows the exact
                # denominator (including Gregorian rows')
                eff = int(g.eff_ms)
            elif g.behavior & Behavior.DURATION_IS_GREGORIAN:
                try:
                    eff = gregorian_rate_duration_ms(int(g.duration))
                except (ValueError, KeyError):
                    eff = 1
            else:
                eff = max(int(g.duration), 1)
            burst = int(g.burst) if g.burst > 0 else int(g.update.limit)
            if alg == int(Algorithm.LEAKY_BUCKET):
                # broadcasts carry whole tokens (× eff to td); handover
                # messages (eff_ms set) carry the raw td fixed point —
                # lossless across the hop
                rem = (int(g.update.remaining) if g.eff_ms > 0
                       else int(g.update.remaining) * eff)
                expire = int(g.created_at) + eff
            else:
                rem = int(g.update.remaining)
                expire = int(g.update.reset_time)
            cols["meta"][j] = (alg & 1) | ((int(g.update.status) & 1) << 1)
            cols["limit"][j] = int(g.update.limit)
            cols["duration"][j] = int(g.duration)
            cols["eff_ms"][j] = eff
            cols["burst"][j] = burst
            cols["remaining"][j] = rem
            cols["t_ms"][j] = int(g.created_at)
            cols["expire_at"][j] = expire
        with self._engine_mu:
            self.engine.upsert_rows(khash, cols)

    # ---- health / lifecycle --------------------------------------------

    def health_status(self) -> str:
        """Cheap liveness answer ("healthy"/"unhealthy") from the async
        managers' last-error state alone — NO device work, no metrics
        side effects.  For callers that poll (health Watch streams):
        ``health_check`` additionally syncs a device occupancy count,
        which must not run at poll frequency."""
        if self.global_manager is not None and self.global_manager.last_error:
            return "unhealthy"
        if self.mr_manager is not None and self.mr_manager.last_error:
            return "unhealthy"
        return "healthy"

    # ---- SLO plane (ISSUE 11) ------------------------------------------

    def _build_slo(self) -> None:
        """Register the catalog (slo.py › SLO_CATALOG) against this
        instance's live signals and start the tick loop.  Sources are
        cheap reads of already-maintained state — the SLO plane adds
        no work to the serving path."""
        from .config import parse_duration_ms
        from .interval import IntervalLoop
        from .slo import (DEFAULT_BURN_THRESHOLD, DEFAULT_FAST_S,
                          DEFAULT_SLOW_S, SLO, SLO_CATALOG, SLOEngine)

        def _dur_s(v: str, default_s: float) -> float:
            if not v:
                return default_s
            try:
                return parse_duration_ms(v) / 1000.0
            except (ValueError, TypeError):
                return default_s

        def _flt(v: str, default: float) -> float:
            try:
                return float(v or default)
            except ValueError:
                return default

        fast = _dur_s(os.environ.get("GUBER_SLO_FAST", ""),
                      DEFAULT_FAST_S)
        slow = _dur_s(os.environ.get("GUBER_SLO_SLOW", ""),
                      DEFAULT_SLOW_S)
        tick_s = _dur_s(os.environ.get("GUBER_SLO_TICK", ""), 1.0)
        burn = _flt(os.environ.get("GUBER_SLO_BURN", ""),
                    DEFAULT_BURN_THRESHOLD)
        p99_s = _flt(os.environ.get("GUBER_SLO_P99_MS", ""),
                     250.0) / 1000.0
        def _breach_exemplar():
            # a burning SLO links to one concrete sampled trace
            # (ISSUE 12); None when nothing sampled recently
            ex = self.span_recorder.exemplar()
            return ex["trace_id"] if ex else None

        eng = SLOEngine(metrics=self.metrics, recorder=self.recorder,
                        fast_s=fast, slow_s=slow, burn_threshold=burn,
                        exemplar=_breach_exemplar)
        ana = self.dispatcher.analytics

        def decision_p99():
            p = (ana.phases.recent_p99("device")
                 if ana is not None else None)
            return (p or 0.0, p99_s)

        stale_target = 2.0 * max(
            self.config.behaviors.global_sync_wait_ms, 100) / 1000.0

        def global_staleness():
            mge = self._meshglobal
            if mge is None:
                return (0.0, stale_target)
            v = float(mge.last_staleness_s)
            ok = self._mesh_last_fold_ok
            if ok is not None:
                # a wedged/failing fold stops updating last_staleness_s
                # — age against the last SUCCESSFUL fold so the SLO
                # still sees the coherence gap widening
                v = max(v, time.monotonic() - ok)
            return (v, stale_target)

        def error_ratio():
            t = ana.tenant_totals() if ana is not None else {}
            return (t.get("errors", 0) + t.get("degraded", 0),
                    t.get("requests", 0))

        def shed_ratio():
            t = ana.tenant_totals() if ana is not None else {}
            return (t.get("shed", 0),
                    t.get("requests", 0) + t.get("shed", 0))

        eng.register(SLO("decision_p99", "threshold", 0.95,
                         decision_p99, SLO_CATALOG["decision_p99"]))
        eng.register(SLO("global_staleness", "threshold", 0.95,
                         global_staleness,
                         SLO_CATALOG["global_staleness"]))
        eng.register(SLO("error_ratio", "ratio", 0.999, error_ratio,
                         SLO_CATALOG["error_ratio"]))
        eng.register(SLO("shed_ratio", "ratio", 0.999, shed_ratio,
                         SLO_CATALOG["shed_ratio"]))
        led = self.memledger
        if led is not None:
            # the ledger's pressure sample IS the (value, target) pair;
            # it also edge-triggers the memory_pressure event, so the
            # early-warning fires on the same tick cadence as the SLO
            eng.register(SLO("hbm_pressure", "threshold", 0.95,
                             led.pressure_sample,
                             SLO_CATALOG["hbm_pressure"]))
        if ana is not None:
            eng.register_group(
                "tenant_error_ratio", 0.999,
                lambda: ana.tenant_red("errors"),
                SLO_CATALOG["tenant_error_ratio"])
            eng.register_group(
                "tenant_shed_ratio", 0.999,
                lambda: ana.tenant_red("shed"),
                SLO_CATALOG["tenant_shed_ratio"])
        if self.auditor.enabled:
            # value = seconds the audit drift has been nonzero, target
            # = the one-flush-window staleness bound; a partition (or a
            # real loss) holds drift nonzero past the bound and burns
            eng.register(SLO("fleet_conservation", "threshold", 0.95,
                             self.auditor.slo_sample,
                             SLO_CATALOG["fleet_conservation"]))
        self.slo = eng
        self._slo_loop = IntervalLoop(
            max(int(tick_s * 1000), 10), eng.tick, name="slo-engine")

    def audit_doc(self) -> dict:
        """The conservation audit vector served at GET /debug/audit
        (fleet.py › ConservationAuditor.doc): per-lane injected /
        applied / queued / in-flight / degraded-pending counters and
        the drift they prove, plus the ring view the fleet fold
        cross-checks.  Always available — the auditor rides the GLOBAL
        lanes' own accounting, no extra thread."""
        return self.auditor.doc()

    def health_check(self) -> HealthCheckResponse:
        """reference: gubernator.go › HealthCheck — healthy + peer count,
        surfacing the last async replication error if any."""
        msg = ""
        status = "healthy"
        if self.global_manager is not None and self.global_manager.last_error:
            status = "unhealthy"
            msg = self.global_manager.last_error
        elif self.mr_manager is not None and self.mr_manager.last_error:
            status = "unhealthy"
            msg = self.mr_manager.last_error
        # under _engine_mu: occupancy/saturation read self.engine.state,
        # which the donated step consumes and rebinds mid-wave — an
        # unlocked read can be handed a deleted buffer.  One device
        # call (pre-warmed at engine init) so serving waves queue
        # behind a sync, not a compile.
        with self._engine_mu:
            if hasattr(self.engine, "occupancy_and_saturation"):
                occ, full, total = self.engine.occupancy_and_saturation()
                self.metrics.bucket_saturation.set(full / max(total, 1))
            else:
                occ = self.engine_occupancy()
            self.metrics.cache_size.set(int(occ))
            self.metrics.dropped_rows.set(self.engine.dropped_rows)
        self.metrics.cache_capacity.set(self.engine.cap_local
                                        * self.engine.n)
        return HealthCheckResponse(status=status, message=msg,
                                   peer_count=len(self.peers()))

    def remove(self, name: str, unique_key: str) -> bool:
        """Delete one rate limit's state (library admin path; the
        reference exposes the same through its Cache.Remove + Store).
        Returns True when a row existed."""
        kh = hash_key(name, unique_key)
        if self._hotset is not None and self._hotset.is_pinned(kh):
            self._demote(kh)
        if self._meshglobal is not None and self._meshglobal.is_pinned(kh):
            self._mesh_demote(kh)
        with self._engine_mu:
            n = self.engine.remove_rows(np.array([kh], np.uint64))
            if self._tier is not None \
                    and self._tier.pop_row(kh) is not None:
                n += 1  # cold-resident: the row lived in the cold tier
        if self.store is not None:
            self.store.remove(f"{name}_{unique_key}")
        return n > 0

    def _tier_victim_pinned(self, kh: int) -> bool:
        """Tier-eviction victim filter: a replica-pinned key's device
        row is the HOME copy of hot-set/mesh coherence — demoting it
        to the cold tier while the pin serves would fork its state."""
        hs = self._hotset
        if hs is not None and hs.is_pinned(kh):
            return True
        mge = self._meshglobal
        return mge is not None and mge.is_pinned(kh)

    def engine_occupancy(self) -> int:
        # the engine owns its table layout (SoA columns vs the pallas
        # engine's bucket rows) — layout-specific counting lives there
        return self.engine.occupancy()

    # ---- device-memory ledger probes (ISSUE 13) --------------------
    # Each probe re-reads the live attributes at snapshot time (state
    # arrays rebind on grow/sweep/donated steps) and takes the owning
    # lock itself — the ledger never holds its own lock across a probe.

    def _enroll_memledger(self) -> None:
        led = self.memledger
        if led is None:
            return
        if getattr(self.engine, "state", None) is not None \
                and hasattr(self.engine, "cap_local"):
            led.enroll("hot_table", self._probe_hot_table,
                       advisable=True)
        if getattr(self.engine, "wave_pool", None) is not None:
            led.enroll("wave_pool", self._probe_wave_pool, host=True)
        if self.analytics is not None:
            led.enroll("sketch", self._probe_sketch, host=True)
        if self._tier is not None:
            led.enroll("cold_store", self._probe_cold_store, host=True)

    @staticmethod
    def _leaves_nbytes(leaves) -> int:
        return sum(int(getattr(a, "nbytes", 0)) for a in leaves)

    def _probe_hot_table(self) -> dict:
        import jax

        eng = self.engine
        # under _engine_mu: the donated step consumes and rebinds
        # state mid-wave — an unlocked read can hold a deleted buffer
        with self._engine_mu:
            nbytes = self._leaves_nbytes(jax.tree.leaves(eng.state))
            cap = int(getattr(eng, "cap_local", 0)) \
                * int(getattr(eng, "n", 1))
            live = int(getattr(eng, "live_rows", -1))
            if live < 0:
                # tick-cadence sampler: must not WAIT on the device
                # gate while holding the engine lock (that convoys
                # serving waves in multi-engine processes) — reuse the
                # last sample when the gate is contended
                fresh = eng.occupancy_nowait() \
                    if hasattr(eng, "occupancy_nowait") else None
                if fresh is None:
                    live = self._memledger_live
                else:
                    live = self._memledger_live = int(fresh)
        demand: dict = {}
        ana = self.analytics
        if ana is not None:
            demand["ranks"] = ana.rank_distribution()
        tier = self._tier
        if tier is not None:
            st = tier.stats()
            demand["promote_rate"] = st.get("promotions", 0)
            demand["demote_rate"] = st.get("demotions", 0)
            demand["overflow"] = st.get("cold_served", 0)
        return {"bytes": nbytes, "capacity_rows": cap,
                "occupied_rows": max(live, 0), "demand": demand}

    def _probe_wave_pool(self) -> dict:
        pool = getattr(self.engine, "wave_pool", None)
        if pool is None:
            return {"bytes": 0}
        st = pool.mem_stats()
        return {"bytes": st["pooled_bytes"], "capacity_rows": 0,
                "occupied_rows": st["pooled"],
                "demand": {"rate": st["hits"]}}

    def _probe_sketch(self) -> dict:
        ana = self.analytics
        if ana is None:
            return {"bytes": 0}
        st = ana.mem_stats()
        return {"bytes": st["bytes"], "capacity_rows": st["width"],
                "occupied_rows": st["used"],
                "demand": {"rate": st["total_weight"]}}

    def _probe_cold_store(self) -> dict:
        tier = self._tier
        if tier is None:
            return {"bytes": 0}
        st = tier.stats()
        return {"bytes": tier.mem_bytes(), "capacity_rows": 0,
                "occupied_rows": st["cold_keys"],
                "demand": {"promote_rate": st["promotions"],
                           "demote_rate": st["demotions"],
                           "rate": st["cold_served"]}}

    def _probe_hotset(self) -> dict:
        import jax

        hs = self._hotset
        if hs is None:
            return {"bytes": 0}
        with hs._state_mu:
            nbytes = self._leaves_nbytes(
                jax.tree.leaves(hs.state) + [hs.base_rem, hs.base_t])
        with hs._mu:
            occ = len(hs.slots)
        with self._hot_mu:
            rate = float(sum(self._hot_counts.values()))
        return {"bytes": nbytes, "capacity_rows": int(hs.capacity),
                "occupied_rows": occ, "demand": {"hit_rate": rate}}

    def _probe_meshglobal(self) -> dict:
        import jax

        mge = self._meshglobal
        if mge is None:
            return {"bytes": 0}
        # state + BOTH accumulator buffers; never mge.stats() here —
        # it drains collectives, a probe must stay read-only
        with mge._state_mu:
            nbytes = self._leaves_nbytes(
                jax.tree.leaves(mge.state)
                + jax.tree.leaves(mge._acc))
            folded = float(mge.folded_hits + mge.injected_hits)
        with mge._mu:
            occ = len(mge._occupied)
        return {"bytes": nbytes, "capacity_rows": int(mge.capacity),
                "occupied_rows": occ,
                "demand": {"fold_rate": folded}}

    def close(self) -> None:
        """Flush async managers, snapshot via Loader, drop peers.
        reference: V1Instance.Close (SURVEY.md §3.5)."""
        if self._closed:
            return
        self._closed = True
        if self._slo_loop is not None:
            # first: the close runs one FINAL tick, so the verdicts the
            # debug dump captures below reflect end-of-life state
            self._slo_loop.close()
        if self.global_manager is not None:
            self.global_manager.close()
        if self.mr_manager is not None:
            self.mr_manager.close()
        if self._hot_sync_loop is not None:
            self._hot_sync_loop.close()
        if self._probe_loop is not None:
            self._probe_loop.close()
        self.dispatcher.close()
        if self.dispatcher.analytics is not None:
            self.dispatcher.analytics.close()
        self._write_debug_dump()
        self._save_to_loader()
        if self.memledger is not None:
            # stand the ledger down leak-free: every enrolled consumer
            # releases (tests assert consumers() drains to empty here)
            for consumer in self.memledger.consumers():
                self.memledger.release(consumer)
        for p in self.peers():
            p.shutdown()

    def _write_debug_dump(self) -> None:
        """Crash forensics (ISSUE 11): when ``GUBER_DEBUG_DUMP_DIR`` is
        set, drain dumps the whole event ring plus the final SLO
        verdicts as JSONL — a killed pod leaves its black box on disk.
        Best-effort: a dying process must never wedge on forensics."""
        dirpath = os.environ.get("GUBER_DEBUG_DUMP_DIR", "")
        if not dirpath:
            return
        try:
            from .telemetry import write_debug_dump

            verdicts = (self.slo.verdicts()
                        if self.slo is not None else None)
            iid = (os.environ.get("GUBER_INSTANCE_ID", "")
                   or self.config.advertise_address or "instance")
            path = write_debug_dump(
                dirpath, iid,
                self.recorder.events(), slo_verdicts=verdicts)
            self.recorder.record("debug_dump_written", path=path,
                                 events=len(self.recorder))
            spans = self.span_recorder.spans()
            if spans:
                # trace-plane sibling (ISSUE 12): sampled spans spill
                # next to the event dump, trace_assemble.py-readable
                from .telemetry import write_trace_dump

                write_trace_dump(dirpath, iid, spans)
        except Exception as e:  # noqa: BLE001 - forensics is best-effort
            log.warning("debug dump failed: %s", exc_text(e))

"""Persistence hooks and checkpoint/resume.

reference: store.go › Store{OnChange, Get, Remove} (synchronous
write-through per mutation) and Loader{Load, Save} (startup/shutdown
snapshot), plus MockStore/MockLoader used by the test suite —
reconstructed, mount empty.

The TPU design checkpoints the device table as plain arrays: TableState
is a NamedTuple of [capacity] columns, so Save/Load is a device→host
`np.savez` round-trip (SURVEY.md §5.4) — no per-item heap walk.  The
item-granular Store/Loader protocols are kept for API parity and for
user-supplied databases; the array fast path is `save_table`/`load_table`.
"""
from __future__ import annotations

import io
import os
import tempfile
from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional, Protocol

import numpy as np

from .types import Algorithm, RateLimitRequest


@dataclass
class CacheItem:
    """One persisted rate-limit counter.

    reference: cache.go › CacheItem (Algorithm/Key/Value/ExpireAt); the
    value fields are flattened here instead of an interface{} payload.
    """

    key: str = ""
    key_hash: int = 0  # 64-bit identity; 0 = unknown (rehash from key)
    algorithm: int = int(Algorithm.TOKEN_BUCKET)
    limit: int = 0
    duration: int = 0
    eff_ms: int = 1
    burst: int = 0
    remaining: int = 0  # token: tokens; leaky: td fixed point
    t_ms: int = 0
    expire_at: int = 0
    status: int = 0


class Store(Protocol):
    """Write-through persistence, invoked synchronously around cache
    mutations.  reference: store.go › Store."""

    def on_change(self, req: RateLimitRequest, item: CacheItem) -> None: ...

    def get(self, req: RateLimitRequest) -> Optional[CacheItem]: ...

    def remove(self, key: str) -> None: ...


class Loader(Protocol):
    """Snapshot persistence at daemon startup/shutdown.
    reference: store.go › Loader."""

    def load(self) -> Iterable[CacheItem]: ...

    def save(self, items: Iterator[CacheItem]) -> None: ...


@dataclass
class MockStore:
    """In-memory Store recording calls (reference: store.go › MockStore)."""

    called: dict = field(default_factory=lambda: {
        "on_change": 0, "get": 0, "remove": 0})
    items: dict = field(default_factory=dict)

    def on_change(self, req: RateLimitRequest, item: CacheItem) -> None:
        self.called["on_change"] += 1
        self.items[item.key or req.key] = item

    def get(self, req: RateLimitRequest) -> Optional[CacheItem]:
        self.called["get"] += 1
        return self.items.get(req.key)

    def remove(self, key: str) -> None:
        self.called["remove"] += 1
        self.items.pop(key, None)


@dataclass
class MockLoader:
    """In-memory Loader recording calls (reference: store.go › MockLoader)."""

    called: dict = field(default_factory=lambda: {"load": 0, "save": 0})
    contents: List[CacheItem] = field(default_factory=list)

    def load(self) -> Iterable[CacheItem]:
        self.called["load"] += 1
        return list(self.contents)

    def save(self, items: Iterator[CacheItem]) -> None:
        self.called["save"] += 1
        self.contents = list(items)


class FileLoader:
    """Loader persisting to an .npz snapshot file (the array fast path)."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Iterable[CacheItem]:
        if not os.path.exists(self.path):
            return []
        return items_from_arrays(dict(np.load(self.path, allow_pickle=False)))

    def save(self, items: Iterator[CacheItem]) -> None:
        arrays = arrays_from_items(list(items))
        save_arrays(self.path, arrays)


_COLUMNS = ("key", "meta", "limit", "duration", "eff_ms", "burst",
            "remaining", "t_ms", "expire_at")


def save_arrays(path: str, arrays: dict) -> None:
    """Atomic .npz write (tmp + rename) — a crash mid-save keeps the old
    snapshot, matching the reference's expectation that Save is all-or-
    nothing at daemon shutdown."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def table_to_arrays(state) -> dict:
    """Device TableState → host column dict (drops empty rows)."""
    cols = {name: np.asarray(getattr(state, name)) for name in _COLUMNS}
    live = cols["key"] != 0
    return {name: col[live] for name, col in cols.items()}


def items_from_arrays(arrays: dict) -> List[CacheItem]:
    n = len(arrays["key"])
    out = []
    for i in range(n):
        meta = int(arrays["meta"][i])
        out.append(CacheItem(
            key="", key_hash=int(arrays["key"][i]),
            algorithm=meta & 1, status=(meta >> 1) & 1,
            limit=int(arrays["limit"][i]),
            duration=int(arrays["duration"][i]),
            eff_ms=int(arrays["eff_ms"][i]),
            burst=int(arrays["burst"][i]),
            remaining=int(arrays["remaining"][i]),
            t_ms=int(arrays["t_ms"][i]),
            expire_at=int(arrays["expire_at"][i]),
        ))
    return out


def arrays_from_items(items: List[CacheItem]) -> dict:
    from .hashing import hash_key

    n = len(items)
    arrays = {
        "key": np.zeros(n, np.uint64),
        "meta": np.zeros(n, np.int32),
        "limit": np.zeros(n, np.int64),
        "duration": np.zeros(n, np.int64),
        "eff_ms": np.ones(n, np.int64),
        "burst": np.zeros(n, np.int64),
        "remaining": np.zeros(n, np.int64),
        "t_ms": np.zeros(n, np.int64),
        "expire_at": np.zeros(n, np.int64),
    }
    for i, it in enumerate(items):
        kh = it.key_hash
        if kh == 0 and it.key:
            name, _, uniq = it.key.partition("_")
            kh = hash_key(name, uniq)
        arrays["key"][i] = np.uint64(kh)
        arrays["meta"][i] = (it.algorithm & 1) | ((it.status & 1) << 1)
        arrays["limit"][i] = it.limit
        arrays["duration"][i] = it.duration
        arrays["eff_ms"][i] = max(it.eff_ms, 1)
        arrays["burst"][i] = it.burst
        arrays["remaining"][i] = it.remaining
        arrays["t_ms"][i] = it.t_ms
        arrays["expire_at"][i] = it.expire_at
    return arrays

"""GLOBAL behavior: async hit reconciliation + owner broadcasts.

reference: global.go › globalManager{QueueHits, QueueUpdate,
runAsyncHits, runBroadcasts} — reconstructed, mount empty.

Any peer answers GLOBAL requests immediately from its local replica of
the counter; hits are queued here and asynchronously flushed to the
key's owner (aggregated per key); the owner applies them to its
authoritative copy and periodically broadcasts merged state to every
peer, which overwrites the replicas.  Short-window over-admission is the
documented consequence (SURVEY.md §2.4 GLOBAL).

On a TPU pod the intra-node analog of this manager is the psum delta
fold (SURVEY.md §3.3); this module is the inter-node (host gRPC) tier.
"""
from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Tuple

from .config import BehaviorConfig
from .interval import IntervalLoop
from .telemetry import exc_text
from .types import Behavior, RateLimitRequest

log = logging.getLogger("gubernator_tpu.global")


def _raw_lanes_available() -> bool:
    """The columnar flush paths need the native codec (peer_client's
    send lanes split responses with it)."""
    try:
        from .ops import native  # noqa: F401
        return True
    except ImportError:  # pragma: no cover - unbuilt extension
        return False


def _failed_future(e: BaseException):
    from concurrent.futures import Future

    f: Future = Future()
    f.set_exception(e)
    return f


class GlobalManager:
    def __init__(self, instance, behaviors: BehaviorConfig, metrics):
        self.instance = instance
        self.behaviors = behaviors
        self.metrics = metrics
        self._mu = threading.Lock()
        #: cross-lane arrival order (under _mu): when the SAME key is
        #: queued through both the object and wire lanes in one window,
        #: the prototype with the highest seq wins the flush-time merge
        #: — "latest config wins" must hold across lanes, not just
        #: within one
        self._seq = 0  # guarded-by: self._mu
        #: key → (request prototype, accumulated hits, seq) — non-owner.
        self._hits: Dict[str, Tuple[RateLimitRequest, int, int]] = {}  # guarded-by: self._mu
        #: key → (seq, request prototype) for changed GLOBAL keys —
        #: owner side.
        self._updates: Dict[str, Tuple[int, RateLimitRequest]] = {}  # guarded-by: self._mu
        #: key-hash → (request TLV bytes, accumulated hits, seq) — the
        #: wire lane's non-owner side.  The columnar request path queues
        #: the raw `requests` TLV slice instead of building per-request
        #: objects; entries materialize into prototypes at flush
        #: cadence (_req_from_tlv) and merge into _hits.
        self._hits_raw: Dict[int, Tuple[bytes, int, int]] = {}  # guarded-by: self._mu
        #: key-hash → (seq, request TLV bytes) — wire lane, owner side.
        self._updates_raw: Dict[int, Tuple[int, bytes]] = {}  # guarded-by: self._mu
        #: degraded share of the queued accumulators, keyed like the
        #: queues (ISSUE 19): parallel dicts instead of widening the
        #: queue tuples — external drivers (chaos) unpack 3-tuples
        self._deg: Dict[str, int] = {}  # guarded-by: self._mu
        self._deg_raw: Dict[int, int] = {}  # guarded-by: self._mu
        #: conservation audit tap (ISSUE 19, fleet.py): sender-side
        #: double-entry ledger behind GET /debug/audit.  Own leaf
        #: lock; every call sits OUTSIDE self._mu so the tap adds no
        #: lock-order edge.  None when GUBER_FLEET_AUDIT=0.
        from .fleet import AuditTap, audit_enabled

        self.audit = AuditTap() if audit_enabled() else None
        self._err_mu = threading.Lock()
        self._last_error = ""  # guarded-by: self._err_mu
        self._last_error_at = 0.0  # guarded-by: self._err_mu
        self._hits_loop = IntervalLoop(
            behaviors.global_sync_wait_ms, self._run_async_hits,
            name="global-async-hits")
        self._bcast_loop = IntervalLoop(
            behaviors.global_broadcast_interval_ms, self._run_broadcasts,
            name="global-broadcasts")

    # ---- producers (called from the request path) ----------------------

    def queue_hits(self, req: RateLimitRequest,
                   degraded: bool = False) -> None:
        """Accumulate hits for async reconcile to the owner.
        reference: global.go › QueueHits.  ``degraded`` marks hits
        queued by a degraded-mode serve (ISSUE 19 audit vector)."""
        inc = max(int(req.hits), 0)
        with self._mu:
            self._seq += 1
            _, acc, _ = self._hits.get(req.key, (req, 0, 0))
            self._hits[req.key] = (req, acc + inc, self._seq)
            if degraded and inc:
                self._deg[req.key] = self._deg.get(req.key, 0) + inc
            # both lanes share the flush: threshold and gauge must see
            # the raw queue too or mixed-lane traffic undercounts
            n = len(self._hits) + len(self._hits_raw)
        if self.audit is not None:
            self.audit.inject(inc, degraded)
        self.metrics.queue_length.set(n)
        if n >= self.behaviors.global_batch_limit:
            self._hits_loop.poke()

    def queue_update(self, req: RateLimitRequest) -> None:
        """Mark a GLOBAL key changed on the owner; broadcast on next tick.
        reference: global.go › QueueUpdate."""
        with self._mu:
            self._seq += 1
            self._updates[req.key] = (self._seq, req)
            n = len(self._updates) + len(self._updates_raw)
        if n >= self.behaviors.global_batch_limit:
            self._bcast_loop.poke()

    # ---- wire-lane producers (columnar request path) -------------------
    #
    # The clustered wire fast lane has no per-request Python objects —
    # only parsed columns and the raw `requests` TLV slices.  These
    # producers keep it that way: the request path hands over (key-hash,
    # TLV bytes, aggregated hits) per UNIQUE key; prototypes are built
    # lazily at flush cadence, off the request path, then flow through
    # the same flush/broadcast machinery as the object-path queues (so
    # a key served through both lanes merges correctly).

    def queue_hits_raw(self, khash: int, tlv: bytes, hits: int,
                       degraded: bool = False) -> None:
        """Wire-lane twin of ``queue_hits``: accumulate ``hits`` for the
        key identified by ``khash``, with ``tlv`` (the verbatim
        GetRateLimitsReq.requests TLV slice) as the deferred prototype.
        A hits=0 entry still refreshes the prototype, exactly as
        queue_hits stores the latest req unconditionally."""
        inc = max(int(hits), 0)
        with self._mu:
            self._seq += 1
            _, acc, _ = self._hits_raw.get(khash, (tlv, 0, 0))
            # keep the LATEST tlv as the prototype, exactly as
            # queue_hits keeps the latest req: a mid-window config
            # change must reconcile under the new limit/duration
            self._hits_raw[khash] = (tlv, acc + inc, self._seq)
            if degraded and inc:
                self._deg_raw[khash] = self._deg_raw.get(khash, 0) + inc
            n = len(self._hits_raw) + len(self._hits)
        if self.audit is not None:
            self.audit.inject(inc, degraded)
        self.metrics.queue_length.set(n)
        if n >= self.behaviors.global_batch_limit:
            self._hits_loop.poke()

    def queue_update_raw(self, khash: int, tlv: bytes) -> None:
        """Wire-lane twin of ``queue_update`` (owner side)."""
        with self._mu:
            self._seq += 1
            self._updates_raw[khash] = (self._seq, tlv)
            n = len(self._updates_raw) + len(self._updates)
        if n >= self.behaviors.global_batch_limit:
            self._bcast_loop.poke()

    @staticmethod
    def _req_from_tlv(tlv: bytes) -> RateLimitRequest:
        """Deferred prototype (wire.req_from_tlv).  Flush-cadence only."""
        from .wire import req_from_tlv

        return req_from_tlv(tlv)

    def queued_hits(self) -> Tuple[int, int]:
        """(total queued hit weight, degraded share) across both
        lanes — the audit vector's live-queue leg (ISSUE 19)."""
        with self._mu:
            q = (sum(a for _, a, _ in self._hits.values())
                 + sum(a for _, a, _ in self._hits_raw.values()))
            d = sum(self._deg.values()) + sum(self._deg_raw.values())
        return q, d

    def _requeue_hits(self, entries) -> None:
        """Put a FAILED flush's aggregates back into the queues
        (ISSUE 5): degraded-mode hits reconcile EXACTLY once the owner
        recovers, so an unreachable owner must requeue, not drop.
        ``entries``: (key-or-khash, proto (req object or raw TLV),
        accumulated hits, seq, degraded share); merges with anything
        queued since the flush popped them (latest-prototype-wins,
        sums preserved).  A requeue is NOT a re-inject — the audit
        tap saw these hits at queue-entry; they simply stay queued."""
        if not entries:
            return
        with self._mu:
            for k, proto, acc, seq, deg in entries:
                if isinstance(proto, bytes):
                    t0, a0, s0 = self._hits_raw.get(k, (proto, 0, 0))
                    self._hits_raw[k] = (proto if seq >= s0 else t0,
                                         a0 + acc, max(s0, seq))
                    if deg:
                        self._deg_raw[k] = self._deg_raw.get(k, 0) + deg
                else:
                    p0, a0, s0 = self._hits.get(k, (proto, 0, 0))
                    self._hits[k] = (proto if seq >= s0 else p0,
                                     a0 + acc, max(s0, seq))
                    if deg:
                        self._deg[k] = self._deg.get(k, 0) + deg
            n = len(self._hits) + len(self._hits_raw)
        self.metrics.queue_length.set(n)

    def _fault_tick(self, point: str, stage: str) -> bool:
        """Chaos hook for the async loops: True aborts this tick (the
        queues were not popped yet, so nothing is lost)."""
        f = getattr(self.instance, "faults", None)
        if f is None or not f.armed:
            return False
        try:
            f.fire(point)
        except Exception as e:  # noqa: BLE001 - incl. FaultInjected
            msg = f"{stage}: {exc_text(e)}"
            log.warning(msg)
            self._record([msg])
            return True
        return False

    # ---- async loops ---------------------------------------------------

    def _tick_context(self, name: str):
        """Traced context for one async tick (ISSUE 12): the tick gets
        its OWN trace (there is no caller request on this thread), a
        root span named after the aggregate, and hop spans from the
        lanes it sends on — so an owner-side UpdatePeerGlobals /
        GetPeerRateLimits handler stitches back to the flush that
        caused it.  No-op (null context) without a span recorder."""
        rec = getattr(self.instance, "span_recorder", None)
        if rec is None:
            import contextlib

            return contextlib.nullcontext()
        from .tracing import request_context, span

        @contextmanager
        def _cm():
            with request_context(None, recorder=rec), span(name):
                yield

        return _cm()

    def _run_async_hits(self) -> None:
        """Flush aggregated hits to each key's owner.
        reference: global.go › runAsyncHits.

        Columnar path (default-hash pickers + native codec): BOTH
        lanes' queues merge in raw-khash space, each key's aggregate
        becomes one TLV with the summed hits appended
        (wire.tlv_with_hits — zero request materialization), and the
        per-owner payloads ride the peers' pooled forward lanes
        (pipelined flushes, retry, circuit fail-fast), aggregated per
        peer per window.  Non-default pickers / no codec keep the
        legacy object flush."""
        with self._tick_context("global.hits_flush"):
            self._hits_tick()

    def _hits_tick(self) -> None:
        if self._fault_tick("global_hits", "global hits flush"):
            return
        # Mesh reconcile backend (ISSUE 7, GUBER_GLOBAL_MODE=mesh):
        # pod-local GLOBAL counters converge through the engine-side
        # collective fold instead of gRPC fan-out; the tick no-ops in
        # grpc mode.  The queued aggregates below (cross-pod owners,
        # degraded-mode reconcile) keep the gRPC lanes either way —
        # that path is also the mesh tier's degraded fallback.
        tick = getattr(self.instance, "_mesh_reconcile_tick", None)
        if tick is not None:
            tick()
        with self._mu:
            hits, self._hits = self._hits, {}
            hits_raw, self._hits_raw = self._hits_raw, {}
            deg, self._deg = self._deg, {}
            deg_raw, self._deg_raw = self._deg_raw, {}
        self.metrics.queue_length.set(0)
        inst = self.instance
        tap = self.audit
        if ((hits_raw or hits) and _raw_lanes_available()
                and inst.default_hash_routing()):
            self._flush_hits_raw(hits, hits_raw, deg, deg_raw)
            return
        for khash, (tlv, acc, seq) in hits_raw.items():
            d = deg_raw.get(khash, 0)
            try:
                req = self._req_from_tlv(tlv)
            except Exception:  # noqa: BLE001 - a corrupt queued TLV
                # can only come from a parser bug; drop it rather than
                # poison the whole flush
                log.warning("dropping unparseable queued TLV for key "
                            "hash %d", khash)
                if tap is not None:
                    # injected weight that will never apply: the audit
                    # vector's `lost` leg (permanent drift — ISSUE 19)
                    tap.lose(acc, d)
                continue
            proto, a0, s0 = hits.get(req.key, (req, 0, seq))
            hits[req.key] = (req if seq >= s0 else proto, a0 + acc,
                             max(s0, seq))
            if d:
                deg[req.key] = deg.get(req.key, 0) + d
        if not hits:
            return
        # group by owner peer; each entry keeps its requeue tuple so a
        # failed chunk goes BACK on the queue instead of vanishing
        by_owner: Dict[str, Tuple[object, List[RateLimitRequest],
                                  List[tuple]]] = {}
        absorbed = absorbed_deg = 0
        for key, (req, acc, seq) in hits.items():
            if acc <= 0:
                continue
            d = deg.get(key, 0)
            peer = self.instance.owner_of(key)
            if peer is None or self.instance.is_self(peer):
                # we are the owner: already applied locally — settle
                # the audit entry as absorbed
                absorbed += acc
                absorbed_deg += d
                continue
            merged = RateLimitRequest(
                name=req.name, unique_key=req.unique_key, hits=acc,
                limit=req.limit, duration=req.duration,
                algorithm=req.algorithm, behavior=req.behavior,
                burst=req.burst)
            addr = peer.info.grpc_address
            slot = by_owner.setdefault(addr, (peer, [], []))
            slot[1].append(merged)
            slot[2].append((key, req, acc, seq, d))
        if tap is not None:
            tap.apply(absorbed, absorbed_deg, absorbed=True)
        errors = []
        for addr, (peer, reqs, entries) in by_owner.items():
            limit = self.behaviors.global_batch_limit
            for i in range(0, len(reqs), limit):
                try:
                    peer.get_peer_rate_limits(
                        reqs[i:i + limit],
                        timeout_s=self.behaviors.global_timeout_ms
                        / 1000.0)
                except Exception as e:  # noqa: BLE001 - requeue, next
                    # tick retries (exact reconcile, ISSUE 5).
                    # exc_text: a peer deadline/TimeoutError str()s empty
                    self._requeue_hits(entries[i:])
                    errors.append(f"global hits sync to {addr}: "
                                  f"{exc_text(e)}")
                    self.metrics.check_error_counter.labels(
                        error="global_hits_sync").inc()
                    log.warning(errors[-1])
                    self._record_event("error", stage="global_hits_sync",
                                       error=errors[-1])
                    break
                if tap is not None:
                    # the owner acked this chunk: settle its entries
                    ent = entries[i:i + limit]
                    tap.apply(sum(e[2] for e in ent),
                              sum(e[4] for e in ent))
        self._record(errors)

    def _flush_hits_raw(self, hits, hits_raw, deg=None,
                        deg_raw=None) -> None:
        """Columnar hit flush: raw-khash merge → per-key TLV with the
        aggregate hits → per-owner payloads on the forward lanes."""
        from .hashing import fnv1a64
        from .wire import req_to_tlv, tlv_with_hits

        tap = self.audit
        merged: Dict[int, Tuple[object, int, int]] = dict(hits_raw)
        degm: Dict[int, int] = dict(deg_raw or {})
        for key, (req, acc, seq) in hits.items():
            kh = fnv1a64(key.encode("utf-8"))
            cur = merged.get(kh)
            if cur is None:
                merged[kh] = (req, acc, seq)
            else:
                proto, a0, s0 = cur
                merged[kh] = (req if seq >= s0 else proto, a0 + acc,
                              max(s0, seq))
            d = (deg or {}).get(key, 0)
            if d:
                degm[kh] = degm.get(kh, 0) + d
        inst = self.instance
        by_owner: Dict[str, Tuple[object, List[bytes], List[tuple]]] = {}
        absorbed = absorbed_deg = 0
        for kh, (proto, acc, seq) in merged.items():
            if acc <= 0:
                continue
            d = degm.get(kh, 0)
            peer = inst.owner_by_raw_khash(kh)
            if peer is None or inst.is_self(peer):
                # we are the owner: already applied locally — settle
                # the audit entry as absorbed
                absorbed += acc
                absorbed_deg += d
                continue
            tlv = (tlv_with_hits(proto, acc) if isinstance(proto, bytes)
                   else req_to_tlv(RateLimitRequest(
                       name=proto.name, unique_key=proto.unique_key,
                       hits=acc, limit=proto.limit,
                       duration=proto.duration,
                       algorithm=proto.algorithm, behavior=proto.behavior,
                       burst=proto.burst)))
            addr = peer.info.grpc_address
            slot = by_owner.setdefault(addr, (peer, [], []))
            slot[1].append(tlv)
            # requeue tuple keyed the way it was queued: raw-lane
            # protos under the raw khash, object-lane under the key
            if isinstance(proto, bytes):
                slot[2].append((kh, proto, acc, seq, d))
            else:
                slot[2].append((proto.key, proto, acc, seq, d))
        if tap is not None:
            tap.apply(absorbed, absorbed_deg, absorbed=True)
        futs = []
        limit = self.behaviors.global_batch_limit
        for addr, (peer, tlvs, entries) in by_owner.items():
            for i in range(0, len(tlvs), limit):
                chunk = tlvs[i:i + limit]
                ent = entries[i:i + limit]
                try:
                    # clock-ok: GLOBAL aggregate hit deltas — accumulated counts, not fresh requests; the owner's authoritative bucket is the time base by design
                    futs.append((addr, peer.forward_raw(
                        b"".join(chunk), len(chunk)), ent))
                except Exception as e:  # noqa: BLE001 - ErrCircuitOpen/
                    # ErrClosing fail fast; requeued below
                    futs.append((addr, _failed_future(e), ent))
        errors = []
        deadline = time.monotonic() + \
            self.behaviors.global_timeout_ms / 1000.0 + 30.0
        for addr, fut, ent in futs:
            try:
                fut.result(timeout=max(deadline - time.monotonic(), 0.1))
            except Exception as e:  # noqa: BLE001 - requeue so the
                # aggregates survive until the owner is reachable
                # (exact reconcile, ISSUE 5)
                self._requeue_hits(ent)
                errors.append(f"global hits sync to {addr}: "
                              f"{exc_text(e)}")
                self.metrics.check_error_counter.labels(
                    error="global_hits_sync").inc()
                log.warning(errors[-1])
                self._record_event("error", stage="global_hits_sync",
                                   error=errors[-1])
                continue
            if tap is not None:
                # the owner acked this chunk: settle its entries
                tap.apply(sum(e[2] for e in ent),
                          sum(e[4] for e in ent))
        self._record(errors)

    def _run_broadcasts(self) -> None:
        """Owner side: push merged authoritative state to all peers.
        reference: global.go › runBroadcasts → UpdatePeerGlobals."""
        with self._tick_context("global.broadcast"):
            self._broadcast_tick()

    def _broadcast_tick(self) -> None:
        if self._fault_tick("global_broadcast", "global broadcast"):
            return
        with self._mu:
            updates, self._updates = self._updates, {}
            updates_raw, self._updates_raw = self._updates_raw, {}
        for khash, (seq, tlv) in updates_raw.items():
            try:
                req = self._req_from_tlv(tlv)
            except Exception:  # noqa: BLE001
                log.warning("dropping unparseable queued TLV for key "
                            "hash %d", khash)
                continue
            cur = updates.get(req.key)
            if cur is None or seq > cur[0]:
                updates[req.key] = (seq, req)
        if not updates:
            return
        t0 = time.perf_counter()
        msgs = self.instance.build_global_updates(
            [r for _, r in updates.values()])
        if not msgs:
            return
        peers = [p for p in self.instance.peers() if not self.instance.is_self(p)]
        errors = []
        limit = self.behaviors.global_batch_limit
        if peers and _raw_lanes_available():
            # columnar broadcast: serialize each UpdatePeerGlobal ONCE
            # into its `globals` TLV (the typed stub re-serialized the
            # same messages per peer), then every peer's chunk rides
            # its pooled update lane — pipelined, retried, circuit-
            # gated, aggregated per peer per window
            from .wire import _varint

            tlvs = []
            for m in msgs:
                payload = m.SerializeToString()
                tlvs.append(b"\x0a" + _varint(len(payload)) + payload)
            chunks = [b"".join(tlvs[i:i + limit])
                      for i in range(0, len(tlvs), limit)]
            futs = []
            for peer in peers:
                for i, chunk in enumerate(chunks):
                    n = min(limit, len(tlvs) - i * limit)
                    try:
                        futs.append((peer.info.grpc_address,
                                     peer.send_globals_raw(chunk, n)))
                    except Exception as e:  # noqa: BLE001 - fail fast
                        futs.append((peer.info.grpc_address,
                                     _failed_future(e)))
            deadline = time.monotonic() + \
                self.behaviors.global_timeout_ms / 1000.0 + 30.0
            failed_addrs = set()
            for addr, fut in futs:
                try:
                    fut.result(timeout=max(deadline - time.monotonic(),
                                           0.1))
                except Exception as e:  # noqa: BLE001
                    if addr not in failed_addrs:
                        failed_addrs.add(addr)
                        errors.append(f"global broadcast to {addr}: "
                                      f"{exc_text(e)}")
                        self.metrics.check_error_counter.labels(
                            error="global_broadcast").inc()
                        log.warning(errors[-1])
        else:
            for peer in peers:
                try:
                    for i in range(0, len(msgs), limit):
                        peer.update_peer_globals(msgs[i:i + limit])
                except Exception as e:  # noqa: BLE001
                    errors.append(f"global broadcast to "
                                  f"{peer.info.grpc_address}: "
                                  f"{exc_text(e)}")
                    self.metrics.check_error_counter.labels(
                        error="global_broadcast").inc()
                    log.warning(errors[-1])
        self._record(errors)
        self.metrics.global_broadcast_counter.inc()
        dt = time.perf_counter() - t0
        self.metrics.broadcast_duration.observe(dt)
        # per-phase attribution (closes the PR-4 ROADMAP open item):
        # the broadcast path lands in the PhaseLedger / histogram next
        # to ingest/device/peer_flush
        disp = getattr(self.instance, "dispatcher", None)
        if disp is not None:
            disp._obs_phase("broadcast", dt)
            ana = getattr(disp, "analytics", None)
            if ana is not None and peers and not errors:
                # cost-model sample (ISSUE 11): one broadcast fans the
                # serialized update set out to every peer.  Errored
                # rounds are excluded — a timeout's duration measures
                # the deadline, not the transfer.
                nbytes = sum(m.ByteSize() for m in msgs) * len(peers)
                ana.tap_cost("broadcast", nbytes, len(peers) + 1, dt)
        self._record_event("broadcast", keys=len(msgs), peers=len(peers),
                           errors=len(errors),
                           error=("; ".join(errors) or None))

    # ---- error surfacing (health_check) --------------------------------

    #: An async-replication error older than this no longer marks the
    #: daemon unhealthy (the loops retry every tick; a stale error would
    #: otherwise fail readiness probes forever).
    ERROR_TTL_S = 60.0

    def _record_event(self, kind: str, **fields) -> None:
        """Best-effort flight-recorder hook (instance owns the ring)."""
        rec = getattr(self.instance, "recorder", None)
        if rec is not None:
            rec.record(kind, **fields)

    def _record(self, errors) -> None:
        """Per-tick error aggregation: success clears, failure stamps."""
        with self._err_mu:
            if errors:
                self._last_error = "; ".join(errors)
                self._last_error_at = time.monotonic()
            else:
                self._last_error = ""

    @property
    def last_error(self) -> str:
        with self._err_mu:
            if (self._last_error and
                    time.monotonic() - self._last_error_at > self.ERROR_TTL_S):
                return ""
            return self._last_error

    def poke(self) -> None:
        """Force both loops to run now (tests / shutdown flush)."""
        self._hits_loop.poke()
        self._bcast_loop.poke()

    def close(self) -> None:
        self._hits_loop.close()
        self._bcast_loop.close()

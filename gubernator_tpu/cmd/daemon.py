"""The daemon binary: config → spawn → wait for signal.

reference: cmd/gubernator/main.go — reconstructed, mount empty.
Usage: python -m gubernator_tpu.cmd.daemon [--config FILE]
(all GUBER_* env vars apply; see config.py).
"""
from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="gubernator-tpu daemon")
    ap.add_argument("--config", default="", help="KEY=value config file")
    ap.add_argument("--grpc", default="", help="override GUBER_GRPC_ADDRESS")
    ap.add_argument("--http", default="", help="override GUBER_HTTP_ADDRESS")
    ap.add_argument("--client", default="",
                    help="override GUBER_CLIENT_ADDRESS (shared "
                         "SO_REUSEPORT front door)")
    args = ap.parse_args(argv)

    from . import maybe_pin_platform

    maybe_pin_platform()

    from ..config import setup_daemon_config
    from ..daemon import spawn_daemon

    cfg = setup_daemon_config(conf_file=args.config)
    if args.grpc:
        cfg.grpc_listen_address = args.grpc
    if args.http:
        cfg.http_listen_address = args.http
    if args.client:
        cfg.client_listen_address = args.client
    logging.basicConfig(
        level=getattr(logging, cfg.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    d = spawn_daemon(cfg)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    print(f"gubernator-tpu listening grpc={cfg.grpc_listen_address} "
          f"http={cfg.http_listen_address}", flush=True)
    stop.wait()
    d.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Load-test + debug CLI.

reference: cmd/gubernator-cli/main.go — reconstructed, mount empty.
Usage: python -m gubernator_tpu.cmd.cli --address host:port
       [--rate-limits N] [--concurrency C] [--batch B] [--duration S]
       [--zipf A] [--http]

Debug subcommand (the flight-recorder round trip, OBSERVABILITY.md):
       python -m gubernator_tpu.cmd.cli debug events
       [--url http://host:port] [--limit N] [--json] [--kind K]

Fleet subcommand (ISSUE 19, OBSERVABILITY.md › Fleet plane): fan in
every daemon's debug endpoints and fold them exactly (fleet.py):
       python -m gubernator_tpu.cmd.cli fleet
       {status,audit,topkeys,tenants,slo,memory}
       --url http://d1:1050 --url http://d2:1050 ...
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _fetch_json(url: str, timeout: float):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as f:
        return json.loads(f.read())


def _debug_main(argv) -> int:
    """``debug events``: fetch the daemon's flight-recorder ring from
    GET /debug/events (kind/since-seq filtered SERVER-side) and print
    it.  ``debug topkeys``: the heavy-hitter ledger from
    GET /debug/topkeys."""
    ap = argparse.ArgumentParser(
        prog="guber-cli debug",
        description="gubernator-tpu debug introspection")
    sub = ap.add_subparsers(dest="what", required=True)
    ev = sub.add_parser("events",
                        help="dump the daemon's flight-recorder ring")
    ev.add_argument("--url", default="http://localhost:1050",
                    help="daemon HTTP base url (or a full "
                         "/debug/events url)")
    ev.add_argument("--limit", type=int, default=0,
                    help="only the newest N events")
    ev.add_argument("--kind", default="",
                    help="only events of this kind (e.g. wave_stalled)")
    ev.add_argument("--since-seq", type=int, default=0,
                    help="only events with seq > N (incremental polls)")
    ev.add_argument("--trace", default="",
                    help="only events stamped with this trace id "
                         "(server-side filter)")
    ev.add_argument("--timeout", type=float, default=10.0)
    ev.add_argument("--json", action="store_true",
                    help="print the raw JSON document")
    tr = sub.add_parser("traces",
                        help="dump the daemon's span-recorder ring "
                             "(/debug/traces), assembled per trace")
    tr.add_argument("--url", action="append", dest="urls", default=None,
                    help="daemon HTTP base url (default "
                         "http://localhost:1050); repeat to stitch "
                         "several daemons' slices into one tree")
    tr.add_argument("--trace-id", default="",
                    help="only spans of this trace (server-side)")
    tr.add_argument("--limit", type=int, default=0,
                    help="only the newest N spans per daemon")
    tr.add_argument("--waterfall", action="store_true",
                    help="render each assembled trace as a text "
                         "waterfall")
    tr.add_argument("--timeout", type=float, default=10.0)
    tr.add_argument("--json", action="store_true",
                    help="print the raw JSON document")
    tk = sub.add_parser("topkeys",
                        help="dump the daemon's heavy-hitter key "
                             "ledger (/debug/topkeys)")
    tk.add_argument("--url", default="http://localhost:1050",
                    help="daemon HTTP base url (or a full "
                         "/debug/topkeys url)")
    tk.add_argument("--limit", type=int, default=0,
                    help="only the heaviest N keys")
    tk.add_argument("--timeout", type=float, default=10.0)
    tk.add_argument("--json", action="store_true",
                    help="print the raw JSON document")
    tn = sub.add_parser("tenants",
                        help="dump the daemon's per-tenant RED ledger "
                             "(/debug/tenants)")
    tn.add_argument("--url", default="http://localhost:1050",
                    help="daemon HTTP base url (or a full "
                         "/debug/tenants url)")
    tn.add_argument("--timeout", type=float, default=10.0)
    tn.add_argument("--json", action="store_true",
                    help="print the raw JSON document")
    sl = sub.add_parser("slo",
                        help="dump the daemon's SLO burn-rate verdicts "
                             "(/debug/slo)")
    sl.add_argument("--url", default="http://localhost:1050",
                    help="daemon HTTP base url (or a full "
                         "/debug/slo url)")
    sl.add_argument("--timeout", type=float, default=10.0)
    sl.add_argument("--json", action="store_true",
                    help="print the raw JSON document")
    mm = sub.add_parser("memory",
                        help="dump the daemon's device-memory ledger "
                             "(/debug/memory)")
    mm.add_argument("--url", default="http://localhost:1050",
                    help="daemon HTTP base url (or a full "
                         "/debug/memory url)")
    mm.add_argument("--advise", action="store_true",
                    help="include the water-filling split "
                         "recommendation (?advise=1)")
    mm.add_argument("--timeout", type=float, default=10.0)
    mm.add_argument("--json", action="store_true",
                    help="print the raw JSON document")
    fl = sub.add_parser("faults",
                        help="inspect or arm the daemon's fault-"
                             "injection points (/debug/faults)")
    fl.add_argument("--url", default="http://localhost:1050",
                    help="daemon HTTP base url")
    fl.add_argument("--set", dest="spec", default=None,
                    help="arm this fault spec (e.g. "
                         "'peer_send:error:0.3,device_step:delay:50ms')")
    fl.add_argument("--seed", type=int, default=None,
                    help="deterministic seed for the armed points")
    fl.add_argument("--clear", action="store_true",
                    help="disarm every faultpoint")
    fl.add_argument("--timeout", type=float, default=10.0)
    fl.add_argument("--json", action="store_true",
                    help="print the raw JSON document")
    args = ap.parse_args(argv)
    if args.what == "topkeys":
        return _debug_topkeys(args)
    if args.what == "tenants":
        return _debug_tenants(args)
    if args.what == "slo":
        return _debug_slo(args)
    if args.what == "memory":
        return _debug_memory(args)
    if args.what == "faults":
        return _debug_faults(args)
    if args.what == "traces":
        return _debug_traces(args)

    url = args.url
    if "/debug/events" not in url:
        url = url.rstrip("/") + "/debug/events"

    def _q(param):
        nonlocal url
        url += ("&" if "?" in url else "?") + param

    if args.limit > 0:
        _q(f"limit={args.limit}")
    if args.kind:
        # server-side filter; the client-side pass below still applies
        # (harmless, and keeps the flag working against older daemons)
        _q(f"kind={args.kind}")
    if args.since_seq > 0:
        _q(f"since_seq={args.since_seq}")
    if args.trace:
        _q(f"trace={args.trace}")
    try:
        body = _fetch_json(url, args.timeout)
    except Exception as e:  # noqa: BLE001
        print(f"fetch failed: {e!r}", file=sys.stderr)
        return 1
    events = body.get("events", [])
    if args.kind:
        events = [e for e in events if e.get("kind") == args.kind]
    if args.json:
        print(json.dumps({"events": events}))
        return 0
    for e in events:
        seq, kind = e.get("seq"), e.get("kind")
        t_ms, trace = e.get("t_ms"), e.get("trace")
        rest = {k: v for k, v in e.items()
                if k not in ("seq", "kind", "t_ms", "trace")}
        line = f"#{seq} t={t_ms} {kind}"
        if trace:
            line += f" trace={trace}"
        if rest:
            line += " " + json.dumps(rest, sort_keys=True)
        print(line)
    if not events:
        print("(no events)", file=sys.stderr)
    return 0


def _debug_traces(args) -> int:
    urls = args.urls or ["http://localhost:1050"]
    spans, meta = [], []
    for base in urls:
        url = base
        if "/debug/traces" not in url:
            url = url.rstrip("/") + "/debug/traces"
        if args.trace_id:
            url += ("&" if "?" in url else "?") + f"trace_id={args.trace_id}"
        if args.limit > 0:
            url += ("&" if "?" in url else "?") + f"limit={args.limit}"
        try:
            body = _fetch_json(url, args.timeout)
        except Exception as e:  # noqa: BLE001
            print(f"fetch failed ({base}): {e!r}", file=sys.stderr)
            return 1
        spans.extend(body.get("spans", []))
        meta.append({k: body.get(k) for k in ("sample", "capacity", "dropped")})
    if args.json:
        print(json.dumps({"daemons": meta, "spans": spans}))
        return 0
    from ..tracing import assemble, render_waterfall
    traces = assemble(spans, trace_id=args.trace_id or None)
    if not traces:
        print("(no spans)", file=sys.stderr)
        return 0
    for trace in traces:
        if args.waterfall:
            print(render_waterfall(trace))
            print()
            continue
        tid = trace["trace_id"]
        print(f"trace {tid}: {trace['spans']} span(s)")

        def _walk(node, depth):
            dur_ms = (node["end"] - node["start"]) * 1e3
            line = (f"  {'  ' * depth}{node['name']} "
                    f"[{node['span_id']}] {dur_ms:.3f}ms")
            if node.get("attrs"):
                line += " " + json.dumps(node["attrs"], sort_keys=True)
            print(line)
            for c in node.get("children", []):
                _walk(c, depth + 1)

        for root in trace["roots"]:
            _walk(root, 0)
    return 0


def _debug_topkeys(args) -> int:
    url = args.url
    if "/debug/topkeys" not in url:
        url = url.rstrip("/") + "/debug/topkeys"
    if args.limit > 0:
        url += ("&" if "?" in url else "?") + f"limit={args.limit}"
    try:
        body = _fetch_json(url, args.timeout)
    except Exception as e:  # noqa: BLE001
        print(f"fetch failed: {e!r}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body))
        return 0
    print(f"top-{body.get('k')} of ~{body.get('total_hits_observed')} "
          f"hits across {body.get('waves_tapped')} waves "
          f"(width={body.get('width')}, "
          f"admission_err<={body.get('admission_error_bound')}, "
          f"dropped={body.get('taps_dropped')})")
    keys = body.get("keys", [])
    for e in keys:
        name = e.get("key") or e.get("khash")
        line = (f"{e.get('hits'):>12}  over={e.get('over_limit'):<8} "
                f"err<={e.get('err'):<6} {name}")
        if e.get("owner"):
            line += f"  owner={e['owner']}"
        print(line)
    if not keys:
        print("(no keys tracked)", file=sys.stderr)
    return 0


def _debug_tenants(args) -> int:
    """``debug tenants``: the per-tenant RED ledger round trip."""
    url = args.url
    if "/debug/tenants" not in url:
        url = url.rstrip("/") + "/debug/tenants"
    try:
        body = _fetch_json(url, args.timeout)
    except Exception as e:  # noqa: BLE001
        print(f"fetch failed: {e!r}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body))
        return 0
    if not body.get("enabled", False):
        print("tenant attribution disabled", file=sys.stderr)
        return 1
    print(f"tenants: {body.get('tenant_count')} "
          f"(delim={body.get('delim')!r} max={body.get('max_tenants')} "
          f"overflowed={body.get('overflowed')})")
    hdr = ("requests", "hits", "over_limit", "errors", "degraded",
           "shed")
    print(f"{'tenant':<24}" + "".join(f"{h:>11}" for h in hdr))
    rows = sorted(body.get("tenants", {}).items(),
                  key=lambda kv: -kv[1].get("requests", 0))
    for name, c in rows:
        print(f"{name:<24}" + "".join(f"{c.get(h, 0):>11}"
                                      for h in hdr))
    tot = body.get("totals", {})
    print(f"{'TOTAL':<24}" + "".join(f"{tot.get(h, 0):>11}"
                                     for h in hdr))
    return 0


def _debug_slo(args) -> int:
    """``debug slo``: the burn-rate verdict round trip."""
    url = args.url
    if "/debug/slo" not in url:
        url = url.rstrip("/") + "/debug/slo"
    try:
        body = _fetch_json(url, args.timeout)
    except Exception as e:  # noqa: BLE001
        print(f"fetch failed: {e!r}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body))
        return 0
    print(f"windows: fast={body.get('fast_window_s')}s "
          f"slow={body.get('slow_window_s')}s "
          f"threshold={body.get('burn_threshold')} "
          f"ticks={body.get('ticks')}")
    for r in body.get("slos", []):
        name = r["slo"]
        if r.get("tenant"):
            name += f"[{r['tenant']}]"
        state = "BREACH" if r.get("breached") else "ok"
        line = (f"  {name:<40} {state:<7} "
                f"fast={r.get('fast_burn'):<8} "
                f"slow={r.get('slow_burn'):<8}")
        if r.get("value") is not None:
            line += (f" value={r['value']} target={r['target']}")
        print(line)
    return 0


def _debug_memory(args) -> int:
    """``debug memory``: the device-memory ledger round trip."""
    url = args.url
    if "/debug/memory" not in url:
        url = url.rstrip("/") + "/debug/memory"
    if args.advise:
        url += ("&" if "?" in url else "?") + "advise=1"
    try:
        body = _fetch_json(url, args.timeout)
    except Exception as e:  # noqa: BLE001
        print(f"fetch failed: {e!r}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body))
        return 0
    print(f"device_bytes={body.get('device_bytes')} "
          f"host_bytes={body.get('host_bytes')} "
          f"pressure={body.get('pressure'):.4f} "
          f"target={body.get('pressure_target')}")
    for name, rec in sorted(body.get("consumers", {}).items()):
        if "error" in rec:
            print(f"  {name:<14} ERROR {rec['error']}")
            continue
        side = "host" if rec.get("host") else "hbm"
        line = (f"  {name:<14} {side:<4} bytes={rec['bytes']:<12} "
                f"rows={rec['occupied_rows']}/{rec['capacity_rows']}")
        if rec.get("advisable"):
            line += " advisable"
        print(line)
    adv = body.get("advise")
    if adv:
        print(f"advised split over {adv['total_rows']} rows "
              f"(floor {adv['floor_rows']}):")
        for name in sorted(adv.get("advised", {})):
            print(f"  {name:<14} {adv['current'].get(name, 0):>8} "
                  f"-> {adv['advised'][name]:>8} "
                  f"(pow2 {adv['advised_pow2'][name]})")
    return 0


def _debug_faults(args) -> int:
    """``debug faults``: round-trip the daemon's fault-injection state
    (GET /debug/faults; --set/--clear POST a new spec)."""
    import urllib.request

    url = args.url.rstrip("/") + "/debug/faults"
    try:
        if args.clear or args.spec is not None:
            payload = ({"clear": True} if args.clear
                       else {"spec": args.spec, "seed": args.seed})
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=args.timeout) as f:
                body = json.loads(f.read())
        else:
            body = _fetch_json(url, args.timeout)
    except Exception as e:  # noqa: BLE001
        print(f"fetch failed: {e!r}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body))
        return 0
    state = "ARMED" if body.get("armed") else "disarmed"
    print(f"faults {state} (seed={body.get('seed')}) "
          f"spec={body.get('spec') or '-'}")
    for p in body.get("points", []):
        tag = f"@{p['tag']}" if p.get("tag") else ""
        extra = (f" delay={p['delay_ms']}ms" if p.get("delay_ms")
                 else "")
        print(f"  {p['point']}{tag}:{p['mode']} p={p['prob']}{extra} "
              f"checked={p['checked']} fired={p['fired']}")
    if not body.get("points"):
        print(f"  (catalog: {', '.join(body.get('catalog', []))})")
    return 0


def _fleet_main(argv) -> int:
    """``fleet {status,audit,topkeys,tenants,slo,memory}``: fetch the
    matching /debug endpoint from EVERY --url daemon and fold the
    documents through fleet.py's exact merges.  Exit 1 on fetch
    failure or a failed conservation/consistency verdict, so the
    command doubles as a cluster health probe."""
    ap = argparse.ArgumentParser(
        prog="guber-cli fleet",
        description="cluster-wide folds over every daemon's debug "
                    "endpoints (fleet.py)")
    sub = ap.add_subparsers(dest="what", required=True)
    helps = {
        "status": "healthz rollup + ring consistency + conservation",
        "audit": "fold the daemons' conservation audit vectors",
        "topkeys": "cluster top-K via the exact Space-Saving merge",
        "tenants": "fleet tenant RED rollup (sum-asserted)",
        "slo": "fleet SLO burn rollup (worst-of latch + summed burn)",
        "memory": "fleet memory-ledger pressure",
    }
    for what, h in helps.items():
        p = sub.add_parser(what, help=h)
        p.add_argument("--url", action="append", dest="urls",
                       default=None,
                       help="daemon HTTP base url (repeat per daemon; "
                            "default http://localhost:1050)")
        p.add_argument("--timeout", type=float, default=10.0)
        p.add_argument("--json", action="store_true",
                       help="print the raw folded JSON document")
        if what == "topkeys":
            p.add_argument("--limit", type=int, default=0,
                           help="only the heaviest N keys")
    args = ap.parse_args(argv)
    urls = args.urls or ["http://localhost:1050"]
    endpoint = {"status": "/healthz", "audit": "/debug/audit",
                "topkeys": "/debug/topkeys",
                "tenants": "/debug/tenants", "slo": "/debug/slo",
                "memory": "/debug/memory"}[args.what]

    def _fan(path):
        docs = []
        for base in urls:
            try:
                docs.append(_fetch_json(
                    base.rstrip("/") + path, args.timeout))
            except Exception as e:  # noqa: BLE001
                print(f"fetch failed ({base}{path}): {e!r}",
                      file=sys.stderr)
                return None
        return docs

    docs = _fan(endpoint)
    if docs is None:
        return 1
    from .. import fleet

    if args.what == "status":
        audits = _fan("/debug/audit")
        body = fleet.merge_status(docs, audits)
        if args.json:
            print(json.dumps(body))
        else:
            print(f"daemons: {body['healthy']}/{body['daemons']} "
                  f"healthy  peer_counts={body['peer_counts']}")
            ring = body.get("ring")
            if ring:
                state = ("consistent" if ring["consistent"] else
                         "DIVERGED(" + ",".join(ring["reasons"]) + ")")
                print(f"ring: {state}  ejected={ring['ejected']}")
            cons = body.get("conservation")
            if cons:
                print(f"conservation: drift={cons['drift']} "
                      f"{'OK' if cons['conserved'] else 'DRIFTING'}")
        ring_ok = (body.get("ring") or {}).get("consistent", True)
        cons_ok = (body.get("conservation")
                   or {}).get("conserved", True)
        return 0 if (body["healthy"] == body["daemons"] and ring_ok
                     and cons_ok) else 1
    if args.what == "audit":
        body = fleet.fold_audits(docs)
        body["ring"] = fleet.ring_verdict(docs)
        if args.json:
            print(json.dumps(body))
        else:
            t = body["totals"]
            print(f"fleet drift: {body['drift']} "
                  f"({'CONSERVED' if body['conserved'] else 'DRIFT'})"
                  f"  bound={body['bound_s']}s "
                  f"staleness<={body['staleness_bound_s']}s")
            print(f"  injected={t['injected']} applied={t['applied']} "
                  f"queued={t['queued']} in_flight={t['in_flight']} "
                  f"lost={t['lost']} deg_pending={t['deg_pending']}")
            if t["mesh_injected"] or t["mesh_folded"]:
                print(f"  mesh: injected={t['mesh_injected']} "
                      f"folded={t['mesh_folded']}")
            for r in body["per_daemon"]:
                print(f"  {r['instance'] or '?':<24} "
                      f"drift={r['drift']:<8} queued={r['queued']:<8} "
                      f"in_flight={r['in_flight']:<6} "
                      f"lost={r['lost']:<6} "
                      f"drain_age={r['drain_age_s']}s")
            ring = body["ring"]
            state = ("consistent" if ring["consistent"] else
                     "DIVERGED(" + ",".join(ring["reasons"]) + ")")
            print(f"ring: {state} across {ring['daemons']} daemon(s)")
        return 0 if (body["conserved"]
                     and body["ring"]["consistent"]) else 1
    if args.what == "topkeys":
        body = fleet.merge_topkeys(docs, k=args.limit or None)
        if args.json:
            print(json.dumps(body))
        else:
            print(f"fleet top-{body['k']} of "
                  f"~{body['total_hits_observed']} hits across "
                  f"{body['daemons']} daemon(s) "
                  f"(admission_err<={body['admission_error_bound']})")
            for e in body["keys"]:
                name = e.get("key") or e.get("khash")
                line = (f"{e.get('hits'):>12}  "
                        f"over={e.get('over_limit'):<8} "
                        f"err<={e.get('err'):<6} {name}")
                if e.get("owner"):
                    line += f"  owner={e['owner']}"
                print(line)
        return 0
    if args.what == "tenants":
        body = fleet.merge_tenants(docs)
        if args.json:
            print(json.dumps(body))
        else:
            print(f"fleet tenants: {body['tenant_count']} across "
                  f"{body['enabled_daemons']}/{body['daemons']} "
                  f"daemon(s)  "
                  f"{'SUM-OK' if body['conserved'] else 'SUM-MISMATCH'}")
            hdr = ("requests", "hits", "over_limit", "errors",
                   "degraded", "shed")
            print(f"{'tenant':<24}"
                  + "".join(f"{h:>11}" for h in hdr))
            rows = sorted(body["tenants"].items(),
                          key=lambda kv: -kv[1].get("requests", 0))
            for name, c in rows:
                print(f"{name:<24}"
                      + "".join(f"{c.get(h, 0):>11}" for h in hdr))
            tot = body["totals"]
            print(f"{'TOTAL':<24}"
                  + "".join(f"{tot.get(h, 0):>11}" for h in hdr))
        return 0 if body["conserved"] else 1
    if args.what == "slo":
        body = fleet.merge_slo(docs)
        if args.json:
            print(json.dumps(body))
        else:
            print(f"fleet SLOs across {body['daemons']} daemon(s), "
                  f"{body['ticks']} ticks; "
                  f"breached: {body['breached'] or 'none'}")
            for r in body["slos"]:
                name = r["slo"]
                if r.get("tenant"):
                    name += f"[{r['tenant']}]"
                state = "BREACH" if r["breached"] else "ok"
                line = (f"  {name:<40} {state:<7} "
                        f"fast_max={r['fast_burn_max']:<8} "
                        f"fast_sum={r['fast_burn_sum']:<8}")
                if r.get("value_max") is not None:
                    line += (f" value_max={r['value_max']} "
                             f"target={r.get('target')}")
                print(line)
        return 0
    body = fleet.merge_memory(docs)
    if args.json:
        print(json.dumps(body))
    else:
        print(f"fleet memory: device={body['device_bytes']} "
              f"host={body['host_bytes']} "
              f"max_pressure={body['max_pressure']}")
        for name, b in sorted(body["consumer_bytes"].items()):
            print(f"  {name:<14} bytes={b}")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "debug":
        return _debug_main(argv[1:])
    if argv and argv[0] == "fleet":
        return _fleet_main(argv[1:])
    ap = argparse.ArgumentParser(description="gubernator-tpu load tester")
    ap.add_argument("--address", default="localhost:1051")
    ap.add_argument("--http", action="store_true",
                    help="use the HTTP/JSON gateway instead of gRPC")
    ap.add_argument("--rate-limits", type=int, default=100_000,
                    help="distinct keys")
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--batch", type=int, default=1000)
    ap.add_argument("--duration", type=float, default=10.0, help="seconds")
    ap.add_argument("--zipf", type=float, default=1.1,
                    help="Zipf skew (0 = uniform)")
    ap.add_argument("--limit", type=int, default=100)
    ap.add_argument("--window", type=int, default=10_000, help="ms")
    ap.add_argument("--json", action="store_true", help="one-line JSON out")
    args = ap.parse_args(argv)

    from ..client import Client, HttpClient
    from ..types import RateLimitRequest

    def draw_keys(rng, n):
        if args.zipf > 1.0:
            return rng.zipf(args.zipf, size=n) % args.rate_limits
        return rng.integers(0, args.rate_limits, size=n)

    def mk_client():
        if args.http:
            return HttpClient(f"http://{args.address}")
        return Client(args.address)

    stop = time.monotonic() + args.duration
    lats: list = []
    counts = [0] * args.concurrency
    over = [0] * args.concurrency
    errs: list = []
    lock = threading.Lock()

    def worker(w: int):
        c = mk_client()
        rng = np.random.default_rng(w)  # Generator is not thread-safe
        while time.monotonic() < stop:
            keys = draw_keys(rng, args.batch)
            reqs = [RateLimitRequest(
                name="load", unique_key=f"k{k}", hits=1, limit=args.limit,
                duration=args.window) for k in keys]
            t0 = time.perf_counter()
            try:
                resps = c.get_rate_limits(reqs)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errs.append(str(e) or repr(e))
                return
            dt = time.perf_counter() - t0
            counts[w] += len(resps)
            over[w] += sum(1 for r in resps if int(r.status) == 1)
            with lock:
                lats.append(dt * 1000)

    threads = [threading.Thread(target=worker, args=(w,),
                                name=f"cli-bench-{w}")
               for w in range(args.concurrency)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    elapsed = time.monotonic() - t_start

    total = sum(counts)
    out = {
        "decisions": total,
        "decisions_per_s": round(total / max(elapsed, 1e-9)),
        "over_limit": sum(over),
        "p50_ms": round(float(np.percentile(lats, 50)), 3) if lats else None,
        "p99_ms": round(float(np.percentile(lats, 99)), 3) if lats else None,
        "batch": args.batch,
        "concurrency": args.concurrency,
        "errors": errs[:3],
    }
    if args.json:
        print(json.dumps(out))
    else:
        print(f"decisions: {out['decisions']} "
              f"({out['decisions_per_s']}/s)  over_limit: {out['over_limit']}")
        print(f"latency: p50={out['p50_ms']}ms p99={out['p99_ms']}ms "
              f"(batch={args.batch} x{args.concurrency} workers)")
        for e in errs[:3]:
            print("ERROR:", e, file=sys.stderr)
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())

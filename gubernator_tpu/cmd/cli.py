"""Load-test CLI: fire GetRateLimits traffic, report latency/throughput.

reference: cmd/gubernator-cli/main.go — reconstructed, mount empty.
Usage: python -m gubernator_tpu.cmd.cli --address host:port
       [--rate-limits N] [--concurrency C] [--batch B] [--duration S]
       [--zipf A] [--http]
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="gubernator-tpu load tester")
    ap.add_argument("--address", default="localhost:1051")
    ap.add_argument("--http", action="store_true",
                    help="use the HTTP/JSON gateway instead of gRPC")
    ap.add_argument("--rate-limits", type=int, default=100_000,
                    help="distinct keys")
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--batch", type=int, default=1000)
    ap.add_argument("--duration", type=float, default=10.0, help="seconds")
    ap.add_argument("--zipf", type=float, default=1.1,
                    help="Zipf skew (0 = uniform)")
    ap.add_argument("--limit", type=int, default=100)
    ap.add_argument("--window", type=int, default=10_000, help="ms")
    ap.add_argument("--json", action="store_true", help="one-line JSON out")
    args = ap.parse_args(argv)

    from ..client import Client, HttpClient
    from ..types import RateLimitRequest

    def draw_keys(rng, n):
        if args.zipf > 1.0:
            return rng.zipf(args.zipf, size=n) % args.rate_limits
        return rng.integers(0, args.rate_limits, size=n)

    def mk_client():
        if args.http:
            return HttpClient(f"http://{args.address}")
        return Client(args.address)

    stop = time.monotonic() + args.duration
    lats: list = []
    counts = [0] * args.concurrency
    over = [0] * args.concurrency
    errs: list = []
    lock = threading.Lock()

    def worker(w: int):
        c = mk_client()
        rng = np.random.default_rng(w)  # Generator is not thread-safe
        while time.monotonic() < stop:
            keys = draw_keys(rng, args.batch)
            reqs = [RateLimitRequest(
                name="load", unique_key=f"k{k}", hits=1, limit=args.limit,
                duration=args.window) for k in keys]
            t0 = time.perf_counter()
            try:
                resps = c.get_rate_limits(reqs)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errs.append(str(e))
                return
            dt = time.perf_counter() - t0
            counts[w] += len(resps)
            over[w] += sum(1 for r in resps if int(r.status) == 1)
            with lock:
                lats.append(dt * 1000)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(args.concurrency)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t_start

    total = sum(counts)
    out = {
        "decisions": total,
        "decisions_per_s": round(total / max(elapsed, 1e-9)),
        "over_limit": sum(over),
        "p50_ms": round(float(np.percentile(lats, 50)), 3) if lats else None,
        "p99_ms": round(float(np.percentile(lats, 99)), 3) if lats else None,
        "batch": args.batch,
        "concurrency": args.concurrency,
        "errors": errs[:3],
    }
    if args.json:
        print(json.dumps(out))
    else:
        print(f"decisions: {out['decisions']} "
              f"({out['decisions_per_s']}/s)  over_limit: {out['over_limit']}")
        print(f"latency: p50={out['p50_ms']}ms p99={out['p99_ms']}ms "
              f"(batch={args.batch} x{args.concurrency} workers)")
        for e in errs[:3]:
            print("ERROR:", e, file=sys.stderr)
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())

"""Command-line entry points (reference: cmd/ binaries)."""


def maybe_pin_platform() -> None:
    """Honor GUBER_JAX_PLATFORM=cpu|tpu before any backend init.

    Must go through jax.config: some sandboxes overwrite the
    jax_platforms config at interpreter start, so the JAX_PLATFORMS env
    var alone is ignored.  Every jax-using CLI calls this first.
    """
    import os

    plat = os.environ.get("GUBER_JAX_PLATFORM", "")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

"""Container healthcheck: exit 0 iff the daemon reports healthy.

reference: cmd/healthcheck/main.go — reconstructed, mount empty.
Usage: python -m gubernator_tpu.cmd.healthcheck [--url URL]
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--url", default="http://localhost:1050/v1/HealthCheck")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    try:
        with urllib.request.urlopen(args.url, timeout=args.timeout) as f:
            body = json.loads(f.read())
    except Exception as e:  # noqa: BLE001
        print(f"unhealthy: {e}", file=sys.stderr)
        return 1
    if body.get("status") != "healthy":
        print(f"unhealthy: {body}", file=sys.stderr)
        return 1
    print("healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Container healthcheck: exit 0 iff the daemon reports healthy.

reference: cmd/healthcheck/main.go — reconstructed, mount empty.
Usage: python -m gubernator_tpu.cmd.healthcheck [--url URL] [--deep]

``--deep`` requests the daemon's deep health mode (``/healthz?deep=1``)
and prints the dispatcher block (queue depth, last-wave age, stalled
state — see OBSERVABILITY.md).  A diagnosed stall does NOT flip the
exit code by itself (a cold compile recovers on its own; restarting the
container mid-compile would make it worse) unless ``--fail-on-stall``
is also given.

``--fail-on-burn`` (implies deep mode) exits 1 while any SLO is in the
breached state (slo.py) — the k8s READINESS hook: a pod burning its
error budget stops taking traffic before it pages an operator, and
recovery (fast-window burn back under the threshold) re-admits it.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from urllib.parse import urlencode, urlsplit, urlunsplit


def _with_deep(url: str) -> str:
    """Append deep=1 to the url's query string (preserving any query)."""
    parts = urlsplit(url)
    q = parts.query + ("&" if parts.query else "") + urlencode({"deep": 1})
    return urlunsplit((parts.scheme, parts.netloc, parts.path, q,
                       parts.fragment))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--url", default="http://localhost:1050/v1/HealthCheck")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--deep", action="store_true",
                    help="request dispatcher queue/wave/stall state "
                         "(/healthz?deep=1) and print it")
    ap.add_argument("--fail-on-stall", action="store_true",
                    help="with --deep: exit 1 when the dispatcher "
                         "reports a stalled wave")
    ap.add_argument("--fail-on-burn", action="store_true",
                    help="exit 1 when any SLO is breached (implies "
                         "--deep; the k8s readiness hook — a burning "
                         "pod stops taking traffic before it pages)")
    args = ap.parse_args(argv)
    deep = args.deep or args.fail_on_burn
    url = _with_deep(args.url) if deep else args.url
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as f:
            body = json.loads(f.read())
    except Exception as e:  # noqa: BLE001
        # str() of a socket timeout can be empty — keep the repr
        print(f"unhealthy: {e!r}", file=sys.stderr)
        return 1
    if body.get("status") != "healthy":
        print(f"unhealthy: {body}", file=sys.stderr)
        return 1
    slo = body.get("slo")
    if args.fail_on_burn and slo is not None:
        if slo.get("breached"):
            print(f"SLO breached: {', '.join(slo['breached'])} "
                  f"(max_fast_burn={slo.get('max_fast_burn')}, "
                  f"threshold={slo.get('burn_threshold')})",
                  file=sys.stderr)
            return 1
        print("slo:", json.dumps(slo, sort_keys=True))
    disp = body.get("dispatcher")
    if args.deep and disp is not None:
        print("dispatcher:", json.dumps(disp, sort_keys=True))
        if disp.get("stalled"):
            print("WARNING: dispatcher reports a stalled wave "
                  f"(oldest_wave_age_s={disp.get('oldest_wave_age_s')}, "
                  f"threshold={disp.get('stall_threshold_s')}s) — "
                  "likely a cold device compile in flight",
                  file=sys.stderr)
            if args.fail_on_stall:
                return 1
    print("healthy")
    return 0


if __name__ == "__main__":
    sys.exit(main())

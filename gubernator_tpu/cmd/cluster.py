"""Boot a local N-daemon cluster (development tool).

reference: cmd/gubernator-cluster/main.go — reconstructed, mount empty.
Usage: python -m gubernator_tpu.cmd.cluster [--count N] [--base-port P]
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="local gubernator-tpu cluster")
    ap.add_argument("--count", type=int, default=4)
    ap.add_argument("--base-port", type=int, default=9080)
    ap.add_argument("--cache-size", type=int, default=1 << 16)
    args = ap.parse_args(argv)

    from . import maybe_pin_platform

    maybe_pin_platform()

    from ..cluster import start_with
    from ..config import DaemonConfig

    cfgs = [DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{args.base_port + 2 * i}",
        http_listen_address=f"127.0.0.1:{args.base_port + 2 * i + 1}",
        cache_size=args.cache_size) for i in range(args.count)]
    c = start_with(cfgs)
    for i, d in enumerate(c.daemons):
        print(f"daemon[{i}] grpc={d.cfg.grpc_listen_address} "
              f"http={d.cfg.http_listen_address}", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    c.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

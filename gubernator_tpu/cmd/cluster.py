"""Boot a local N-daemon cluster (development tool).

reference: cmd/gubernator-cluster/main.go — reconstructed, mount empty.
Usage: python -m gubernator_tpu.cmd.cluster [--count N] [--base-port P]
       python -m gubernator_tpu.cmd.cluster --group [--client-port P]

--group boots the SO_REUSEPORT front-door shape instead (OS processes
sharing one client port, each with its own engine and GIL —
ARCHITECTURE.md §3.1); without it, daemons run in-process on unique
ports (the functional-test topology).
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="local gubernator-tpu cluster")
    ap.add_argument("--count", type=int, default=4)
    ap.add_argument("--base-port", type=int, default=9080)
    ap.add_argument("--cache-size", type=int, default=1 << 16)
    ap.add_argument("--group", action="store_true",
                    help="SO_REUSEPORT subprocess group sharing one "
                         "client port")
    ap.add_argument("--client-port", type=int, default=0,
                    help="with --group: shared client port "
                         "(0 = OS-assigned)")
    args = ap.parse_args(argv)

    if args.group and args.base_port != ap.get_default("base_port"):
        ap.error("--base-port applies only without --group (group "
                 "workers use OS-assigned peer ports; use --client-port "
                 "for the shared front door)")

    from . import maybe_pin_platform

    maybe_pin_platform()

    def _serve(handle):
        """Install signal handlers only AFTER startup, so Ctrl-C during
        a slow/hung boot still interrupts (KeyboardInterrupt) instead of
        setting an event nothing reads yet."""
        stop = threading.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: stop.set())
        stop.wait()
        handle.stop()

    if args.group:
        from ..cluster import start_subprocess_group

        g = start_subprocess_group(args.count, cache_size=args.cache_size,
                                   client_port=args.client_port)
        print(f"group client={g.client_address}", flush=True)
        for i, addr in enumerate(g.grpc_addresses):
            print(f"worker[{i}] peer-grpc={addr} "
                  f"http={g.http_addresses[i]}", flush=True)
        _serve(g)
        return 0

    from ..cluster import start_with
    from ..config import DaemonConfig

    cfgs = [DaemonConfig(
        grpc_listen_address=f"127.0.0.1:{args.base_port + 2 * i}",
        http_listen_address=f"127.0.0.1:{args.base_port + 2 * i + 1}",
        cache_size=args.cache_size) for i in range(args.count)]
    c = start_with(cfgs)
    for i, d in enumerate(c.daemons):
        print(f"daemon[{i}] grpc={d.cfg.grpc_listen_address} "
              f"http={d.cfg.http_listen_address}", flush=True)

    _serve(c)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""In-process multi-daemon cluster harness.

reference: cluster/cluster.go › Start / StartWith / Restart / Stop —
reconstructed, mount empty.  Boots real daemons (real gRPC over
loopback) inside one process, exactly like the reference's functional
test setup; tests then drive daemon 0 with a real client.

All daemons share one JAX device set; each gets its own device table on
the same mesh, so identical shapes reuse one compiled step program.
"""
from __future__ import annotations

import logging
from typing import List, Optional

from .config import BehaviorConfig, DaemonConfig
from .daemon import Daemon, spawn_daemon
from .netutil import free_port
from .types import PeerInfo

log = logging.getLogger("gubernator_tpu.cluster")


class Cluster:
    def __init__(self, daemons: List[Daemon]):
        self.daemons = daemons

    # reference: cluster.go naming
    def peer_at(self, i: int) -> PeerInfo:
        return self.daemons[i].peer_info()

    def instance_at(self, i: int):
        return self.daemons[i].instance

    def daemon_at(self, i: int) -> Daemon:
        return self.daemons[i]

    def grpc_address(self, i: int = 0) -> str:
        return self.daemons[i].advertise_address

    def http_address(self, i: int = 0) -> str:
        return f"http://{self.daemons[i].cfg.http_listen_address}"

    def owner_daemon_of(self, key: str) -> "Daemon":
        """The daemon owning ``key`` (via daemon 0's picker)."""
        owner = self.daemons[0].instance.owner_of(key)
        addr = owner.info.grpc_address
        for d in self.daemons:
            if d.advertise_address == addr:
                return d
        raise AssertionError(f"no daemon for owner {addr}")

    def restart(self, i: int) -> Daemon:
        """Stop and re-spawn daemon i on the same addresses
        (cluster.go › Restart)."""
        old = self.daemons[i]
        cfg, mesh = old.cfg, old.instance.engine.mesh
        old.close()
        d = spawn_daemon(cfg, mesh=mesh)
        self.daemons[i] = d
        infos = [dm.peer_info() for dm in self.daemons]
        for dm in self.daemons:
            dm.set_peers(infos)
        return d

    def stop(self) -> None:
        for d in self.daemons:
            d.close()


def start(n: int, mesh=None, behaviors: Optional[BehaviorConfig] = None,
          cache_size: int = 1 << 12, batch_rows: int = 64,
          **cfg_kwargs) -> Cluster:
    """Boot ``n`` daemons on localhost free ports and join them
    (cluster.go › Start)."""
    cfgs = []
    for _ in range(n):
        cfgs.append(DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{free_port()}",
            http_listen_address=f"127.0.0.1:{free_port()}",
            cache_size=cache_size,
            behaviors=behaviors or BehaviorConfig(),
            **cfg_kwargs))
    return start_with(cfgs, mesh=mesh, batch_rows=batch_rows)


def start_with(cfgs: List[DaemonConfig], mesh=None,
               batch_rows: int = 64) -> Cluster:
    """Boot daemons from explicit configs and join them
    (cluster.go › StartWith)."""
    from .parallel import ShardedEngine, make_mesh

    if mesh is None:
        mesh = make_mesh()
    daemons: List[Daemon] = []
    for cfg in cfgs:
        n_dev = mesh.shape["shard"]
        cap_local = max(cfg.cache_size // n_dev, 256)
        cap_local = 1 << (cap_local - 1).bit_length()
        from .parallel.sharded import autogrow_limit_per_shard

        engine = ShardedEngine(
            mesh, capacity_per_shard=cap_local, batch_per_shard=batch_rows,
            auto_grow_limit=autogrow_limit_per_shard(
                cfg.cache_autogrow_max, n_dev, cap_local))
        daemons.append(spawn_daemon(cfg, mesh=mesh, engine=engine))
    infos = [d.peer_info() for d in daemons]
    for d in daemons:
        d.set_peers(infos)
    return Cluster(daemons)

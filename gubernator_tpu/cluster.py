"""In-process multi-daemon cluster harness.

reference: cluster/cluster.go › Start / StartWith / Restart / Stop —
reconstructed, mount empty.  Boots real daemons (real gRPC over
loopback) inside one process, exactly like the reference's functional
test setup; tests then drive daemon 0 with a real client.

All daemons share one JAX device set; each gets its own device table on
the same mesh, so identical shapes reuse one compiled step program.
"""
from __future__ import annotations

import logging
from typing import List, Optional

from .config import BehaviorConfig, DaemonConfig
from .daemon import Daemon, spawn_daemon
from .netutil import free_port
from .types import PeerInfo

log = logging.getLogger("gubernator_tpu.cluster")


class Cluster:
    def __init__(self, daemons: List[Daemon]):
        self.daemons = daemons

    # reference: cluster.go naming
    def peer_at(self, i: int) -> PeerInfo:
        return self.daemons[i].peer_info()

    def instance_at(self, i: int):
        return self.daemons[i].instance

    def daemon_at(self, i: int) -> Daemon:
        return self.daemons[i]

    def grpc_address(self, i: int = 0) -> str:
        return self.daemons[i].advertise_address

    def http_address(self, i: int = 0) -> str:
        return f"http://{self.daemons[i].cfg.http_listen_address}"

    def owner_daemon_of(self, key: str) -> "Daemon":
        """The daemon owning ``key`` (via daemon 0's picker)."""
        owner = self.daemons[0].instance.owner_of(key)
        addr = owner.info.grpc_address
        for d in self.daemons:
            if d.advertise_address == addr:
                return d
        raise AssertionError(f"no daemon for owner {addr}")

    def restart(self, i: int) -> Daemon:
        """Stop and re-spawn daemon i on the same addresses
        (cluster.go › Restart)."""
        old = self.daemons[i]
        cfg, mesh = old.cfg, old.instance.engine.mesh
        old.close()
        d = spawn_daemon(cfg, mesh=mesh)
        self.daemons[i] = d
        infos = [dm.peer_info() for dm in self.daemons]
        for dm in self.daemons:
            dm.set_peers(infos)
        return d

    def stop(self) -> None:
        for d in self.daemons:
            d.close()


def start(n: int, mesh=None, behaviors: Optional[BehaviorConfig] = None,
          cache_size: int = 1 << 12, batch_rows: int = 64,
          **cfg_kwargs) -> Cluster:
    """Boot ``n`` daemons on localhost free ports and join them
    (cluster.go › Start)."""
    cfgs = []
    for _ in range(n):
        cfgs.append(DaemonConfig(
            grpc_listen_address=f"127.0.0.1:{free_port()}",
            http_listen_address=f"127.0.0.1:{free_port()}",
            cache_size=cache_size,
            behaviors=behaviors or BehaviorConfig(),
            **cfg_kwargs))
    return start_with(cfgs, mesh=mesh, batch_rows=batch_rows)


class SubprocessGroup:
    """A SO_REUSEPORT daemon group: ``n`` OS processes share one
    client-facing gRPC port (the kernel balances inbound connections)
    while clustering over unique per-process peer ports.

    This is the front-door scaling answer for a GIL-bound host (VERDICT
    r1 item 5): each process has its own interpreter lock and its own
    engine, keys are ring-split across the group, and non-owned
    sub-batches ride the raw-TLV peer wire lane.  On a TPU host the
    same shape runs ingest workers on the CPU backend alongside one
    device-owner daemon (see ARCHITECTURE.md §"front door").
    """

    def __init__(self, procs, client_address: str,
                 grpc_addresses: List[str], http_addresses: List[str],
                 log_paths: List[str]):
        self.procs = procs
        self.client_address = client_address
        self.grpc_addresses = grpc_addresses
        self.http_addresses = http_addresses
        self.log_paths = log_paths

    def stop(self, remove_logs: bool = True) -> None:
        import os as _os
        import signal as _signal

        for p in self.procs:
            if p.poll() is None:
                p.send_signal(_signal.SIGTERM)
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                p.kill()
                p.wait(timeout=5)
        if remove_logs:
            for lp in self.log_paths:
                try:
                    _os.unlink(lp)
                except OSError:
                    pass


def start_subprocess_group(n: int, cache_size: int = 1 << 16,
                           batch_rows: int = 1024,
                           ready_timeout: float = 120.0,
                           env_extra: Optional[dict] = None,
                           client_port: int = 0) -> SubprocessGroup:
    """Spawn ``n`` daemon subprocesses sharing one SO_REUSEPORT client
    port, statically clustered over unique peer ports.  Blocks until
    every process answers grpc.health.v1 SERVING on its peer port.

    Subprocesses are pinned to the CPU backend (JAX_PLATFORMS=cpu): a
    single TPU chip cannot be opened by several processes, and the
    group exists to scale the HOST side; see SubprocessGroup docstring
    for the heterogeneous TPU deployment shape.
    """
    import os
    import subprocess
    import sys
    import tempfile
    import time

    import grpc as _grpc

    client_address = f"127.0.0.1:{client_port or free_port()}"

    def draw_port() -> int:
        # never hand a worker the user-chosen client port: the daemon
        # would try to bind it both as its peer listener and as the
        # SO_REUSEPORT front door, and fail confusingly
        while True:
            p = free_port()
            if p != client_port:
                return p

    grpc_addresses = [f"127.0.0.1:{draw_port()}" for _ in range(n)]
    http_addresses = [f"127.0.0.1:{draw_port()}" for _ in range(n)]
    procs, log_paths = [], []
    try:
        for i in range(n):
            env = dict(os.environ)
            env.update({
                "GUBER_CLIENT_ADDRESS": client_address,
                "GUBER_GRPC_ADDRESS": grpc_addresses[i],
                "GUBER_HTTP_ADDRESS": http_addresses[i],
                "GUBER_PEER_DISCOVERY_TYPE": "static",
                "GUBER_PEERS": ",".join(grpc_addresses),
                "GUBER_CACHE_SIZE": str(cache_size),
                "GUBER_BATCH_ROWS": str(batch_rows),
                "GUBER_INSTANCE_ID": f"group-{i}",
                "JAX_PLATFORMS": "cpu",
                # belt and braces: some sandboxes reset jax_platforms
                # at interpreter start; the CLI re-pins via jax.config
                "GUBER_JAX_PLATFORM": "cpu",
            })
            env.update(env_extra or {})
            lf = tempfile.NamedTemporaryFile(
                mode="wb", prefix=f"guber-group-{i}-", suffix=".log",
                delete=False)
            log_paths.append(lf.name)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "gubernator_tpu.cmd.daemon"],
                stdout=lf, stderr=subprocess.STDOUT, env=env))
            lf.close()
    except BaseException:
        # a failed spawn (fd limit, ENOMEM) must not orphan the
        # daemons that did start
        SubprocessGroup(procs, client_address, grpc_addresses,
                        http_addresses, log_paths).stop(remove_logs=False)
        raise
    group = SubprocessGroup(procs, client_address, grpc_addresses,
                            http_addresses, log_paths)
    deadline = time.monotonic() + ready_timeout
    try:
        for i, addr in enumerate(grpc_addresses):
            ch = _grpc.insecure_channel(addr)
            try:
                check = ch.unary_unary("/grpc.health.v1.Health/Check")
                while True:
                    if procs[i].poll() is not None:
                        with open(log_paths[i], "rb") as lf2:
                            tail = lf2.read()[-2000:]
                        raise RuntimeError(
                            f"group daemon {i} exited "
                            f"rc={procs[i].returncode}: {tail!r}")
                    try:
                        if check(b"", timeout=2.0) == bytes([0x08, 0x01]):
                            break
                    except _grpc.RpcError:
                        pass
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"group daemon {i} not SERVING within "
                            f"{ready_timeout}s (log: {log_paths[i]})")
                    time.sleep(0.25)
            finally:
                ch.close()
    except BaseException:
        # keep the log files: the raised error cites their paths
        group.stop(remove_logs=False)
        raise
    return group


def start_with(cfgs: List[DaemonConfig], mesh=None,
               batch_rows: int = 64) -> Cluster:
    """Boot daemons from explicit configs and join them
    (cluster.go › StartWith)."""
    from .parallel import ShardedEngine, make_mesh

    if mesh is None:
        mesh = make_mesh()
    daemons: List[Daemon] = []
    for cfg in cfgs:
        n_dev = mesh.shape["shard"]
        cap_local = max(cfg.cache_size // n_dev, 256)
        cap_local = 1 << (cap_local - 1).bit_length()
        from .parallel.sharded import autogrow_limit_per_shard

        engine = ShardedEngine(
            mesh, capacity_per_shard=cap_local, batch_per_shard=batch_rows,
            auto_grow_limit=autogrow_limit_per_shard(
                cfg.cache_autogrow_max, n_dev, cap_local))
        daemons.append(spawn_daemon(cfg, mesh=mesh, engine=engine))
    infos = [d.peer_info() for d in daemons]
    for d in daemons:
        d.set_peers(infos)
    return Cluster(daemons)

"""Runtime jit-compile ledger (ISSUE 14) — the dynamic half of the
``retrace`` lint pass.

The static pass proves jit call SITES are retrace-stable; this module
proves the RUNTIME agrees: it counts every XLA compile per function
name, snapshots the counts once the service path is warm
(:meth:`CompileLedger.mark_steady`), and renders a verdict — a warmed
daemon must show **zero** compiles after the mark.  A nonzero
steady-state count is the retrace bug class at runtime: a weak-typed
scalar or drifting dtype at some call site is silently recompiling the
serving program per wave, turning a ~µs dispatch into a ~100 ms
compile stall.

Hook mechanism: jax (0.4.x) logs one ``"Compiling <fn> ..."`` record
on the ``jax._src.interpreters.pxla`` logger per actual XLA
compilation — including every recompile of an already-jitted function
— at DEBUG level, independent of the ``jax_log_compiles`` config.  The
ledger installs a :class:`logging.Handler` there and sets the logger
to DEBUG with ``propagate = False`` (else the raised level would spray
compile logs to stderr through the root handler); uninstall restores
the previous level/propagate.  No jax internals are imported — a
missing/renamed logger degrades to an empty ledger, never an error.

Exposed surfaces:

- ``gubernator_jit_compiles_total{fn}`` on every attached per-instance
  metrics registry (OBSERVABILITY.md);
- the ``compile_ledger`` block on bench row ``6_service_path``
  (``verdict()``: total compiles, steady flag, per-fn recompile map);
- tier-1: tests/test_compileledger.py asserts zero steady-state
  recompiles on the service path and that a deliberate dtype-drift
  escape makes the detector fire.

``GUBER_COMPILE_LEDGER=0`` disables installation (the handler, while
cheap — one regex per compile, and compiles are rare by definition —
sits on a global logger, so operators get an off switch).
"""
from __future__ import annotations

import logging
import os
import re
import threading
import weakref
from typing import Dict, List, Optional

#: the logger jax's pxla lowering emits per-compile records on; pinned
#: by tests/test_compileledger.py so a jax upgrade that moves it fails
#: loudly instead of silently recording nothing
_JAX_COMPILE_LOGGER = "jax._src.interpreters.pxla"

#: "Compiling <fn> with global shapes and types ..." — fn is the
#: jitted callable's __name__ (wrappers like jit(<lambda>) included)
_COMPILE_RX = re.compile(r"^Compiling ([^\s]+)")


def enabled() -> bool:
    return os.environ.get("GUBER_COMPILE_LEDGER", "1") != "0"


class _LedgerHandler(logging.Handler):
    """Parses compile records into the owning ledger.  Never raises —
    a logging handler that throws poisons every subsequent log call."""

    def __init__(self, ledger: "CompileLedger"):
        super().__init__(level=logging.DEBUG)
        self._ledger = ledger

    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _COMPILE_RX.match(record.getMessage())
            if m:
                self._ledger._record_compile(m.group(1))
        except Exception:  # noqa: BLE001 - see class docstring
            pass


class CompileLedger:
    """Per-process compile counts + steady-state verdict.

    install()/uninstall() are idempotent; counts survive uninstall (a
    bench run uninstalls nothing, tests uninstall in teardown).
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._counts: Dict[str, int] = {}  # guarded-by: self._mu
        self._steady_base: Optional[Dict[str, int]] = None  # guarded-by: self._mu
        self._handler: Optional[_LedgerHandler] = None  # guarded-by: self._mu
        self._prev_level: Optional[int] = None  # guarded-by: self._mu
        self._prev_propagate: Optional[bool] = None  # guarded-by: self._mu
        #: weakrefs to attached Metrics objects (per-instance
        #: registries; a 3-daemon test cluster attaches three)
        self._metrics: List[weakref.ref] = []  # guarded-by: self._mu

    # -- install / uninstall --------------------------------------------

    def install(self) -> bool:
        """Attach the handler to the jax compile logger.  Returns True
        when installed (or already was), False when jax never created
        the logger in this process (nothing to observe yet is fine —
        logging.getLogger creates it eagerly, so this is always True
        in practice)."""
        with self._mu:
            if self._handler is not None:
                return True
            lg = logging.getLogger(_JAX_COMPILE_LOGGER)
            self._handler = _LedgerHandler(self)
            self._prev_level = lg.level
            self._prev_propagate = lg.propagate
            lg.addHandler(self._handler)
            # DEBUG so the per-compile records reach the handler;
            # propagate off so the raised level doesn't leak compile
            # spam to stderr via the root handler while we listen
            lg.setLevel(logging.DEBUG)
            lg.propagate = False
            return True

    def uninstall(self) -> None:
        with self._mu:
            if self._handler is None:
                return
            lg = logging.getLogger(_JAX_COMPILE_LOGGER)
            lg.removeHandler(self._handler)
            if self._prev_level is not None:
                lg.setLevel(self._prev_level)
            if self._prev_propagate is not None:
                lg.propagate = self._prev_propagate
            self._handler = None
            self._prev_level = None
            self._prev_propagate = None

    @property
    def installed(self) -> bool:
        with self._mu:
            return self._handler is not None

    # -- recording ------------------------------------------------------

    def _record_compile(self, fn: str) -> None:
        with self._mu:
            self._counts[fn] = self._counts.get(fn, 0) + 1
            sinks = [m() for m in self._metrics]
            self._metrics = [r for r, m in zip(self._metrics, sinks)
                             if m is not None]
        for m in sinks:  # metric bump outside _mu: leaf lock stays leaf
            if m is not None:
                try:
                    m.jit_compiles.labels(fn=fn).inc()
                except Exception:  # noqa: BLE001 - a torn-down registry
                    # must not break compile accounting
                    pass

    def attach_metrics(self, metrics) -> None:
        """Mirror per-fn compile counts into ``metrics.jit_compiles``
        (held weakly: a closed instance's registry just drops off)."""
        with self._mu:
            if any(r() is metrics for r in self._metrics):
                return
            self._metrics.append(weakref.ref(metrics))

    # -- reading --------------------------------------------------------

    def counts(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._counts)

    def total(self) -> int:
        with self._mu:
            return sum(self._counts.values())

    def reset(self) -> None:
        """Test hook: forget everything (counts AND steady mark)."""
        with self._mu:
            self._counts = {}
            self._steady_base = None

    # -- steady-state verdict -------------------------------------------

    def mark_steady(self) -> None:
        """Declare warmup over: compiles past this point are verdict
        failures.  Re-marking moves the baseline forward."""
        with self._mu:
            self._steady_base = dict(self._counts)

    def steady_compiles(self) -> Dict[str, int]:
        """Per-fn compiles since :meth:`mark_steady` (empty before the
        mark, and empty is the healthy answer after it)."""
        with self._mu:
            if self._steady_base is None:
                return {}
            out = {}
            for fn, n in self._counts.items():
                d = n - self._steady_base.get(fn, 0)
                if d > 0:
                    out[fn] = d
            return out

    def verdict(self) -> Dict[str, object]:
        """The bench/tier-1 provenance block: did the steady-state
        service path recompile?"""
        with self._mu:
            marked = self._steady_base is not None
            total = sum(self._counts.values())
            recompiles: Dict[str, int] = {}
            if marked:
                for fn, n in self._counts.items():
                    d = n - self._steady_base.get(fn, 0)
                    if d > 0:
                        recompiles[fn] = d
        return {
            "enabled": enabled(),
            "installed": self.installed,
            "marked_steady": marked,
            "total_compiles": total,
            "steady_recompiles": recompiles,
            "steady": marked and not recompiles,
        }


#: process-wide singleton: XLA compiles are process-wide events, so a
#: per-instance ledger would double-count a shared logger anyway
LEDGER = CompileLedger()


def install_if_enabled() -> bool:
    """Instance-construction hook: install the singleton unless
    GUBER_COMPILE_LEDGER=0.  Returns whether the ledger is live."""
    if not enabled():
        return False
    return LEDGER.install()

"""Tracing/profiling hooks (SURVEY.md §5.1).

The reference grew OpenTelemetry spans around handlers (otelgrpc
interceptors in daemon.go, span-per-request in gubernator.go —
version-dependent).  Here:

- ``span(name)`` wraps host-side sections; if the ``opentelemetry``
  SDK is installed it emits real OTEL spans, otherwise it degrades to
  a no-op that still feeds the prometheus duration histogram.
- ``device_profile(...)`` captures a jax.profiler trace of the device
  step (the TPU-side profiling story: view in TensorBoard/XProf).

Enable device profiling with GUBER_PROFILE_DIR=/path (daemon reads it).
"""
from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Iterator, Optional

log = logging.getLogger("gubernator_tpu.tracing")

try:  # pragma: no cover - OTEL not in this image; degrade gracefully
    from opentelemetry import trace as _otel_trace

    _tracer = _otel_trace.get_tracer("gubernator_tpu")
except ImportError:
    _tracer = None


# --- W3C trace-context propagation (traceparent in/out) ---------------
#
# The reference wires otelgrpc server/client interceptors (daemon.go),
# which propagate the W3C `traceparent` header across hops.  The OTEL
# SDK isn't required for that contract: the header format is a spec
# ("00-<32hex trace-id>-<16hex parent-span-id>-<2hex flags>"), so we
# parse/generate it natively and carry the active trace in a
# thread-local — servicers adopt the inbound header, and every peer
# call (pb2 or raw wire) re-emits it with a fresh span id.  When the
# OTEL SDK is present the `span()` context manager still opens real
# spans on top.

import secrets
import threading

_tls = threading.local()

#: Test/diagnostic hook: called with the RAW inbound traceparent header
#: (or None) each time a request context is adopted.
inbound_hook = None


def parse_traceparent(header: Optional[str]):
    """(trace_id_hex32, flags_hex2) or None for absent/malformed input
    (malformed → start a new trace, per the W3C spec's restart rule)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    tid, sid, flags = parts[1].lower(), parts[2].lower(), parts[3]
    if len(tid) != 32 or len(sid) != 16 or len(flags) != 2 \
            or tid == "0" * 32 or sid == "0" * 16:
        return None
    try:
        int(tid, 16), int(sid, 16), int(flags, 16)
    except ValueError:
        return None
    return tid, flags


def current_trace_id() -> Optional[str]:
    """The active request's 32-hex trace id, or None outside any
    request context.  Cheap enough for hot-path capture (the flight
    recorder and dispatcher jobs stamp it at submit time — worker
    threads have no request context of their own)."""
    tp = getattr(_tls, "trace", None)
    return tp[0] if tp is not None else None


def current_traceparent() -> Optional[str]:
    """Outbound header for the active request's trace (fresh span id
    per hop), or None outside any request context."""
    tp = getattr(_tls, "trace", None)
    if tp is None:
        return None
    tid, flags = tp
    return f"00-{tid}-{secrets.token_hex(8)}-{flags}"


@contextlib.contextmanager
def request_context(traceparent: Optional[str]) -> Iterator[None]:
    """Adopt an inbound traceparent — or start a new trace — for the
    handler's duration; peer calls made inside propagate the same
    trace id (otelgrpc server-interceptor parity)."""
    if inbound_hook is not None:
        inbound_hook(traceparent)
    parsed = parse_traceparent(traceparent)
    prev = getattr(_tls, "trace", None)
    _tls.trace = parsed or (secrets.token_hex(16), "01")
    try:
        yield
    finally:
        _tls.trace = prev


def grpc_request_context(context):
    """request_context from a grpc servicer context's metadata."""
    header = None
    try:
        for k, v in context.invocation_metadata():
            if k.lower() == "traceparent":
                header = v
                break
    except Exception:  # noqa: BLE001 - metadata is best-effort
        pass
    return request_context(header)


def outbound_metadata(extra=()):
    """grpc call metadata carrying the active trace (otelgrpc
    client-interceptor parity); None when there is neither a trace nor
    extra metadata."""
    tp = current_traceparent()
    md = list(extra)
    if tp is not None:
        md.append(("traceparent", tp))
    return md or None


@contextlib.contextmanager
def span(name: str, metrics=None) -> Iterator[None]:
    """Host-side span: OTEL when available, always a duration metric —
    including on the error path (try/finally)."""
    t0 = time.perf_counter()
    try:
        if _tracer is not None:  # pragma: no cover
            with _tracer.start_as_current_span(name):
                yield
        else:
            yield
    finally:
        if metrics is not None:
            metrics.func_duration.labels(name=name).observe(
                time.perf_counter() - t0)


class DeviceProfiler:
    """jax.profiler session around the serving loop.

    Usage: ``prof = DeviceProfiler.from_env(); ...; prof.stop()`` —
    writes an XProf trace for TensorBoard under the given directory.
    """

    def __init__(self, log_dir: str):
        import jax

        self.log_dir = log_dir
        jax.profiler.start_trace(log_dir)
        self._active = True
        log.info("device profiling → %s", log_dir)

    @classmethod
    def from_env(cls) -> Optional["DeviceProfiler"]:
        d = os.environ.get("GUBER_PROFILE_DIR", "")
        return cls(d) if d else None

    def stop(self) -> None:
        if self._active:
            self._active = False
            import jax

            jax.profiler.stop_trace()


@contextlib.contextmanager
def step_annotation(name: str) -> Iterator[None]:
    """Named region visible in device traces (jax.profiler.TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield

"""Tracing/profiling hooks (SURVEY.md §5.1).

The reference grew OpenTelemetry spans around handlers (otelgrpc
interceptors in daemon.go, span-per-request in gubernator.go —
version-dependent).  Here:

- ``span(name)`` wraps host-side sections; if the ``opentelemetry``
  SDK is installed it emits real OTEL spans, otherwise it degrades to
  a no-op that still feeds the prometheus duration histogram.
- ``SpanRecorder`` (ISSUE 12) keeps the structure: when a request
  context is armed with a recorder, every ``span()`` — and the
  dispatcher's wave spans — lands in a bounded per-daemon ring,
  head-sampled at ``GUBER_TRACE_SAMPLE`` with forced sampling on
  error/degraded/shed outcomes.  ``GET /debug/traces`` exports the
  ring; ``assemble()``/``render_waterfall()`` stitch per-daemon
  slices into a cluster-wide tree (tools/trace_assemble.py).
- ``device_profile(...)`` captures a jax.profiler trace of the device
  step (the TPU-side profiling story: view in TensorBoard/XProf).

Enable device profiling with GUBER_PROFILE_DIR=/path (daemon reads it).
"""
from __future__ import annotations

import contextlib
import logging
import os
import time
from collections import OrderedDict, deque
from typing import Dict, Iterator, List, Optional

log = logging.getLogger("gubernator_tpu.tracing")

try:  # pragma: no cover - OTEL not in this image; degrade gracefully
    from opentelemetry import trace as _otel_trace

    _tracer = _otel_trace.get_tracer("gubernator_tpu")
except ImportError:
    _tracer = None


# --- W3C trace-context propagation (traceparent in/out) ---------------
#
# The reference wires otelgrpc server/client interceptors (daemon.go),
# which propagate the W3C `traceparent` header across hops.  The OTEL
# SDK isn't required for that contract: the header format is a spec
# ("00-<32hex trace-id>-<16hex parent-span-id>-<2hex flags>"), so we
# parse/generate it natively and carry the active trace in a
# thread-local — servicers adopt the inbound header, and every peer
# call (pb2 or raw wire) re-emits it with a fresh span id.  When the
# OTEL SDK is present the `span()` context manager still opens real
# spans on top.

import secrets
import threading

_tls = threading.local()

#: Test/diagnostic hook: called with the RAW inbound traceparent header
#: (or None) each time a request context is adopted.
inbound_hook = None


def parse_traceparent(header: Optional[str]):
    """(trace_id_hex32, flags_hex2) or None for absent/malformed input
    (malformed → start a new trace, per the W3C spec's restart rule)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    tid, sid, flags = parts[1].lower(), parts[2].lower(), parts[3]
    if len(tid) != 32 or len(sid) != 16 or len(flags) != 2 \
            or tid == "0" * 32 or sid == "0" * 16:
        return None
    try:
        int(tid, 16), int(sid, 16), int(flags, 16)
    except ValueError:
        return None
    return tid, flags


def parent_span_id(header: Optional[str]) -> Optional[str]:
    """The 16-hex parent-span-id of a valid traceparent, or None.
    ``parse_traceparent`` deliberately discards it (the trace context
    is (trace_id, flags)); the span plane needs it back so an inbound
    request's first span parents under the caller's hop span."""
    if parse_traceparent(header) is None:
        return None
    return header.strip().split("-")[2].lower()


def current_trace_id() -> Optional[str]:
    """The active request's 32-hex trace id, or None outside any
    request context.  Cheap enough for hot-path capture (the flight
    recorder and dispatcher jobs stamp it at submit time — worker
    threads have no request context of their own)."""
    tp = getattr(_tls, "trace", None)
    return tp[0] if tp is not None else None


def current_traceparent() -> Optional[str]:
    """Outbound header for the active request's trace (fresh span id
    per hop), or None outside any request context."""
    tp = getattr(_tls, "trace", None)
    if tp is None:
        return None
    tid, flags = tp
    return f"00-{tid}-{secrets.token_hex(8)}-{flags}"


# --- span plane (ISSUE 12) --------------------------------------------
#
# ``span()`` historically measured durations into histograms and threw
# the structure away.  The SpanRecorder keeps it: completed spans
# (trace_id/span_id/parent_id, name, start/end, attrs) buffer per-trace
# while the request runs, then commit as a unit — head-sampled by a
# DETERMINISTIC function of the trace id so every daemon in a cluster
# keeps (or drops) the same traces and cross-daemon assembly always
# sees whole traces, with forced sampling on error/degraded/shed
# outcomes so the interesting requests survive even at sample=0.

#: span-name catalog (linted against OBSERVABILITY.md by
#: tools/check_metrics.py, like slo.SLO_CATALOG)
SPAN_CATALOG: Dict[str, str] = {
    "grpc.GetRateLimits": "public V1 handler (pb2 and raw-wire twins)",
    "grpc.GetPeerRateLimits": "owner-side peer handler (pb2 and wire)",
    "grpc.UpdatePeerGlobals": "owner→replica GLOBAL broadcast handler",
    "http.GetRateLimits": "HTTP/JSON gateway handler",
    "peer.forward": "caller-side hop: batched forward lane send",
    "global.hits_flush": "async GLOBAL hit-flush tick (owner-bound)",
    "global.broadcast": "async GLOBAL broadcast tick (replica-bound)",
    "wave": "one dispatcher wave (fan-in over the batched jobs)",
    "wave.pack": "host pack phase (absent when the engine fuses it)",
    "wave.device": "device step phase",
    "wave.resolve": "host resolve/demux phase",
}


class SpanRecorder:
    """Bounded, lock-aware ring of completed spans (ISSUE 12).

    Spans ``add()``ed while a request runs buffer per-trace; the
    request context's exit ``commit()``s the whole trace — into the
    ring when head-sampled or forced, dropped otherwise.  A bounded
    tombstone map remembers recent commit decisions so late adds from
    pipelined wave workers (future resolved before ``_wave_end`` ran)
    still route correctly.  All state is O(bounded); the lock is a
    leaf (never held while calling out)."""

    PENDING_TRACES = 128   # distinct in-flight traces buffered
    PENDING_SPANS = 64     # spans buffered per trace
    TOMBSTONES = 256       # remembered commit decisions

    def __init__(self, capacity: int = 2048, sample: float = 0.0):
        if capacity < 1:
            raise ValueError("span recorder capacity must be >= 1")
        self.capacity = capacity
        #: head-sampling rate in [0,1]; plain attr, racy reads are fine
        self.sample = float(sample)
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)  # guarded-by: self._mu
        self._pending: OrderedDict = OrderedDict()  # guarded-by: self._mu
        self._done: OrderedDict = OrderedDict()  # guarded-by: self._mu
        self._last_sampled: Optional[str] = None  # guarded-by: self._mu
        self._dropped = 0  # guarded-by: self._mu

    def head_sampled(self, trace_id: str) -> bool:
        """Deterministic head-sampling decision: a pure function of the
        trace id, so every daemon in the cluster keeps the same traces
        (cluster-wide assembly never sees half a trace)."""
        rate = self.sample
        if rate <= 0.0:
            return False
        if rate >= 1.0:
            return True
        try:
            return int(trace_id[:8], 16) / 4294967296.0 < rate
        except (ValueError, TypeError):
            return False

    def add(self, span_dict: dict) -> None:
        """Buffer one completed span under its trace (bounded).  After
        the trace committed, route by the remembered decision."""
        tid = span_dict.get("trace_id")
        if not tid:
            return
        with self._mu:
            if tid in self._done:
                if self._done[tid]:
                    self._ring.append(span_dict)
                else:
                    self._dropped += 1
                return
            buf = self._pending.get(tid)
            if buf is None:
                while len(self._pending) >= self.PENDING_TRACES:
                    self._pending.popitem(last=False)
                    self._dropped += 1
                buf = self._pending[tid] = []
            if len(buf) < self.PENDING_SPANS:
                buf.append(span_dict)
            else:
                self._dropped += 1

    def commit(self, trace_id: str, forced=None) -> bool:
        """Resolve a trace's buffered spans: keep when forced or
        head-sampled, drop otherwise.  Returns the decision."""
        sampled = bool(forced) or self.head_sampled(trace_id)
        with self._mu:
            buf = self._pending.pop(trace_id, None)
            self._done[trace_id] = sampled
            while len(self._done) > self.TOMBSTONES:
                self._done.popitem(last=False)
            if sampled:
                if buf:
                    self._ring.extend(buf)
                self._last_sampled = trace_id
            elif buf:
                self._dropped += len(buf)
        return sampled

    def discard(self, trace_id: str) -> None:
        """Drop a trace's buffered spans without a tombstone."""
        with self._mu:
            self._pending.pop(trace_id, None)

    def exemplar(self) -> Optional[dict]:
        """The most recently committed SAMPLED trace, as a prometheus
        exemplar label dict — the histogram/SLO link from a burning
        signal to one concrete trace."""
        with self._mu:
            tid = self._last_sampled
        return {"trace_id": tid} if tid else None

    def spans(self, trace_id: Optional[str] = None,
              limit: Optional[int] = None) -> List[dict]:
        """Chronological snapshot of committed spans (oldest first);
        ``trace_id`` filters server-side, ``limit`` keeps the newest N."""
        with self._mu:
            out = list(self._ring)
        if trace_id:
            out = [s for s in out if s.get("trace_id") == trace_id]
        if limit is not None and limit >= 0:
            out = out[len(out) - min(limit, len(out)):]
        return out

    def stats(self) -> dict:
        with self._mu:
            return {"spans": len(self._ring), "capacity": self.capacity,
                    "sample": self.sample, "pending": len(self._pending),
                    "dropped": self._dropped}

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)


class _SpanState:
    """Per-request span bookkeeping (thread-local): the recorder, the
    open-span stack, the inbound parent id, and the forced-sample
    verdict."""

    __slots__ = ("recorder", "trace_id", "parent", "stack", "forced")

    def __init__(self, recorder, trace_id, parent):
        self.recorder = recorder
        self.trace_id = trace_id
        self.parent = parent
        self.stack: List[str] = []
        self.forced: Optional[str] = None


def new_span_id() -> str:
    return secrets.token_hex(8)


def current_span_id() -> Optional[str]:
    """The innermost open recorded span's id (the wave's parent when
    launched from a request thread), or None when the span plane is
    not armed here."""
    st = getattr(_tls, "span", None)
    if st is None:
        return None
    return st.stack[-1] if st.stack else st.parent


def force_sample(reason: str) -> None:
    """Flag the active trace for forced sampling (error / degraded /
    shed outcomes must survive even at sample=0).  First reason wins."""
    st = getattr(_tls, "span", None)
    if st is not None and st.forced is None:
        st.forced = reason


def hop_traceparent(name: str, attrs: Optional[dict] = None
                    ) -> Optional[str]:
    """Mint an outbound traceparent AND record the caller-side hop as
    an instant span whose span id IS the minted parent id — the
    receiving daemon's request span then parents under it, stitching
    owner-side work back to this request (ISSUE 12)."""
    tp = getattr(_tls, "trace", None)
    if tp is None:
        return None
    tid, flags = tp
    sid = secrets.token_hex(8)
    st = getattr(_tls, "span", None)
    if st is not None and st.trace_id == tid:
        now = time.time()  # clock-ok: telemetry wall clock (span timestamps)
        st.recorder.add({
            "trace_id": tid, "span_id": sid,
            "parent_id": st.stack[-1] if st.stack else st.parent,
            "name": name, "start": now, "end": now,
            "attrs": dict(attrs) if attrs else {}})
    return f"00-{tid}-{sid}-{flags}"


@contextlib.contextmanager
def request_context(traceparent: Optional[str],
                    recorder: Optional[SpanRecorder] = None
                    ) -> Iterator[None]:
    """Adopt an inbound traceparent — or start a new trace — for the
    handler's duration; peer calls made inside propagate the same
    trace id (otelgrpc server-interceptor parity).  With ``recorder``
    the span plane arms: ``span()`` records, and exit commits the
    trace (head-sampled / forced)."""
    if inbound_hook is not None:
        inbound_hook(traceparent)
    parsed = parse_traceparent(traceparent)
    prev = getattr(_tls, "trace", None)
    _tls.trace = parsed or (secrets.token_hex(16), "01")
    st = prev_st = None
    if recorder is not None:
        prev_st = getattr(_tls, "span", None)
        st = _SpanState(recorder, _tls.trace[0],
                        parent_span_id(traceparent))
        _tls.span = st
    try:
        yield
    finally:
        _tls.trace = prev
        if st is not None:
            _tls.span = prev_st
            st.recorder.commit(st.trace_id, forced=st.forced)


def grpc_request_context(context, recorder: Optional[SpanRecorder] = None):
    """request_context from a grpc servicer context's metadata."""
    header = None
    try:
        for k, v in context.invocation_metadata():
            if k.lower() == "traceparent":
                header = v
                break
    except Exception:  # noqa: BLE001 - metadata is best-effort
        pass
    return request_context(header, recorder=recorder)


def outbound_metadata(extra=()):
    """grpc call metadata carrying the active trace (otelgrpc
    client-interceptor parity); None when there is neither a trace nor
    extra metadata."""
    tp = current_traceparent()
    md = list(extra)
    if tp is not None:
        md.append(("traceparent", tp))
    return md or None


@contextlib.contextmanager
def span(name: str, metrics=None, attrs: Optional[dict] = None
         ) -> Iterator[None]:
    """Host-side span: OTEL when available, always a duration metric —
    including on the error path (try/finally).  When the request
    context armed a SpanRecorder, the span is RECORDED: fresh span id,
    parented under the innermost open span (or the inbound hop), and
    an exception in the body force-samples the whole trace."""
    t0 = time.perf_counter()
    st = getattr(_tls, "span", None)
    sid = parent = None
    wall0 = 0.0
    if st is not None:
        sid = secrets.token_hex(8)
        parent = st.stack[-1] if st.stack else st.parent
        wall0 = time.time()  # clock-ok: telemetry wall clock (span start)
        st.stack.append(sid)
    try:
        if _tracer is not None:  # pragma: no cover
            with _tracer.start_as_current_span(name):
                yield
        else:
            yield
    except BaseException:
        if st is not None and st.forced is None:
            st.forced = "error"
        raise
    finally:
        dt = time.perf_counter() - t0
        if st is not None:
            if st.stack and st.stack[-1] == sid:
                st.stack.pop()
            st.recorder.add({
                "trace_id": st.trace_id, "span_id": sid,
                "parent_id": parent, "name": name,
                "start": wall0, "end": wall0 + dt,
                "attrs": dict(attrs) if attrs else {}})
        if metrics is not None:
            metrics.func_duration.labels(name=name).observe(dt)


# --- cross-daemon assembly (ISSUE 12) ---------------------------------


def assemble(spans: List[dict], trace_id: Optional[str] = None
             ) -> List[dict]:
    """Stitch span slices (possibly from N daemons' /debug/traces)
    into per-trace trees.  Returns one dict per trace — ``trace_id``,
    ``spans`` (count), ``roots`` (nested via ``children``) — ordered
    by earliest span start.  Duplicate span ids (the same daemon's
    slice fetched twice) dedup; orphans (parent not in the slice
    set) surface as extra roots rather than vanishing."""
    by_trace: Dict[str, dict] = {}
    for s in spans:
        tid = s.get("trace_id")
        if not tid or (trace_id and tid != trace_id):
            continue
        by_trace.setdefault(tid, {}).setdefault(s.get("span_id"), s)
    out = []
    for tid, seen in by_trace.items():
        nodes = {sid: dict(s, children=[]) for sid, s in seen.items()}
        roots = []
        for n in nodes.values():
            p = n.get("parent_id")
            if p and p in nodes and p != n.get("span_id"):
                nodes[p]["children"].append(n)
            else:
                roots.append(n)
        for n in nodes.values():
            n["children"].sort(key=lambda c: c.get("start") or 0.0)
        roots.sort(key=lambda c: c.get("start") or 0.0)
        out.append({"trace_id": tid, "spans": len(nodes),
                    "roots": roots})
    out.sort(key=lambda t: min((r.get("start") or 0.0
                                for r in t["roots"]), default=0.0))
    return out


def render_waterfall(trace: dict, width: int = 40) -> str:
    """Text waterfall for one assembled trace (a dict from
    ``assemble()``): indent = depth, one bar per span scaled to the
    trace's [min start, max end] window."""
    flat: List[tuple] = []

    def _walk(n, depth):
        flat.append((depth, n))
        for c in n.get("children", ()):
            _walk(c, depth + 1)

    for r in trace.get("roots", ()):
        _walk(r, 0)
    if not flat:
        return f"trace {trace.get('trace_id')}: no spans"
    t0 = min(n.get("start") or 0.0 for _, n in flat)
    t1 = max(n.get("end") or 0.0 for _, n in flat)
    window = max(t1 - t0, 1e-9)
    lines = [f"trace {trace.get('trace_id')}  "
             f"({trace.get('spans')} spans, {window * 1e3:.2f}ms)"]
    for depth, n in flat:
        s = (n.get("start") or 0.0) - t0
        e = (n.get("end") or 0.0) - t0
        lo = int(s / window * width)
        hi = max(int(e / window * width), lo + 1)
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        dur_ms = max(e - s, 0.0) * 1e3
        lines.append(f"  [{bar}] {'  ' * depth}{n.get('name')} "
                     f"+{s * 1e3:.2f}ms {dur_ms:.2f}ms")
    return "\n".join(lines)


class DeviceProfiler:
    """jax.profiler session around the serving loop.

    Usage: ``prof = DeviceProfiler.from_env(); ...; prof.stop()`` —
    writes an XProf trace for TensorBoard under the given directory.
    """

    def __init__(self, log_dir: str):
        import jax

        self.log_dir = log_dir
        jax.profiler.start_trace(log_dir)
        self._active = True
        log.info("device profiling → %s", log_dir)

    @classmethod
    def from_env(cls) -> Optional["DeviceProfiler"]:
        d = os.environ.get("GUBER_PROFILE_DIR", "")
        return cls(d) if d else None

    def stop(self) -> None:
        if self._active:
            self._active = False
            import jax

            jax.profiler.stop_trace()


@contextlib.contextmanager
def step_annotation(name: str) -> Iterator[None]:
    """Named region visible in device traces (jax.profiler.TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield

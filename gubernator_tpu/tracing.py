"""Tracing/profiling hooks (SURVEY.md §5.1).

The reference grew OpenTelemetry spans around handlers (otelgrpc
interceptors in daemon.go, span-per-request in gubernator.go —
version-dependent).  Here:

- ``span(name)`` wraps host-side sections; if the ``opentelemetry``
  SDK is installed it emits real OTEL spans, otherwise it degrades to
  a no-op that still feeds the prometheus duration histogram.
- ``device_profile(...)`` captures a jax.profiler trace of the device
  step (the TPU-side profiling story: view in TensorBoard/XProf).

Enable device profiling with GUBER_PROFILE_DIR=/path (daemon reads it).
"""
from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Iterator, Optional

log = logging.getLogger("gubernator_tpu.tracing")

try:  # pragma: no cover - OTEL not in this image; degrade gracefully
    from opentelemetry import trace as _otel_trace

    _tracer = _otel_trace.get_tracer("gubernator_tpu")
except ImportError:
    _tracer = None


@contextlib.contextmanager
def span(name: str, metrics=None) -> Iterator[None]:
    """Host-side span: OTEL when available, always a duration metric —
    including on the error path (try/finally)."""
    t0 = time.perf_counter()
    try:
        if _tracer is not None:  # pragma: no cover
            with _tracer.start_as_current_span(name):
                yield
        else:
            yield
    finally:
        if metrics is not None:
            metrics.func_duration.labels(name=name).observe(
                time.perf_counter() - t0)


class DeviceProfiler:
    """jax.profiler session around the serving loop.

    Usage: ``prof = DeviceProfiler.from_env(); ...; prof.stop()`` —
    writes an XProf trace for TensorBoard under the given directory.
    """

    def __init__(self, log_dir: str):
        import jax

        self.log_dir = log_dir
        jax.profiler.start_trace(log_dir)
        self._active = True
        log.info("device profiling → %s", log_dir)

    @classmethod
    def from_env(cls) -> Optional["DeviceProfiler"]:
        d = os.environ.get("GUBER_PROFILE_DIR", "")
        return cls(d) if d else None

    def stop(self) -> None:
        if self._active:
            self._active = False
            import jax

            jax.profiler.stop_trace()


@contextlib.contextmanager
def step_annotation(name: str) -> Iterator[None]:
    """Named region visible in device traces (jax.profiler.TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield

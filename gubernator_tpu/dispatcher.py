"""Device dispatcher: cross-request batch coalescing.

The TPU-native replacement for the reference's worker-pool cache
sharding (workers.go › WorkerPool — reconstructed): where the reference
hashes requests to per-core goroutines to avoid lock contention, here
ALL concurrent client batches are merged into one device program launch.
A single dispatcher thread drains the queue, packs every waiting request
into the next device step, and resolves each caller's future with its
slice of the results.

Why it's faster than per-caller engine calls under a lock: the device
step costs roughly the same for 1 request as for 10 000 (it streams the
whole table either way — core/step.py › decide_batch), so merging N
concurrent callers into one launch divides the per-launch cost by N and
removes the serialization point entirely.  This is the service-side
analog of the batch coalescing the raw benchmark does by hand.
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import List, Optional, Sequence, Tuple

from contextlib import contextmanager
from contextvars import ContextVar

from .types import RateLimitRequest, RateLimitResponse

log = logging.getLogger("gubernator_tpu.dispatcher")


class ResourceExhausted(RuntimeError):
    """Raised at ingress when admission control sheds a request batch
    (bounded queue full, projected queue-wait past the caller deadline,
    or drain mode).  The daemon maps it to grpc RESOURCE_EXHAUSTED /
    HTTP 429 — shedding must be CHEAP and explicit, never a timeout."""


#: caller deadline for deadline-aware shedding, set by the serving
#: front door (grpc context.time_remaining / HTTP timeout header) and
#: read by Dispatcher.admit in the same thread/context
_REQUEST_DEADLINE: "ContextVar[Optional[float]]" = ContextVar(
    "guber_request_deadline", default=None)


@contextmanager
def request_deadline(seconds: Optional[float]):
    """Scope the caller's remaining deadline (seconds) for admission
    control; None means 'no deadline' (only queue-full/drain shed)."""
    tok = _REQUEST_DEADLINE.set(seconds)
    try:
        yield
    finally:
        _REQUEST_DEADLINE.reset(tok)


def _job_len(job) -> int:
    return (len(job.reqs) if isinstance(job, _Job) else len(job.khash))


def _concat_columns(parts):
    """[(RequestBatch, khash), ...] → one concatenated (batch, khash)."""
    import numpy as np

    batch = type(parts[0][0])(*[
        np.concatenate([np.asarray(b[f]) for b, _ in parts])
        for f in range(len(parts[0][0]))])
    return batch, np.concatenate([kh for _, kh in parts])


class ResultView:
    """Row-slice view [lo, hi) into a wave's SHARED downloaded result
    columns (status i32, limit i64, remaining i64, reset i64, full
    bool).

    The worker thread resolves each job's future with one of these —
    two ints and a tuple reference — instead of materializing per-job
    column tuples, so everything downstream of the device download
    (slicing, over-limit counting, wire-byte serialization) runs in the
    CALLER's thread, off the single dispatch loop.  Unpacking iterates
    the five sliced columns, so ``st, lim, rem, rst, full = view``
    keeps working at every legacy call site."""

    __slots__ = ("cols", "lo", "hi")

    def __init__(self, cols, lo: int, hi: int):
        self.cols = cols
        self.lo = lo
        self.hi = hi

    def sliced(self) -> tuple:
        lo, hi = self.lo, self.hi
        return tuple(c[lo:hi] for c in self.cols)

    def __iter__(self):
        return iter(self.sliced())

    def __len__(self) -> int:
        return 5


class _Job:
    __slots__ = ("reqs", "now_ms", "future", "t_enq", "trace", "span")

    def __init__(self, reqs, now_ms):
        self.reqs = reqs
        self.now_ms = now_ms
        self.future: Future = Future()
        #: stamped by _submit: queue-wait start + caller's trace id
        #: (+ the caller's open span id, the wave span's parent)
        self.t_enq: Optional[float] = None
        self.trace: Optional[str] = None
        self.span: Optional[str] = None


class _PackedJob:
    """Columnar job (C++ wire-ingest lane): a RequestBatch of numpy
    columns + key hashes instead of RateLimitRequest objects.
    ``mslot`` (ISSUE 8): optional per-request mesh-GLOBAL replica slot
    column (-1 = sharded row) — rides the job so a fused engine can
    serve both lanes in ONE launch."""

    __slots__ = ("batch", "khash", "now_ms", "future", "t_enq", "trace",
                 "span", "mslot")

    def __init__(self, batch, khash, now_ms, mslot=None):
        self.batch = batch
        self.khash = khash
        self.now_ms = now_ms
        self.mslot = mslot
        self.future: Future = Future()
        self.t_enq: Optional[float] = None
        self.trace: Optional[str] = None
        self.span: Optional[str] = None


def _concat_mslot(jobs):
    """Concat the wave's per-job mesh-slot columns (None when no job
    carries one; jobs without a column fill -1 = sharded lane)."""
    if all(getattr(j, "mslot", None) is None for j in jobs):
        return None
    import numpy as np

    return np.concatenate([
        j.mslot if getattr(j, "mslot", None) is not None
        else np.full(_job_len(j), -1, np.int32) for j in jobs])


class Dispatcher:
    """Serializes engine access by merging, not locking."""

    #: Hard cap on how long a caller waits for its wave; protects the
    #: request handler from a wedged device (first compile is warmed by
    #: the daemon before serving, so steady-state waves are ms-scale).
    #: GUBER_RESULT_TIMEOUT_S overrides: a cold wave compile through
    #: the axon tunnel is 250-305 s, so any caller that can arrive
    #: before warmup (benches, probes) must budget past the compile —
    #: 120 s silently truncated the round-5 on-chip service sections
    #: to an empty TimeoutError.
    RESULT_TIMEOUT_S = 120.0

    #: Default stall threshold: a wave in flight this long is flagged by
    #: the watchdog (gauge + log + recorder event) — deliberately well
    #: below RESULT_TIMEOUT_S so a cold compile surfaces as a DIAGNOSED
    #: stall minutes before callers give up.  GUBER_STALL_THRESHOLD_S
    #: overrides; <= 0 disables the watchdog.
    STALL_THRESHOLD_S = 30.0

    #: default depth of the overlapped wave pipeline: how many launched
    #: waves may be in flight (unsynced) at once.  Depth 2 = pack wave
    #: N+1 while wave N runs; GUBER_PIPELINE_DEPTH overrides (min 1 —
    #: depth 1 degenerates to launch-then-sync, i.e. no overlap).
    PIPELINE_DEPTH = 2

    #: default admission bound: rows queued (not yet launched) before
    #: ingress sheds with RESOURCE_EXHAUSTED.  GUBER_ADMISSION_LIMIT
    #: overrides; 0 disables the bound (deadline/drain shed remain).
    ADMISSION_LIMIT_WAVES = 8

    #: fan-in link bound (ISSUE 12): a wave span records at most this
    #: many OTHER batched requests' (trace, span) pairs as attributes
    WAVE_LINKS = 8

    def __init__(self, engine, max_wave: int = 8192,
                 max_delay_ms: float = 0.2,
                 lock: Optional[threading.Lock] = None,
                 metrics=None, recorder=None, clock=time.monotonic,
                 analytics=None, faults=None):
        self.engine = engine
        #: optional FaultSet (faults.py): dispatch_enqueue / _launch /
        #: _sync / device_step faultpoints
        self._faults = faults
        #: key-level analytics subsystem (analytics.py › KeyAnalytics,
        #: optional): resolved waves tap their khash/hits/status
        #: columns into its worker queue AFTER the wave ends — strictly
        #: off the caller's critical path — and per-phase durations
        #: feed its ledger.  None (bare dispatchers) costs nothing.
        self.analytics = analytics
        self._phase_hist: dict = {}  # phase → cached histogram child
        self.max_wave = max_wave
        # coalescing window: how long the worker waits for more jobs
        # after the first before launching the wave.  GUBER_COALESCE_US
        # (microseconds) overrides the constructor default; malformed
        # or negative values keep it.  _drain_wave skips the wait
        # entirely when the queue already holds >= max_wave rows.
        coalesce_env = os.environ.get("GUBER_COALESCE_US", "")
        if coalesce_env:
            try:
                max_delay_ms = max(float(coalesce_env), 0.0) / 1000.0
            except ValueError:
                pass  # malformed: keep the constructor default
        self.max_delay_s = max_delay_ms / 1000.0
        # overlapped-pipeline depth (in-flight launched waves)
        depth_env = os.environ.get("GUBER_PIPELINE_DEPTH", "")
        try:
            depth = int(depth_env) if depth_env else self.PIPELINE_DEPTH
        except ValueError:
            depth = self.PIPELINE_DEPTH
        self.pipeline_depth = max(depth, 1)
        #: per-instance Metrics registry (metrics.py) and FlightRecorder
        #: (telemetry.py); both optional — a bare Dispatcher (tests,
        #: library use) pays only the cheap internal counters.
        self.metrics = metrics
        self.recorder = recorder
        #: optional tracing.SpanRecorder (ISSUE 12): when attached (by
        #: the instance), every wave emits a fan-in span + exact phase
        #: child spans; None (bare dispatchers, bench "off" arm) costs
        #: nothing.  Plain attr — swapped whole, racy reads are fine.
        self.span_recorder = None
        self._clock = clock
        #: mesh-GLOBAL reconcile generation (ISSUE 7): bumped by the
        #: instance after each collective fold; every wave is stamped
        #: with the generation it served under, so a decision window
        #: correlates with the coherence epoch it read.  Single racy
        #: int write/read by design (a wave straddling a fold may carry
        #: either stamp — both are true).
        self.reconcile_gen = 0
        # --- wave telemetry state (all under _tel_mu) ---
        self._tel_mu = threading.Lock()
        #: wave_id → {t0, kind, size, trace, stalled}
        self._inflight: dict = {}  # guarded-by: self._tel_mu
        self._wave_seq = 0  # guarded-by: self._tel_mu
        self._wave_count = 0  # guarded-by: self._tel_mu
        self._stall_count = 0  # guarded-by: self._tel_mu
        self._timeout_count = 0  # guarded-by: self._tel_mu
        self._first_wave_s: Optional[float] = None  # guarded-by: self._tel_mu
        self._last_wave_end: Optional[float] = None  # guarded-by: self._tel_mu
        from collections import deque as _deque

        #: bounded recent-wave samples for telemetry_snapshot percentiles
        #: (prometheus histograms can't answer percentile queries)
        self._recent_sizes: "_deque" = _deque(maxlen=4096)  # guarded-by: self._tel_mu
        self._recent_durs: "_deque" = _deque(maxlen=4096)  # guarded-by: self._tel_mu
        self._recent_waits: "_deque" = _deque(maxlen=4096)  # guarded-by: self._tel_mu
        #: Shared with the instance's row-level ops (gather/upsert/
        #: restore/sweep), which run on other threads and mutate the
        #: same engine state.
        self._engine_lock = lock if lock is not None else threading.Lock()
        self._queue: "queue.Queue[_Job]" = queue.Queue()
        #: worker-local holdover: the job that would have pushed the
        #: current wave past max_wave leads the next wave instead
        #: (only the dispatch thread touches it)
        self._carry = None
        self._closing = threading.Event()
        self._submit_mu = threading.Lock()  # serializes submit vs close
        # ---- overload admission control (ISSUE 5) ----
        # bounded ingress: _queued_rows tracks rows submitted but not
        # yet pulled into a wave; admit() sheds past the limit, when
        # the projected queue wait exceeds the caller's deadline, or in
        # drain mode.  All under _submit_mu (brief).
        adm_env = os.environ.get("GUBER_ADMISSION_LIMIT", "")
        try:
            self.admission_limit = (int(adm_env) if adm_env
                                    else self.ADMISSION_LIMIT_WAVES
                                    * self.max_wave)
        except ValueError:
            self.admission_limit = self.ADMISSION_LIMIT_WAVES * self.max_wave
        self._queued_rows = 0  # guarded-by: self._submit_mu
        #: drain flag: single racy bool write in drain(), lock-free reads
        self._draining = False
        self._shed_rows = 0  # guarded-by: self._submit_mu
        #: recorder rate limit (1/s/reason)
        self._last_shed_event = 0.0  # guarded-by: self._submit_mu
        #: one idle-path inline runner at a time (see _try_inline)
        self._inline_mu = threading.Lock()
        #: pipelining needs BOTH the policy and the engine capability —
        #: folding them here keeps _try_inline's gate and _run's mode
        #: agreeing (a capability-less engine must not lose the inline
        #: fast path to a pipeline that can't exist)
        self._pipelined = (self._want_pipeline()
                           and hasattr(engine, "launch_packed"))
        # fused-engine capabilities (ISSUE 8): a fused engine's wave IS
        # one device program, so the pack mark collapses into the
        # `device` phase (the PhaseLedger partition stays exact — the
        # tail segment is still `resolve`), and the engine emits the
        # heavy-hitter tap columns on device at launch, so the
        # dispatcher's host-side column copies are skipped.
        self._fused_phases = getattr(engine, "fused_serving", False)
        self._fused_tap = getattr(engine, "fused_tap", False)
        if self.metrics is not None:
            self.metrics.pipeline_depth.set(
                self.pipeline_depth if self._pipelined else 0)
        env_timeout = os.environ.get("GUBER_RESULT_TIMEOUT_S", "")
        if env_timeout:
            import math

            try:
                parsed = float(env_timeout)
            except ValueError:
                parsed = 0.0  # malformed: keep the class default
            if math.isfinite(parsed) and parsed > 0:
                # rejects 0/negative/NaN (a 0 s wait would fail EVERY
                # queued wave instantly) AND 'inf' (which silently
                # disabled the wave-wait cap: a wedged wave would park
                # its caller forever with no timeout diagnosis)
                self.RESULT_TIMEOUT_S = parsed
        # Stall watchdog: default well below the result timeout (and
        # scaled down with it, so a tightened timeout keeps the "stall
        # first, timeout later" ordering).  An explicit env value is an
        # operator choice and is honored verbatim; <= 0 disables.
        stall_env = os.environ.get("GUBER_STALL_THRESHOLD_S", "")
        if stall_env:
            try:
                self._stall_threshold_s = float(stall_env)
            except ValueError:
                self._stall_threshold_s = min(
                    self.STALL_THRESHOLD_S, self.RESULT_TIMEOUT_S / 4.0)
            if self._stall_threshold_s != self._stall_threshold_s:  # NaN
                self._stall_threshold_s = 0.0
        else:
            self._stall_threshold_s = min(
                self.STALL_THRESHOLD_S, self.RESULT_TIMEOUT_S / 4.0)
        self._watchdog: Optional[threading.Thread] = None
        if self._stall_threshold_s > 0:
            #: poll well inside the threshold so a stall is flagged
            #: promptly after it crosses the line
            self._watch_interval_s = max(
                min(self._stall_threshold_s / 4.0, 1.0), 0.02)
            self._watchdog = threading.Thread(
                target=self._watchdog_run, daemon=True,
                name="dispatcher-watchdog")
            self._watchdog.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="device-dispatcher")
        self._thread.start()

    @staticmethod
    def _want_pipeline() -> bool:
        """Launch/sync pipelining (depth K, see pipeline_depth) is
        TPU-only by default: the CPU backend effectively serializes
        dispatch, so splitting launch/sync there just adds overhead
        (measured 644k → 227k dec/s at 16 callers).
        GUBER_PIPELINE=1/0 overrides."""
        import os

        pipe_env = os.environ.get("GUBER_PIPELINE", "")
        if pipe_env:
            return pipe_env == "1"
        try:
            import jax

            return jax.default_backend() == "tpu"
        except Exception:  # noqa: BLE001
            return False

    def _try_inline(self) -> bool:
        """Idle fast path: when nothing is queued and no other caller
        is inline, the calling thread may run the engine directly —
        skipping two scheduler wakes plus the coalescing window
        (~0.4-0.8 ms of the service p99 on a 1-core host).  Disabled
        under pipelining: there the worker's launch/sync overlap IS
        the latency optimization and an inline engine call would
        forfeit it.  Caller must release _inline_mu when True."""
        if self._pipelined or not self._queue.empty():
            return False
        if self._closing.is_set():
            return False  # _submit raises the closed error uniformly
        if not self._inline_mu.acquire(blocking=False):
            return False
        if self._closing.is_set():
            # re-checked under _inline_mu: close() drains inliners by
            # acquiring this mutex AFTER setting _closing, so passing
            # the first check and then acquiring late must not start
            # an engine call after close() returned (it would race the
            # close-time checkpoint snapshot) — ADVICE r4
            self._inline_mu.release()
            return False
        if not self._queue.empty():
            # a job slipped in: let the worker coalesce it with ours
            self._inline_mu.release()
            return False
        return True

    def run_inline_wave(self, kind: str, nreq: int, fn,
                        tenant: Optional[str] = None):
        """Run ``fn()`` (an engine call the caller composed — the fused
        wire lane, instance.py › _wire_check_fused) as ONE inline wave
        in the calling thread, with the same engine-lock discipline and
        wave telemetry as check_batch's idle fast path.  Returns
        ``fn()``'s result, or the _BUSY sentinel when the idle inline
        path isn't available (queued jobs / pipelining / closing) — the
        caller then falls back to the classic submit path."""
        if not self._try_inline():
            return self._BUSY
        try:
            wid = self._wave_begin(kind, nreq=nreq, tenant=tenant)
            try:
                self._mark_pack(wid)
                with self._engine_lock:
                    self._fault("device_step")
                    out = fn()
                self._wave_mark(wid, "device")
            except Exception as e:  # noqa: BLE001 - recorded, re-raised
                self._wave_end(wid, error=e)
                raise
            self._wave_end(wid)
            return out
        finally:
            self._inline_mu.release()

    #: run_inline_wave's "dispatcher busy" sentinel (None is a valid
    #: engine-call result, so the miss needs its own identity)
    _BUSY = object()

    def check_batch(self, reqs: Sequence[RateLimitRequest], now_ms: int
                    ) -> List[RateLimitResponse]:
        """Submit and wait; concurrent callers share device launches.
        An idle dispatcher runs the wave in the caller thread (a lone
        job's wave is exactly engine.check_batch — same semantics, no
        thread handoff)."""
        if self._try_inline():
            try:
                wid = self._wave_begin("inline", nreq=len(reqs),
                                       tenant=self._hint_reqs(reqs))
                try:
                    self._mark_pack(wid)
                    with self._engine_lock:
                        self._fault("device_step")
                        out = self.engine.check_batch(list(reqs), now_ms)
                    self._wave_mark(wid, "device")
                except Exception as e:  # noqa: BLE001 - recorded, re-raised
                    self._wave_end(wid, error=e)
                    raise
                self._wave_end(wid)
                self._tap_reqs(reqs, out)
                return out
            finally:
                self._inline_mu.release()
        job = _Job(list(reqs), now_ms)
        self._submit(job)
        try:
            return job.future.result(timeout=self.RESULT_TIMEOUT_S)
        except FuturesTimeout as e:
            raise self._result_timeout(e) from e

    def check_packed(self, batch, khash, now_ms: int,
                     mslot=None) -> tuple:
        """Columnar submit (see engine.check_packed); coalesces with
        other packed callers by column concatenation.  Idle → inline
        (a lone packed job's wave is exactly engine.check_packed).
        Returns the classic 5-tuple of per-request columns; the
        slicing out of the wave's shared result columns happens HERE,
        in the caller's thread (see ResultView).  ``mslot`` (ISSUE 8):
        per-request mesh-GLOBAL slot column for fused engines."""
        return self.check_packed_view(batch, khash, now_ms,
                                      mslot=mslot).sliced()

    def check_packed_view(self, batch, khash, now_ms: int,
                          mslot=None) -> ResultView:
        """``check_packed`` returning the zero-copy ResultView: row
        bounds into the wave's shared downloaded result columns.  The
        wire lanes serialize straight from the view (ops/_native.cpp ›
        build_responses_from_columns) without materializing per-job
        column tuples."""
        if self._try_inline():
            try:
                wid = self._wave_begin("inline_packed",
                                       nreq=len(khash),
                                       tenant=self._hint_khash(khash))
                try:
                    self._mark_pack(wid)
                    with self._engine_lock:
                        self._fault("device_step")
                        out = self._engine_check_packed(batch, khash,
                                                        now_ms, mslot)
                    self._wave_mark(wid, "device")
                except Exception as e:  # noqa: BLE001 - recorded, re-raised
                    self._wave_end(wid, error=e)
                    raise
                self._wave_end(wid)
                self._tap_packed(khash, batch.hits, out[0])
                return ResultView(out, 0, len(khash))
            finally:
                self._inline_mu.release()
        job = _PackedJob(batch, khash, now_ms, mslot=mslot)
        self._submit(job)
        try:
            return job.future.result(timeout=self.RESULT_TIMEOUT_S)
        except FuturesTimeout as e:
            raise self._result_timeout(e) from e

    def _fault(self, point: str) -> None:
        f = self._faults
        if f is not None and f.armed:
            f.fire(point)

    # ---- overload admission control (ISSUE 5) ---------------------------

    def _shed(self, reason: str, nrows: int,
              tenant_cb=None) -> None:
        from .tracing import current_span_id, force_sample

        # a shed outcome must survive head sampling (ISSUE 12): the
        # rejected caller's trace is exactly the one worth keeping
        force_sample("shed")
        if self.metrics is not None:
            self.metrics.admission_shed.labels(reason=reason).inc(nrows)
        # tenant attribution (ISSUE 11): resolved LAZILY — only sheds
        # pay the callback (a prefix split or a dict probe), the admit
        # fast path never does
        tenant = None
        if tenant_cb is not None:
            try:
                tenant = tenant_cb()
            except Exception:  # pragma: no cover - attribution only
                tenant = None
        ana = self.analytics
        if ana is not None:
            ana.tap_flag("shed", nrows, tenant=tenant)
        with self._submit_mu:
            self._shed_rows += nrows
            now = self._clock()
            throttled = now - self._last_shed_event < 1.0
            if not throttled:
                self._last_shed_event = now
        if self.recorder is not None and not throttled:
            # rate-limited: under sustained overload one event per
            # second, not one per rejected call
            ev = {"reason": reason, "rows": nrows,
                  "queued_rows": self._queued_rows}  # lock-free: diagnostic snapshot
            if tenant is not None:
                ev["tenant"] = tenant
            sid = current_span_id()
            if sid is not None:
                ev["span_id"] = sid
            self.recorder.record("admission_shed", **ev)
        raise ResourceExhausted(
            f"admission control shed {nrows} requests ({reason}: "
            f"queued_rows={self._queued_rows}, "  # lock-free: diagnostic snapshot
            f"limit={self.admission_limit})")

    def projected_queue_wait_s(self, extra_rows: int = 0) -> float:
        """Projected QUEUE WAIT for work entering now: how long the
        rows already ahead (+ ``extra_rows``) take to drain, from
        observed service rates.  The per-wave service time prefers the
        analytics PhaseLedger's per-phase means (pack+device+resolve,
        ISSUE 4), falling back to the recent-wave deques; an empty
        queue projects 0 — your wave launches immediately."""
        with self._tel_mu:
            # lock-free: projection input; a racy row read costs one wave of estimate error
            queued = self._queued_rows + extra_rows
            sizes = list(self._recent_sizes)
            durs = list(self._recent_durs)
        if queued <= 0:
            return 0.0
        wave_s = None
        ana = self.analytics
        if ana is not None:
            means = [ana.phases.mean(p)
                     for p in ("pack", "device", "resolve")]
            if any(m is not None for m in means):
                wave_s = sum(m for m in means if m is not None)
        if wave_s is None:
            if not durs:
                return 0.0
            wave_s = sum(durs) / len(durs)
        # queued rows coalesce into waves of up to max_wave rows each,
        # but never better than the sizes actually observed
        avg_rows = max(sum(sizes) / max(len(sizes), 1), 1.0)
        rows_per_wave = min(max(avg_rows, queued), self.max_wave)
        import math

        return math.ceil(queued / rows_per_wave) * wave_s

    def admit(self, nrows: int, deadline_s: Optional[float] = None,
              tenant_cb=None) -> None:
        """Deadline-aware ingress gate: raise ResourceExhausted instead
        of queueing work that cannot finish.  Cheap — a couple of
        reads; no device work, no allocation on the admit path.
        Deadline shedding only engages when a backlog EXISTS: an idle
        dispatcher serves any deadline (the wave launches at once).
        ``tenant_cb`` (ISSUE 11) resolves the triggering tenant — only
        invoked when a shed actually happens."""
        if self._draining:
            self._shed("draining", nrows, tenant_cb)
        lim = self.admission_limit
        if lim and self._queued_rows + nrows > lim:  # lock-free: GIL-atomic int read; admit is approximate by design
            self._shed("queue_full", nrows, tenant_cb)
        dl = deadline_s if deadline_s is not None \
            else _REQUEST_DEADLINE.get()
        if dl is not None and dl > 0 and self._queued_rows > 0:  # lock-free: GIL-atomic int read; admit is approximate by design
            # wait = draining what's AHEAD of this batch; its own
            # service time is not queue wait
            if self.projected_queue_wait_s(0) > dl:
                self._shed("deadline", nrows, tenant_cb)

    def drain(self) -> None:
        """Enter drain mode: queued/in-flight waves complete, new
        ingress sheds with RESOURCE_EXHAUSTED('draining').  Part of the
        daemon's graceful-shutdown sequence."""
        self._draining = True

    def _submit(self, job) -> None:
        from .tracing import current_span_id, current_trace_id

        self._fault("dispatch_enqueue")
        n = _job_len(job)
        self.admit(n)
        job.t_enq = self._clock()
        job.trace = current_trace_id()
        job.span = current_span_id()
        with self._submit_mu:
            # checked under the same lock close() takes, so a job can
            # never slip into the queue after the final drain
            if self._closing.is_set():
                raise RuntimeError("dispatcher is closed")
            self._queue.put(job)
            self._queued_rows += n

    # ---- wave telemetry -------------------------------------------------
    #
    # Every engine execution — inline, list, packed, merged, pipelined
    # launch/sync — is ONE wave: _wave_begin observes size + per-job
    # queue waits and registers the wave in _inflight (the watchdog's
    # scan set); _wave_end observes duration and resolves stall state.
    # All metric/recorder emission is None-guarded: a bare Dispatcher
    # costs two dict ops and a few deque appends per wave.

    def _wave_begin(self, kind: str, jobs=None, nreq: int = 0,
                    trace: Optional[str] = None,
                    slot: Optional[int] = None,
                    tenant: Optional[str] = None) -> int:
        t0 = self._clock()
        waits = []
        parent = None
        links = []
        if jobs:
            nreq = sum(_job_len(j) for j in jobs)
            for j in jobs:
                if j.t_enq is not None:
                    waits.append(max(t0 - j.t_enq, 0.0))
                if trace is None:
                    trace = j.trace
                    parent = getattr(j, "span", None)
                elif self.span_recorder is not None and j.trace \
                        and len(links) < self.WAVE_LINKS:
                    # fan-in: every OTHER request batched into this
                    # wave, linked by (trace, span) pairs (bounded)
                    links.append(f"{j.trace}:{getattr(j, 'span', '') or ''}")
        elif trace is None:
            # inline wave: the caller thread IS the request handler, so
            # its trace context is live right here
            from .tracing import current_span_id, current_trace_id

            trace = current_trace_id()
            parent = current_span_id()
        wspan = None
        if self.span_recorder is not None and trace is not None:
            from .tracing import new_span_id

            wspan = new_span_id()
        if tenant is None and jobs and self.recorder is not None:
            # event-field hint only (one dict probe / prefix split,
            # first job names the wave) — ledger attribution happens
            # in the analytics worker, not here
            tenant = self._job_tenant(jobs[0])
        gen = self.reconcile_gen
        with self._tel_mu:
            self._wave_seq += 1
            wid = self._wave_seq
            self._inflight[wid] = {"t0": t0, "kind": kind, "size": nreq,
                                   "trace": trace, "stalled": False,
                                   "slot": slot, "gen": gen,
                                   "tenant": tenant, "span": wspan,
                                   "parent": parent, "links": links,
                                   "marks": []}
            self._recent_sizes.append(nreq)
            self._recent_waits.extend(waits)
        if self.metrics is not None:
            self.metrics.wave_size.observe(nreq)
            for w in waits:
                self.metrics.wave_queue_wait.observe(w)
            self.metrics.waves_in_flight.inc()
        for w in waits:
            self._obs_phase("queue_wait", w)
        if self.recorder is not None:
            ev = {"trace": trace, "wave": wid, "wave_kind": kind,
                  "size": nreq, "jobs": len(jobs) if jobs else 1}
            if wspan is not None:
                ev["span_id"] = wspan
            if gen:
                # mesh-GLOBAL coherence epoch this wave served under
                ev["gen"] = gen
            if slot is not None:
                # pipeline slot this launch occupies (0 = the oldest
                # in-flight wave) — correlates stalls with ring depth
                ev["slot"] = slot
            if tenant is not None:
                ev["tenant"] = tenant
            self.recorder.record("wave_launched", **ev)
        return wid

    # ---- tenant event hints (ISSUE 11) ----------------------------------
    #
    # Wave/shed/degraded events carry a best-effort ``tenant`` field so
    # one tenant's incident filters server-side (/debug/events?tenant=).
    # Hints are the RAW key prefix (or the learned bucket for khash-only
    # lanes) — bounded-cardinality folding only applies to metric
    # labels, which go through the TenantLedger instead.

    def _hint_reqs(self, reqs) -> Optional[str]:
        if self.recorder is None or not reqs:
            return None
        ana = self.analytics
        if ana is None:
            return None
        return ana.tenant_hint(name=reqs[0].name)

    def _hint_khash(self, khash) -> Optional[str]:
        if self.recorder is None or len(khash) == 0:
            return None
        ana = self.analytics
        if ana is None:
            return None
        return ana.tenant_hint(khash=int(khash[0]))

    def _job_tenant(self, job) -> Optional[str]:
        reqs = getattr(job, "reqs", None)
        if reqs:
            return self._hint_reqs(reqs)
        kh = getattr(job, "khash", None)
        if kh is not None:
            return self._hint_khash(kh)
        return None

    # ---- per-phase attribution (ISSUE 4) --------------------------------
    #
    # Each wave's duration partitions into named segments: a mark with
    # name N stamps the END of segment N; the tail segment (last mark →
    # wave end) is "resolve" (future resolution / view construction).
    # Every execution path marks "pack" (host-side packing/concat up to
    # the engine call, incl. pipelined launch work) and "device" (the
    # engine/sync call), so pack + device + resolve == wave_duration up
    # to float rounding — asserted in tests/test_telemetry.py.

    def _wave_mark(self, wid: int, name: str) -> None:
        t = self._clock()
        with self._tel_mu:
            info = self._inflight.get(wid)
            if info is not None:
                info["marks"].append((name, t))

    def _mark_pack(self, wid: int) -> None:
        """Stamp the end of the pack segment — SUPPRESSED for fused
        engines (ISSUE 8): their wave is one device program, so the
        partition collapses to {device, resolve} and the `device`
        phase absorbs what fusion deletes.  The exact wave-time
        partition (sum of segments == wave duration) holds either way
        — that partition IS the proof of which phase time fusion
        removed, surfaced by the bench A/B's phase_deleted evidence."""
        if not self._fused_phases:
            self._wave_mark(wid, "pack")

    def _engine_check_packed(self, batch, khash, now_ms: int, mslot):
        """engine.check_packed with the mesh-slot column only when one
        exists: non-fused engines (oracle, store-backed) keep their
        3-arg signature."""
        if mslot is None:
            return self.engine.check_packed(batch, khash, now_ms)
        return self.engine.check_packed(batch, khash, now_ms,
                                        mslot=mslot)

    def _obs_phase(self, phase: str, seconds: float,
                   exemplar=None) -> None:
        """One phase sample → histogram (+ the analytics ledger when
        attached; KeyAnalytics.observe_phase already feeds the same
        histogram, so don't double-observe).  ``exemplar`` links the
        bucket to a recent sampled trace (ISSUE 12)."""
        ana = self.analytics
        if ana is not None:
            ana.observe_phase(phase, seconds, exemplar=exemplar)
        elif self.metrics is not None:
            from .metrics import observe_with_exemplar

            child = self._phase_hist.get(phase)
            if child is None:  # benign race: labels() is idempotent
                child = self._phase_hist[phase] = \
                    self.metrics.phase_duration.labels(phase=phase)
            observe_with_exemplar(child, max(seconds, 0.0), exemplar)

    def _tap_packed(self, khash, hits, status) -> None:
        """Post-wave columnar tap (None-guarded, never raises into the
        serving path).  Fused engines already emitted the tap columns
        ON DEVICE inside the wave's program — the host-side copies
        here are exactly what the fusion deleted, so skip them."""
        if self._fused_tap:
            return
        ana = self.analytics
        if ana is not None:
            try:
                ana.tap_packed(khash, hits, status)
            except Exception:  # pragma: no cover - analytics only
                log.exception("analytics tap")

    def _tap_reqs(self, reqs, resps) -> None:
        ana = self.analytics
        if ana is not None:
            try:
                ana.tap_reqs(reqs, resps)
            except Exception:  # pragma: no cover - analytics only
                log.exception("analytics tap")

    def _wave_end(self, wid: int, error: Optional[BaseException] = None
                  ) -> None:
        t1 = self._clock()
        with self._tel_mu:
            info = self._inflight.pop(wid, None)
            if info is None:  # already ended (defensive)
                return
            dur = max(t1 - info["t0"], 0.0)
            self._wave_count += 1
            first = self._wave_count == 1
            if first:
                self._first_wave_s = dur
            self._recent_durs.append(dur)
            self._last_wave_end = t1
            was_stalled = info["stalled"]
            any_stalled = any(i["stalled"]
                              for i in self._inflight.values())
        # segment the wave into its phases (marks stamp segment ENDS;
        # the tail is "resolve") and observe each — off the _tel_mu
        # lock, still before any caller resumes from this wave
        sr = self.span_recorder
        ex = sr.exemplar() if sr is not None else None
        phases = None
        marks = info.get("marks")
        if marks:
            phases = {}
            prev = info["t0"]
            for name, tm in marks:
                phases[name] = max(tm - prev, 0.0)
                prev = tm
            phases["resolve"] = max(t1 - prev, 0.0)
            for name, secs in phases.items():
                self._obs_phase(name, secs, exemplar=ex)
        if sr is not None and info.get("span") and info["trace"]:
            self._record_wave_span(sr, wid, info, dur, phases, error)
        if self.metrics is not None:
            from .metrics import observe_with_exemplar

            observe_with_exemplar(self.metrics.wave_duration, dur, ex)
            self.metrics.waves_in_flight.dec()
            if first:
                self.metrics.first_wave_duration.set(dur)
            if was_stalled and not any_stalled:
                self.metrics.dispatcher_stalled.set(0)
        if was_stalled:
            log.warning("dispatcher stall resolved: wave %d (%s, %d "
                        "reqs) completed after %.1fs%s", wid,
                        info["kind"], info["size"], dur,
                        " with error" if error is not None else "")
        if self.recorder is not None:
            from .telemetry import exc_text

            ev = {"trace": info["trace"], "wave": wid,
                  "wave_kind": info["kind"], "size": info["size"],
                  "duration_ms": round(dur * 1000, 3)}
            if info.get("span"):
                ev["span_id"] = info["span"]
            if info.get("gen"):
                ev["gen"] = info["gen"]
            if info.get("slot") is not None:
                ev["slot"] = info["slot"]
            if info.get("tenant") is not None:
                ev["tenant"] = info["tenant"]
            if phases is not None:
                # per-phase breakdown in ms; sums to duration_ms
                ev["phases"] = {k: round(v * 1000, 3)
                                for k, v in phases.items()}
            if error is not None:
                self.recorder.record("wave_error", error=exc_text(error),
                                     **ev)
            else:
                self.recorder.record("wave_completed", **ev)
            if first:
                # the compile event: the first wave pays any compile
                # the warmup didn't cover (cold tunnel: 250-305 s)
                self.recorder.record("first_wave", trace=info["trace"],
                                     duration_ms=round(dur * 1000, 3))

    def _record_wave_span(self, sr, wid: int, info: dict, dur: float,
                          phases, error) -> None:
        """Emit the wave's fan-in span + its phase child spans
        (ISSUE 12).  The wave clock is monotonic (`_clock`); spans
        carry wall time, so the wave is reconstructed backwards from
        `now`: children laid end-to-end in mark order EXACTLY
        partition the wave span — the PhaseLedger partition, kept, as
        tree structure.  Never raises into the serving path."""
        try:
            import time as _time

            total = sum(phases.values()) if phases else dur
            start = _time.time() - total  # clock-ok: telemetry wall clock (span layout)
            # lay the children end-to-end FIRST and take the wave's
            # end from the same cumulative walk — bitwise-exact
            # partition (start + sum(...) differs in the last float
            # bits from the accumulated chain)
            c = start
            kids = []
            for name, secs in (phases or {}).items():
                kids.append((name, c, c + secs))
                c += secs
            end = c if kids else start + total
            tid = info["trace"]
            attrs = {"wave": wid, "kind": info["kind"],
                     "size": info["size"]}
            if info.get("gen"):
                attrs["gen"] = info["gen"]
            if info.get("slot") is not None:
                attrs["slot"] = info["slot"]
            if info.get("tenant") is not None:
                attrs["tenant"] = info["tenant"]
            if info.get("links"):
                attrs["links"] = ",".join(info["links"])
            if error is not None:
                from .telemetry import exc_text

                attrs["error"] = exc_text(error)
            sr.add({"trace_id": tid, "span_id": info["span"],
                    "parent_id": info.get("parent"), "name": "wave",
                    "start": start, "end": end, "attrs": attrs})
            from .tracing import new_span_id

            for name, k0, k1 in kids:
                sr.add({"trace_id": tid, "span_id": new_span_id(),
                        "parent_id": info["span"],
                        "name": f"wave.{name}",
                        "start": k0, "end": k1, "attrs": {}})
        except Exception:  # pragma: no cover - tracing only
            log.exception("wave span record")

    def _watchdog_run(self) -> None:
        while not self._closing.wait(self._watch_interval_s):
            try:
                self._watchdog_poll()
            except Exception:  # pragma: no cover - must never die
                log.exception("dispatcher watchdog poll")

    def _watchdog_poll(self) -> bool:
        """One watchdog scan: flag waves in flight past the threshold.
        Separated from the thread loop so tests drive it with a fake
        clock (no real sleeps).  Returns True when a NEW stall was
        flagged this scan."""
        now = self._clock()
        newly = []
        with self._tel_mu:
            for wid, info in self._inflight.items():
                if (not info["stalled"]
                        and now - info["t0"] >= self._stall_threshold_s):
                    info["stalled"] = True
                    newly.append((wid, dict(info)))
            self._stall_count += len(newly)
            any_stalled = any(i["stalled"]
                              for i in self._inflight.values())
        if self.metrics is not None:
            self.metrics.dispatcher_stalled.set(1 if any_stalled else 0)
        for wid, info in newly:
            age = now - info["t0"]
            msg = (f"wave {wid} ({info['kind']}, {info['size']} reqs) in "
                   f"flight {age:.1f}s > stall threshold "
                   f"{self._stall_threshold_s:.1f}s — likely a cold "
                   f"device compile; callers time out at "
                   f"{self.RESULT_TIMEOUT_S:.0f}s "
                   f"(GUBER_RESULT_TIMEOUT_S)")
            log.warning("dispatcher stall: %s", msg)
            if self.metrics is not None:
                self.metrics.stall_event_counter.inc()
            if self.recorder is not None:
                self.recorder.record("wave_stalled", error=msg,
                                     trace=info["trace"], wave=wid,
                                     wave_kind=info["kind"],
                                     size=info["size"],
                                     age_s=round(age, 3))
        return bool(newly)

    def _result_timeout(self, e: BaseException) -> BaseException:
        """Build the caller-facing timeout with a wave diagnosis baked
        into the message — str() of a bare TimeoutError is EMPTY, which
        made the round-5 rows undiagnosable.  Same exception type, so
        existing handlers keep matching."""
        stats = self.debug_stats()
        msg = (f"dispatcher wave result timed out after "
               f"{self.RESULT_TIMEOUT_S:.0f}s (queue_depth="
               f"{stats['queue_depth']}, in_flight={stats['in_flight']}, "
               f"oldest_wave_age_s={stats['oldest_wave_age_s']}, "
               f"stalled={stats['stalled']}; a cold tunnel compile is "
               f"250-305 s — raise GUBER_RESULT_TIMEOUT_S when callers "
               f"can arrive before warmup)")
        with self._tel_mu:
            self._timeout_count += 1
        if self.metrics is not None:
            self.metrics.wave_timeout_counter.inc()
        if self.recorder is not None:
            self.recorder.record("wave_timeout", error=msg)
        return type(e)(msg)

    def debug_stats(self) -> dict:
        """Cheap dispatcher state for /healthz?deep=1 and timeout
        diagnoses — no device work."""
        now = self._clock()
        with self._tel_mu:
            inflight = [dict(i) for i in self._inflight.values()]
            last_end = self._last_wave_end
            waves, stalls = self._wave_count, self._stall_count
            timeouts, first = self._timeout_count, self._first_wave_s
        oldest = max((now - i["t0"] for i in inflight), default=None)
        return {
            "queue_depth": self._queue.qsize(),
            "in_flight": len(inflight),
            "oldest_wave_age_s": (round(oldest, 3)
                                  if oldest is not None else None),
            "last_wave_age_s": (round(now - last_end, 3)
                                if last_end is not None else None),
            "stalled": any(i["stalled"] for i in inflight),
            "waves": waves,
            "stall_events": stalls,
            "timeouts": timeouts,
            "first_wave_s": (round(first, 3)
                             if first is not None else None),
            "stall_threshold_s": self._stall_threshold_s,
            "result_timeout_s": self.RESULT_TIMEOUT_S,
            # overlapped-pipeline shape: 0 when the pipeline is off
            # (CPU default / capability-less engine), else the depth-K
            # in-flight bound (GUBER_PIPELINE_DEPTH)
            "pipeline_depth": (self.pipeline_depth if self._pipelined
                               else 0),
            # overload admission control (ISSUE 5): ingress bound,
            # rows currently inside it, rows shed, drain state
            "admission": {"limit_rows": self.admission_limit,
                          # lock-free: healthz snapshot, staleness ok
                          "queued_rows": self._queued_rows,
                          "shed_rows": self._shed_rows,
                          "draining": self._draining,
                          "projected_wait_s": round(
                              self.projected_queue_wait_s(), 4)},
            "buffer_pool": (self.engine.wave_pool.stats()
                            if hasattr(self.engine, "wave_pool")
                            else None),
            # heavy-hitter tap shape (ISSUE 4): queue depth + drop
            # count — a saturated analytics worker sheds waves, it
            # never backs the serving path up
            "analytics": (self.analytics.stats()
                          if self.analytics is not None else None),
        }

    def telemetry_snapshot(self) -> dict:
        """debug_stats + recent-wave percentiles (bench.py folds this
        into each section's BENCH JSON row so perf rounds are
        self-diagnosing)."""
        import numpy as np

        with self._tel_mu:
            sizes = list(self._recent_sizes)
            durs = list(self._recent_durs)
            waits = list(self._recent_waits)

        def pct(xs, p, scale=1.0, nd=3):
            if not xs:
                return None
            return round(float(np.percentile(xs, p)) * scale, nd)

        snap = self.debug_stats()
        snap.update({
            "wave_size_p50": pct(sizes, 50),
            "wave_size_p99": pct(sizes, 99),
            "wave_duration_p50_ms": pct(durs, 50, 1e3),
            "wave_duration_p99_ms": pct(durs, 99, 1e3),
            "queue_wait_p50_ms": pct(waits, 50, 1e3),
            "queue_wait_p99_ms": pct(waits, 99, 1e3),
        })
        return snap

    # ---- the merge loop -------------------------------------------------

    def _dequeued(self, job) -> None:
        """Admission accounting: the job left the ingress queue (its
        rows now belong to a wave/carry, not the admission bound)."""
        with self._submit_mu:
            self._queued_rows -= _job_len(job)
            if self._queued_rows < 0:  # defensive
                self._queued_rows = 0

    def _drain_wave(self, block_s: float = 0.1) -> List[_Job]:
        """Block for one job (up to ``block_s``), then collect more for
        up to the coalescing window (GUBER_COALESCE_US, bounded by
        max_wave total requests) so bursty concurrent callers share the
        next device launch.  Jobs already queued are taken greedily
        FIRST: when the backlog alone fills max_wave rows, the wave
        launches with NO coalescing wait at all — the window exists to
        catch stragglers, not to tax a saturated queue."""
        if self._carry is not None:
            first, self._carry = self._carry, None
        else:
            try:
                first = (self._queue.get(timeout=block_s) if block_s > 0
                         else self._queue.get_nowait())
            except queue.Empty:
                return []
            self._dequeued(first)
        wave = [first]
        total = _job_len(first)
        deadline = None  # armed only after the backlog is drained
        while total < self.max_wave:
            try:
                job = self._queue.get_nowait()
                self._dequeued(job)
            except queue.Empty:
                if self.max_delay_s <= 0:
                    break
                if deadline is None:
                    deadline = time.monotonic() + self.max_delay_s
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                try:
                    job = self._queue.get(timeout=remain)
                    self._dequeued(job)
                except queue.Empty:
                    break
            if total + _job_len(job) > self.max_wave:
                # never overshoot max_wave: an oversized wave splits
                # into one dense launch + a sparse tail launch at the
                # small bucket — the tail's fixed per-launch cost is
                # pure waste.  The job that would overflow leads the
                # NEXT wave instead.
                self._carry = job
                try:
                    # racer preemption point: delay parks the carried
                    # job across the wave boundary; error drops it
                    # (future failed, never launched)
                    self._fault("dispatch_carry")
                except Exception as e:  # noqa: BLE001 - injected only
                    self._carry = None
                    if not job.future.done():
                        job.future.set_exception(e)
                break
            wave.append(job)
            total += _job_len(job)
        if wave:
            try:
                # racer preemption point: a delay here widens the window
                # between collecting this wave and launching it, so
                # concurrent lanes land in the NEXT wave/engine call
                self._fault("dispatch_merge")
            except Exception as e:  # noqa: BLE001 - injected only
                for j in wave:
                    if not j.future.done():
                        j.future.set_exception(e)
                return []
        return wave

    def _run(self) -> None:
        # Overlapped wave pipeline (depth K = pipeline_depth,
        # GUBER_PIPELINE_DEPTH) for pure-packed waves: while up to K
        # launched waves are in flight on the device, the worker drains
        # and PACKS the next wave into a pooled upload buffer
        # (core/batch.py › WaveBufferPool via engine._fill_packed) —
        # steady-state throughput becomes max(host, device) instead of
        # host + device.  Launches are ordered by the state threading
        # device-side, so correctness does not depend on when results
        # are read; completion resolves strictly oldest-first (the
        # in-flight ring is FIFO), preserving per-job splice order.
        # Mixed/list waves flush the pipeline first (bounded caller
        # latency).  The TPU/CPU policy lives in _want_pipeline (shared
        # with the inline fast path's gate).
        from collections import deque

        pipelined = self._pipelined
        depth = self.pipeline_depth
        pending: deque = deque()  # [(jobs, token)] launched, unsynced

        def flush_pending() -> None:
            while pending:
                self._sync_and_resolve(*pending.popleft())

        while not (self._closing.is_set() and self._queue.empty()
                   and self._carry is None):
            wave = self._drain_wave(block_s=0.0 if pending else 0.1)
            if not wave:
                flush_pending()
                continue
            if pipelined and all(isinstance(j, _PackedJob) for j in wave):
                launched = self._launch_packed_jobs(wave,
                                                    slot=len(pending))
                if launched is not None:
                    pending.append(launched)
                    while len(pending) >= depth:
                        self._sync_and_resolve(*pending.popleft())
                continue
            flush_pending()
            # Packed jobs carry per-request arrival times in their `now`
            # column, so they ALL merge into one launch regardless of
            # wall-clock skew between callers — the device honors each
            # request's own time.  List jobs still group by timestamp
            # (pack_requests bakes one now per job, incl. Gregorian
            # period ends).  Execution units run in ascending-now order
            # so a list job never applies BEHIND a packed launch that
            # already advanced a shared key's clock (the step clamps
            # per-key time as the final defense).
            packed = [j for j in wave if isinstance(j, _PackedJob)]
            by_now: dict = {}
            for j in wave:
                if isinstance(j, _Job):
                    by_now.setdefault(j.now_ms, []).append(j)
            units = [(now, "list", jobs) for now, jobs in by_now.items()]
            if packed:
                units.append((min(j.now_ms for j in packed), "packed",
                              packed))
            if (len(units) > 1 and by_now
                    and hasattr(self.engine, "check_packed")):
                # several instants in one wave: pack each list job at
                # its own now and merge EVERYTHING into the packed
                # launch — per-request time makes quantization
                # unnecessary (single-unit waves keep the object lane's
                # zero-repack path)
                try:
                    self._run_merged_wave(wave)
                    continue
                except Exception as e:  # noqa: BLE001
                    for j in wave:
                        if not j.future.done():
                            j.future.set_exception(e)
                    continue
            for now, kind, jobs in sorted(units, key=lambda u: u[0]):
                if kind == "list":
                    self._run_list_jobs(jobs, now)
                else:
                    self._run_packed_jobs(jobs)
        # closing: resolve anything still in flight
        while pending:
            self._sync_and_resolve(*pending.popleft())

    def _launch_packed_jobs(self, jobs, slot: Optional[int] = None):
        """Concat + LAUNCH a pure-packed wave; returns (jobs, token,
        wave_id) for the sync phase, or None when dispatch failed
        (futures already resolved with the error).  The wave stays "in
        flight" (watchdog-visible) from launch until its sync resolves;
        ``slot`` is its position in the in-flight ring at launch."""
        wid = self._wave_begin("packed_pipelined", jobs, slot=slot)
        try:
            self._fault("dispatch_launch")
            if len(jobs) == 1:
                batch, khash = jobs[0].batch, jobs[0].khash
            else:
                batch, khash = _concat_columns(
                    [(j.batch, j.khash) for j in jobs])
            mslot = _concat_mslot(jobs)
            now = max(j.now_ms for j in jobs)
            with self._engine_lock:
                self._fault("device_step")
                token = (self.engine.launch_packed(batch, khash, now)
                         if mslot is None
                         else self.engine.launch_packed(batch, khash,
                                                        now,
                                                        mslot=mslot))
            # the launch's host-side routing/fill IS pack work; device
            # time runs from here until sync_packed returns
            self._mark_pack(wid)
            return (jobs, token, wid, batch, khash)
        except Exception as e:  # noqa: BLE001 - surfaced per-caller
            self._wave_end(wid, error=e)
            for j in jobs:
                if not j.future.done():
                    j.future.set_exception(e)
            return None

    def _sync_and_resolve(self, jobs, token, wid, batch, khash) -> None:
        try:
            self._fault("dispatch_sync")
            cols = self.engine.sync_packed(
                token, engine_lock=self._engine_lock)
            self._wave_mark(wid, "device")
            # racer preemption point: hold the result splice while later
            # waves launch (callers still waiting on their views)
            self._fault("dispatch_splice")
            a = 0
            for j in jobs:
                b = a + len(j.khash)
                # a row-bounds view, NOT materialized slices: response
                # build runs in each caller's own thread (ResultView)
                j.future.set_result(ResultView(cols, a, b))
                a = b
            self._wave_end(wid)
            self._tap_packed(khash, batch.hits, cols[0])
        except Exception as e:  # noqa: BLE001 - surfaced per-caller
            self._wave_end(wid, error=e)
            for j in jobs:
                if not j.future.done():
                    j.future.set_exception(e)

    def _run_merged_wave(self, wave) -> None:
        """Cross-time merge of a mixed wave: every list job is packed at
        its own now (Gregorian period ends are per-instant), columns
        concatenate with the packed jobs, and ONE launch serves all —
        the device applies each key's requests in arrival-time order."""
        import numpy as np

        from .core.batch import pack_requests
        from .hashing import hash_request_keys
        from .parallel.sharded import responses_from_columns

        wid = self._wave_begin("merged", wave)
        try:
            tap = self._run_merged_wave_inner(
                wave, np, pack_requests, hash_request_keys,
                responses_from_columns, wid)
        except Exception as e:  # noqa: BLE001 - caller fails the futures
            self._wave_end(wid, error=e)
            raise
        self._wave_end(wid)
        self._tap_packed(*tap)

    def _run_merged_wave_inner(self, wave, np, pack_requests,
                               hash_request_keys,
                               responses_from_columns, wid) -> tuple:
        parts = []  # (job, batch, khash, errs or None)
        mparts = []
        for j in wave:
            if isinstance(j, _PackedJob):
                parts.append((j, j.batch, j.khash, None))
                mparts.append(j.mslot if j.mslot is not None
                              else np.full(len(j.khash), -1, np.int32))
            else:
                kh = hash_request_keys([r.name for r in j.reqs],
                                       [r.unique_key for r in j.reqs])
                b, errs = pack_requests(j.reqs, j.now_ms,
                                        size=len(j.reqs), key_hashes=kh)
                parts.append((j, b, kh, errs))
                mparts.append(np.full(len(kh), -1, np.int32))
        batch, khash = _concat_columns([(p[1], p[2]) for p in parts])
        mslot = (np.concatenate(mparts)
                 if any(isinstance(j, _PackedJob)
                        and j.mslot is not None for j in wave)
                 else None)
        now = max(j.now_ms for j in wave)
        self._mark_pack(wid)
        with self._engine_lock:
            self._fault("device_step")
            st, lim, rem, rst, full = self._engine_check_packed(
                batch, khash, now, mslot)
        self._wave_mark(wid, "device")
        self._fault("dispatch_splice")
        a = 0
        cols = (st, lim, rem, rst, full)
        for j, _, kh, errs in parts:
            b_ = a + len(kh)
            if isinstance(j, _PackedJob):
                j.future.set_result(ResultView(cols, a, b_))
            else:
                j.future.set_result(responses_from_columns(
                    (st[a:b_], lim[a:b_], rem[a:b_], rst[a:b_],
                     full[a:b_]), errs))
            a = b_
        return (khash, batch.hits, st)

    def _run_list_jobs(self, jobs, now) -> None:
        if not jobs:
            return
        merged: List[RateLimitRequest] = []
        slices: List[Tuple[_Job, int, int]] = []
        for j in jobs:
            start = len(merged)
            merged.extend(j.reqs)
            slices.append((j, start, len(merged)))
        wid = self._wave_begin("list", jobs)
        try:
            self._fault("dispatch_launch")
            self._mark_pack(wid)
            with self._engine_lock:
                self._fault("device_step")
                resps = self.engine.check_batch(merged, now)
            self._wave_mark(wid, "device")
            self._fault("dispatch_splice")
            for j, a, b in slices:
                j.future.set_result(resps[a:b])
            self._wave_end(wid)
            self._tap_reqs(merged, resps)
        except Exception as e:  # noqa: BLE001 - surfaced per-caller
            self._wave_end(wid, error=e)
            for j, _, _ in slices:
                if not j.future.done():
                    j.future.set_exception(e)

    def _run_packed_jobs(self, jobs) -> None:
        if not jobs:
            return
        import numpy as np

        wid = self._wave_begin("packed", jobs)
        try:
            if len(jobs) == 1:
                batch, khash = jobs[0].batch, jobs[0].khash
            else:
                batch, khash = _concat_columns(
                    [(j.batch, j.khash) for j in jobs])
            mslot = _concat_mslot(jobs)
            # scalar now only backstops sweeps/padding; requests use
            # their own now column.  max() keeps sweep time monotonic.
            now = max(j.now_ms for j in jobs)
            self._fault("dispatch_launch")
            self._mark_pack(wid)
            with self._engine_lock:
                self._fault("device_step")
                cols = self._engine_check_packed(batch, khash, now,
                                                 mslot)
            self._wave_mark(wid, "device")
            self._fault("dispatch_splice")
            a = 0
            for j in jobs:
                b = a + len(j.khash)
                j.future.set_result(ResultView(cols, a, b))
                a = b
            self._wave_end(wid)
            self._tap_packed(khash, batch.hits, cols[0])
        except Exception as e:  # noqa: BLE001 - surfaced per-caller
            self._wave_end(wid, error=e)
            for j in jobs:
                if not j.future.done():
                    j.future.set_exception(e)

    def close(self) -> None:
        with self._submit_mu:
            self._closing.set()
        # Drain inline stragglers: a caller that passed _try_inline's
        # closing check before the set() above may still be inside the
        # engine — re-acquiring its mutex restores the invariant that
        # no dispatcher-initiated engine call is in flight once close()
        # returns (instance.close snapshots engine state right after).
        with self._inline_mu:
            pass
        self._thread.join(timeout=10)
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)
        while True:
            try:
                job = self._queue.get_nowait()
                job.future.set_exception(RuntimeError("dispatcher closed"))
            except queue.Empty:
                break

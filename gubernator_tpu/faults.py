"""Deterministic fault injection: named faultpoints, armed on demand.

The failure-domain resilience layer (ISSUE 5) needs failures it can
cause on purpose: a chaos run that kills an owner, delays the device
step, or drops a broadcast must be REPEATABLE, or a flake found once is
lost forever.  This module provides named faultpoints compiled to
near-zero-cost checks — each instrumented site costs one attribute read
(``fs.armed``) while disarmed — armed from the ``GUBER_FAULT`` env var,
``POST /debug/faults``, or ``guber-cli debug faults --set``.

Spec grammar (comma-separated)::

    point[@tag]:mode[:arg[:prob]]

    peer_send:error:0.3           30% of peer flush RPCs fail
    device_step:delay:50ms        every device step sleeps 50ms
    peer_send@10.0.0.2:5001:error forwards to that peer always fail
    global_broadcast:error:1.0:   (prob defaults to 1.0)

Modes:

- ``error`` — raise :class:`FaultInjected` at the faultpoint; ``arg``
  is the probability (default 1.0).
- ``delay`` — sleep; ``arg`` is a Go-style duration (``50ms``, ``1s``),
  optional 4th field is the probability.

``tag`` scopes a point to one call-site identity (peer points pass the
peer's gRPC address); a point without a tag matches every site.

Determinism: every point draws from its own ``random.Random`` seeded
from ``(seed, point, tag, mode)`` (``GUBER_FAULT_SEED``, default 0), so
a chaos run replays bit-for-bit regardless of how other points
interleave.  Each :class:`FaultSet` is per-instance (the daemon's
``POST /debug/faults`` arms only that daemon), so in-process cluster
tests can fail one daemon's view of the world without touching its
siblings.

The faultpoint catalog lives in :data:`FAULT_POINTS` (documented in
RESILIENCE.md); arming an unknown point is a loud error — a typo'd
chaos run must not silently test nothing.
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger("gubernator_tpu.faults")


class FaultInjected(Exception):
    """Raised by an armed ``error``-mode faultpoint."""


#: faultpoint catalog: name → where the check lives (RESILIENCE.md
#: carries the operator-facing version of this table)
FAULT_POINTS = {
    "peer_send": "peer_client._SendLane._launch — before the flush RPC "
                 "leaves (tag: peer address)",
    "peer_recv": "peer_client._SendLane._rpc_done — after a flush RPC "
                 "succeeded, before entries resolve (tag: peer address)",
    "peer_circuit": "PeerClient._circuit_blocked — forces the peer's "
                    "circuit to read as OPEN (tag: peer address)",
    "dispatch_enqueue": "Dispatcher._submit — job admission into the "
                        "wave queue",
    "dispatch_launch": "Dispatcher wave launch — before the engine call "
                       "of a queued wave",
    "dispatch_sync": "Dispatcher._sync_and_resolve — before a pipelined "
                     "wave's sync",
    "dispatch_merge": "Dispatcher._drain_wave — after a wave's jobs are "
                      "collected, before the merge/launch (delay mode "
                      "widens the window for more callers to land in "
                      "the NEXT wave — the racer's preemption point)",
    "dispatch_carry": "Dispatcher._drain_wave — when an overflow job is "
                      "held as the next wave's carry (delay mode parks "
                      "the carried job across the wave boundary)",
    "dispatch_splice": "Dispatcher result splicing — after the engine "
                       "call, before per-job futures resolve from the "
                       "shared result columns (delay mode holds "
                       "responses while later waves launch)",
    "device_step": "the engine call itself (inline and queued waves)",
    "wire_ingest": "instance wire entry — before the C++ parse",
    "global_broadcast": "GlobalManager._run_broadcasts — before the "
                        "owner broadcast tick",
    "global_hits": "GlobalManager._run_async_hits — before the hit "
                   "flush tick (failed aggregates requeue)",
    "global_accum_swap": "V1Instance._mesh_reconcile_tick — before the "
                         "mesh-GLOBAL accumulator double-buffer swap "
                         "(error aborts the tick; buffers untouched)",
    "global_psum": "V1Instance._mesh_reconcile_tick — before the "
                   "mesh-GLOBAL reconcile collective launches (error "
                   "swaps the retired buffer back; no hit stranded)",
    "mr_sync": "MultiRegionManager._run_async_reqs — before the "
               "cross-region flush tick (queues not yet popped, so an "
               "aborted tick loses nothing)",
    "snapshot": "instance._save_to_loader — before the Loader snapshot",
    "restore": "instance._load_from_loader — before the Loader restore",
    "tier_promote": "TierController.promote — after the admissibility "
                    "gate, before the cold row is written to the "
                    "device table (error aborts the migration: the row "
                    "stays cold, tier_migrations_aborted increments)",
    "tier_demote": "TierController.demote — before the victim row is "
                   "gathered off the device (error aborts the "
                   "eviction: the row stays hot and the triggering "
                   "promotion is abandoned)",
}


class _Point:
    __slots__ = ("name", "tag", "mode", "prob", "delay_s", "rng",
                 "checked", "fired")

    def __init__(self, name: str, tag: Optional[str], mode: str,
                 prob: float, delay_s: float, seed: int):
        self.name = name
        self.tag = tag
        self.mode = mode
        self.prob = prob
        self.delay_s = delay_s
        # per-point stream: replay does not depend on how OTHER points
        # interleave their draws
        self.rng = random.Random(f"{seed}|{name}|{tag}|{mode}")
        self.checked = 0
        self.fired = 0

    def describe(self) -> dict:
        return {"point": self.name, "tag": self.tag, "mode": self.mode,
                "prob": self.prob,
                "delay_ms": round(self.delay_s * 1000, 3),
                "checked": self.checked, "fired": self.fired}


def _parse_spec(spec: str, seed: int) -> List[_Point]:
    from .config import parse_duration_ms

    points: List[_Point] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        head = parts[0]
        tag: Optional[str] = None
        if "@" in head:
            head, _, tag = head.partition("@")
            # peer tags are host:port — the ":" split above cut the
            # port off; a purely-numeric next field can only be that
            # port (modes are words, probabilities carry a dot)
            if len(parts) > 1 and parts[1].isdigit():
                tag = f"{tag}:{parts[1]}"
                parts.pop(1)
        name = head.strip()
        if name not in FAULT_POINTS:
            raise ValueError(
                f"unknown faultpoint {name!r} (catalog: "
                f"{', '.join(sorted(FAULT_POINTS))})")
        mode = parts[1].strip() if len(parts) > 1 else "error"
        prob, delay_s = 1.0, 0.0
        if mode == "error":
            if len(parts) > 2 and parts[2].strip():
                prob = float(parts[2])
        elif mode == "delay":
            if len(parts) < 3 or not parts[2].strip():
                raise ValueError(
                    f"faultpoint {name!r}: delay mode needs a duration "
                    f"(e.g. {name}:delay:50ms)")
            delay_s = parse_duration_ms(parts[2].strip()) / 1000.0
            if len(parts) > 3 and parts[3].strip():
                prob = float(parts[3])
        else:
            raise ValueError(
                f"faultpoint {name!r}: unknown mode {mode!r} "
                "(want 'error' or 'delay')")
        if not (0.0 <= prob <= 1.0):
            raise ValueError(
                f"faultpoint {name!r}: probability {prob} outside [0,1]")
        points.append(_Point(name, tag or None, mode, prob, delay_s, seed))
    return points


class FaultSet:
    """One instance's armed faultpoints.

    ``armed`` is the hot-path gate: every instrumented site reads it
    first (``if fs is not None and fs.armed: fs.fire(...)``) so the
    disarmed cost is one attribute read — the acceptance A/B on
    ``6_service_path`` holds it under 1%.
    """

    def __init__(self, seed: int = 0):
        self.armed = False
        self.seed = seed
        self._mu = threading.Lock()
        self._points: Dict[str, List[_Point]] = {}
        self._spec = ""
        #: optional hooks wired by the owning instance
        self.metrics = None
        self.recorder = None

    @classmethod
    def from_env(cls, env=None) -> "FaultSet":
        env = os.environ if env is None else env
        seed = 0
        raw_seed = env.get("GUBER_FAULT_SEED", "")
        if raw_seed:
            try:
                seed = int(raw_seed)
            except ValueError:
                log.warning("malformed GUBER_FAULT_SEED=%r ignored",
                            raw_seed)
        fs = cls(seed=seed)
        spec = env.get("GUBER_FAULT", "")
        if spec:
            fs.arm(spec)
        return fs

    # ---- arming ---------------------------------------------------------

    def arm(self, spec: str, seed: Optional[int] = None) -> dict:
        """Replace the armed set with ``spec`` (empty spec disarms).
        Raises ValueError on malformed specs — nothing changes then."""
        if seed is not None:
            self.seed = seed
        points = _parse_spec(spec, self.seed)
        by_name: Dict[str, List[_Point]] = {}
        for p in points:
            by_name.setdefault(p.name, []).append(p)
        with self._mu:
            self._points = by_name
            self._spec = spec if points else ""
            self.armed = bool(points)
        if points:
            log.warning("faults ARMED (seed=%d): %s", self.seed, spec)
        if self.recorder is not None:
            if points:
                self.recorder.record("fault_armed", spec=spec,
                                     seed=self.seed)
            else:
                self.recorder.record("fault_cleared")
        return self.describe()

    def clear(self) -> dict:
        return self.arm("")

    def describe(self) -> dict:
        with self._mu:
            pts = [p.describe() for ps in self._points.values()
                   for p in ps]
        return {"armed": self.armed, "seed": self.seed,
                "spec": self._spec, "points": pts,
                "catalog": sorted(FAULT_POINTS)}

    # ---- the hot-path checks -------------------------------------------

    def _match(self, name: str, tag: Optional[str]) -> List[_Point]:
        pts = self._points.get(name)
        if not pts:
            return ()
        return [p for p in pts if p.tag is None or p.tag == tag]

    def fire(self, name: str, tag: Optional[str] = None) -> None:
        """Run the faultpoint: sleep for matched ``delay`` points, raise
        :class:`FaultInjected` for a matched ``error`` point.  Callers
        gate on ``.armed`` first; this re-checks so racing a disarm is
        harmless."""
        if not self.armed:
            return
        boom = False
        delay = 0.0
        fired = 0
        with self._mu:
            for p in self._match(name, tag):
                p.checked += 1
                if p.prob < 1.0 and p.rng.random() >= p.prob:
                    continue
                p.fired += 1
                fired += 1
                if p.mode == "delay":
                    delay += p.delay_s
                else:
                    boom = True
        if fired and self.metrics is not None:
            self.metrics.fault_injected.labels(point=name).inc(fired)
        if delay > 0:
            time.sleep(delay)
        if boom:
            raise FaultInjected(
                f"fault injected: {name}" + (f"@{tag}" if tag else ""))

    def should(self, name: str, tag: Optional[str] = None) -> bool:
        """Boolean twin of ``fire`` for points that gate a condition
        instead of raising (``peer_circuit``: forces circuit-open)."""
        if not self.armed:
            return False
        try:
            self.fire(name, tag)
        except FaultInjected:
            return True
        return False

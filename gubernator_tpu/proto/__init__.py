"""Generated protobuf modules (protoc --python_out; see Makefile).

protoc emits absolute imports (``import gubernator_pb2``) which don't
resolve inside a package; alias the module before loading peers_pb2.
"""
import sys

from . import gubernator_pb2

sys.modules.setdefault("gubernator_pb2", gubernator_pb2)

from . import peers_pb2  # noqa: E402

__all__ = ["gubernator_pb2", "peers_pb2"]

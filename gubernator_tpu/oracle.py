"""M0 — the semantic oracle (parity referee).

Pure-Python, exact-integer implementation of the rate-limit behavior
contract (SURVEY.md §2.4; reference algorithms.go › tokenBucket /
tokenBucketNewItem / leakyBucket / leakyBucketNewItem — reconstructed,
mount was empty).  Every device kernel is tested bit-for-bit against this
module; where the reference's float64 leaky-bucket arithmetic could not be
reproduced exactly, the contract below REDEFINES it in exact integer
"token-duration" fixed point (see `Leaky fixed point` below) and the
deviation is documented.

Contract summary
----------------

All time is int64 epoch milliseconds.  Each key's state ("item"):

- ``algorithm``, ``limit``, ``duration`` (ms or Gregorian ordinal),
  ``burst`` (leaky; 0 → limit), ``t_ms`` (token: created_at; leaky:
  updated_at), ``expire_at`` (token: reset boundary; leaky: sliding cache
  TTL), ``remaining`` and ``status`` (stored; returned for hits=0
  queries, mirroring the reference's ``rl.Status = t.Status`` early
  return).

Token bucket (reference algorithms.go › tokenBucket):

1. Missing or ``now >= expire_at`` → fresh item: remaining=limit,
   created=now, expire = now+duration (or Gregorian period end).
2. Duration change recomputes expire from created_at; if that expires the
   item now, it is re-created fresh.
3. ``RESET_REMAINING`` forces remaining=limit (and adopts the new limit).
4. Limit change adjusts in place: remaining = clamp(remaining +
   new-old, 0, new)  (equivalently new_limit - used, clamped; matches
   TestChangeLimit semantics).
5. hits=0 → pure query: returns stored status, no mutation.
   hits ≤ remaining → UNDER_LIMIT, remaining -= hits.
   hits > remaining → OVER_LIMIT, NO decrement (DRAIN_OVER_LIMIT zeroes
   remaining instead).
6. reset_time = expire_at.

Leaky fixed point (deviation from the reference, by design):

The reference stores leaky ``Remaining`` as float64 and leaks
``elapsed / (duration/limit)`` tokens.  Floating point cannot be
reproduced bit-for-bit across TPU (no f64) and host, so this contract
stores ``remaining_td = remaining × duration_eff`` ("token-duration"
units, int64) and replenishes exactly: ``remaining_td += elapsed × limit``
(clamped to ``burst × duration_eff``).  A request costs
``hits × duration_eff`` td.  Observable integer behavior (allow/deny,
``remaining`` floor, reset_time) matches the reference's within one
sub-millisecond-token rounding; allow/deny parity on integer-rate
workloads is exact.  Domain: every td product is kept ≤ TD_BOUND (2^61)
by the input clamps below plus two in-kernel guards (rescale/replenish —
see the comment block above ``_clamp_token``).

- Gregorian ordinals use the calendar for token expiry; the leak rate for
  leaky uses the fixed-width approximation (GREGORIAN_APPROX_MS).
- duration change rescales td to the new denominator (whole tokens exact,
  fractional part floor-rounded).
- limit change does NOT adjust leaky remaining (the refill rate simply
  changes); burst is re-adopted from each request.
- reset_time = now + duration_eff // limit (ms until one token leaks);
  expire_at = now + duration_eff (sliding TTL).

Input clamps (applied to every request): hits < 0 → 0, limit < 0 → 0,
non-Gregorian duration < 1 → 1, burst ≤ 0 → limit.  int64-safety bounds
(types.py): duration ≤ DURATION_MAX (2^53 ms); token hits/limit ≤
VALUE_MAX (2^53); leaky eff ≤ EFF_MAX (2^35, ~1.09y — calendar windows
beyond that are DURATION_IS_GREGORIAN's job) and leaky hits/limit/burst
≤ TD_BOUND // eff.  A 30-day (or multi-year) millisecond duration passes
through un-truncated on both algorithms.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .gregorian import gregorian_expiration, gregorian_rate_duration_ms
from .types import (
    DURATION_MAX,
    EFF_MAX,
    FRAC_SAFE,
    TD_BOUND,
    VALUE_MAX,
    Algorithm,
    Behavior,
    RateLimitRequest,
    RateLimitResponse,
    Status,
)


@dataclass
class Item:
    """Oracle-side mirror of one device table row."""

    __slots__ = (
        "algorithm",
        "limit",
        "duration",
        "eff_ms",
        "burst",
        "remaining",
        "t_ms",
        "expire_at",
        "status",
    )
    algorithm: int
    limit: int
    duration: int  # as given by the request (ms or Gregorian ordinal)
    eff_ms: int  # effective ms denominator the item was created/rescaled with
    burst: int
    remaining: int  # token: tokens; leaky: token-duration (td) units
    t_ms: int
    expire_at: int
    status: int


def _eff_duration_ms(duration: int, behavior: int) -> int:
    """Effective millisecond duration used for leak rate / td denominator."""
    if behavior & Behavior.DURATION_IS_GREGORIAN:
        return gregorian_rate_duration_ms(duration)
    return max(int(duration), 1)


def _token_expire(now_ms: int, created_ms: int, duration: int, behavior: int) -> int:
    if behavior & Behavior.DURATION_IS_GREGORIAN:
        return gregorian_expiration(now_ms, duration)
    return created_ms + max(int(duration), 1)


# Input clamps (the int64-safety contract; bounds live in types.py and
# are applied identically by the device packers, core/batch.py):
#
# - duration (ms) ≤ DURATION_MAX (2^53, ~285k years) — a 30-day or
#   multi-year window passes through un-truncated.
# - TOKEN_BUCKET hits/limit ≤ VALUE_MAX (2^53).
# - LEAKY_BUCKET: eff ≤ EFF_MAX (2^35, ~1.09y), then hits/limit/burst
#   ≤ TD_BOUND // eff so every td product stays ≤ 2^61.
#
# Two in-kernel guards complete the contract (mirrored bit-for-bit in
# core/step.py › _apply_position):
# - rescale-on-duration-change clamps whole tokens to TD_BOUND // new_eff
#   and keeps the sub-token fraction only when both denominators are
#   ≤ FRAC_SAFE (else floors to whole tokens — a < 1-token deviation);
# - replenish treats elapsed > TD_BOUND // limit as "bucket refilled to
#   burst" (exact: the true product already exceeds the burst cap).


def _clamp_token(req: RateLimitRequest) -> Tuple[int, int, int]:
    hits = min(max(int(req.hits), 0), VALUE_MAX)
    limit = min(max(int(req.limit), 0), VALUE_MAX)
    duration = min(int(req.duration), DURATION_MAX)
    return hits, limit, duration


def _clamp_leaky(req: RateLimitRequest) -> Tuple[int, int, int, int, int]:
    """(hits, limit, duration, burst, eff) under the leaky td bounds."""
    duration = min(int(req.duration), DURATION_MAX)
    eff = min(_eff_duration_ms(duration, int(req.behavior)), EFF_MAX)
    cap_v = min(TD_BOUND // eff, VALUE_MAX)
    hits = min(max(int(req.hits), 0), cap_v)
    limit = min(max(int(req.limit), 0), cap_v)
    burst = int(req.burst) if int(req.burst) > 0 else limit
    burst = min(burst, cap_v)
    return hits, limit, duration, burst, eff


def _new_token_item(req: RateLimitRequest, now_ms: int) -> Item:
    hits, limit, duration = _clamp_token(req)
    return Item(
        algorithm=Algorithm.TOKEN_BUCKET,
        limit=limit,
        duration=duration,
        eff_ms=_eff_duration_ms(duration, req.behavior),
        burst=limit,
        remaining=limit,
        t_ms=now_ms,
        expire_at=_token_expire(now_ms, now_ms, duration, req.behavior),
        status=Status.UNDER_LIMIT,
    )


def _new_leaky_item(req: RateLimitRequest, now_ms: int) -> Item:
    hits, limit, duration, burst, eff = _clamp_leaky(req)
    return Item(
        algorithm=Algorithm.LEAKY_BUCKET,
        limit=limit,
        duration=duration,
        eff_ms=eff,
        burst=burst,
        remaining=burst * eff,  # td units, starts full
        t_ms=now_ms,
        expire_at=now_ms + eff,
        status=Status.UNDER_LIMIT,
    )


def apply_token(item: Optional[Item], req: RateLimitRequest, now_ms: int
                ) -> Tuple[Item, RateLimitResponse]:
    hits, r_limit, r_duration = _clamp_token(req)
    behavior = int(req.behavior)

    if item is None or now_ms >= item.expire_at or item.algorithm != Algorithm.TOKEN_BUCKET:
        item = _new_token_item(req, now_ms)
    else:
        # Duration change → recompute expiry from created_at; if the new
        # duration means we are already expired, start fresh.
        if r_duration != item.duration:
            new_exp = _token_expire(now_ms, item.t_ms, r_duration, behavior)
            if new_exp <= now_ms:
                item = _new_token_item(req, now_ms)
            else:
                item.duration = r_duration
                item.expire_at = new_exp
        if behavior & Behavior.RESET_REMAINING:
            item.remaining = r_limit
            item.limit = r_limit
            item.status = Status.UNDER_LIMIT
        if r_limit != item.limit:
            item.remaining = min(max(item.remaining + (r_limit - item.limit), 0), r_limit)
            item.limit = r_limit

    resp = RateLimitResponse(limit=item.limit, reset_time=item.expire_at)
    if hits == 0:
        resp.status = Status(item.status)
        resp.remaining = item.remaining
        return item, resp

    if hits <= item.remaining:
        item.remaining -= hits
        item.status = Status.UNDER_LIMIT
    else:
        if behavior & Behavior.DRAIN_OVER_LIMIT:
            item.remaining = 0
        item.status = Status.OVER_LIMIT
    resp.status = Status(item.status)
    resp.remaining = item.remaining
    return item, resp


def apply_leaky(item: Optional[Item], req: RateLimitRequest, now_ms: int
                ) -> Tuple[Item, RateLimitResponse]:
    hits, r_limit, r_duration, r_burst, eff = _clamp_leaky(req)
    behavior = int(req.behavior)

    if item is None or now_ms >= item.expire_at or item.algorithm != Algorithm.LEAKY_BUCKET:
        item = _new_leaky_item(req, now_ms)
    else:
        if eff != item.eff_ms:
            # Duration (or its Gregorian interpretation) changed → rescale
            # td to the new denominator, using the denominator the item was
            # actually stored with.  Whole tokens clamp to the new bound
            # (they could not survive the burst cap anyway); the sub-token
            # fraction is kept only while frac × eff fits int64.
            whole, frac = divmod(item.remaining, item.eff_ms)
            whole = min(whole, TD_BOUND // eff)
            if item.eff_ms <= FRAC_SAFE and eff <= FRAC_SAFE:
                item.remaining = whole * eff + (frac * eff) // item.eff_ms
            else:
                item.remaining = whole * eff
            item.eff_ms = eff
        item.duration = r_duration
        if behavior & Behavior.RESET_REMAINING:
            item.remaining = r_limit * eff
            item.status = Status.UNDER_LIMIT
        item.limit = r_limit
        item.burst = r_burst
        # Replenish exactly: elapsed ms × limit td, clamped to burst.
        # When elapsed × limit would overflow int64 the true product
        # already exceeds the burst cap (cap ≤ TD_BOUND), so the bucket
        # is simply full — exact, not an approximation.
        elapsed = now_ms - item.t_ms
        cap = item.burst * eff
        if elapsed > TD_BOUND // max(item.limit, 1):
            item.remaining = cap
        else:
            item.remaining = min(item.remaining + elapsed * item.limit, cap)
        item.t_ms = now_ms

    rate = eff // item.limit if item.limit > 0 else eff
    item.expire_at = now_ms + eff
    resp = RateLimitResponse(limit=item.limit, reset_time=now_ms + rate)
    if hits == 0:
        resp.status = Status(item.status)
        resp.remaining = item.remaining // eff
        return item, resp

    hits_td = hits * eff
    if hits_td <= item.remaining:
        item.remaining -= hits_td
        item.status = Status.UNDER_LIMIT
    else:
        if behavior & Behavior.DRAIN_OVER_LIMIT:
            item.remaining = 0
        item.status = Status.OVER_LIMIT
    resp.status = Status(item.status)
    resp.remaining = item.remaining // eff
    return item, resp


class Oracle:
    """Sequential reference implementation over an unbounded key→Item map.

    The device path must produce identical responses for any request
    stream (same ``now_ms`` fed to both).  This is the `cluster/`-style
    referee used by the parity harness (SURVEY.md §4).
    """

    def __init__(self) -> None:
        self.items: Dict[str, Item] = {}

    def check(self, req: RateLimitRequest, now_ms: int) -> RateLimitResponse:
        key = req.key
        item = self.items.get(key)
        if int(req.algorithm) == Algorithm.LEAKY_BUCKET:
            item, resp = apply_leaky(item, req, now_ms)
        else:
            item, resp = apply_token(item, req, now_ms)
        self.items[key] = item
        return resp

    def check_batch(self, reqs: List[RateLimitRequest], now_ms: int
                    ) -> List[RateLimitResponse]:
        return [self.check(r, now_ms) for r in reqs]


class OracleEngine:
    """The Oracle behind the V1Instance engine interface (hot-path
    subset): lets the service layer — dispatcher coalescing, daemon
    listeners, wave telemetry — run and be tested on pure Python, with
    no jax/sharded stack at all.  Columnar and row-level ops are
    deliberately absent: anything that needs them should use a real
    engine.  Not thread-safe by itself; the dispatcher's engine lock
    serializes access exactly as it does for device engines."""

    def __init__(self, capacity: int = 1 << 16):
        self.oracle = Oracle()
        self.cap_local = capacity
        self.n = 1
        self.dropped_rows = 0

    def check_batch(self, reqs: List[RateLimitRequest], now_ms: int
                    ) -> List[RateLimitResponse]:
        return self.oracle.check_batch(list(reqs), now_ms)

    def occupancy(self) -> int:
        return len(self.oracle.items)

    def sweep(self, now_ms: int) -> None:
        self.oracle.items = {k: it for k, it in self.oracle.items.items()
                             if it.expire_at >= now_ms}

    def snapshot(self) -> dict:
        return {}

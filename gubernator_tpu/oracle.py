"""M0 — the semantic oracle (parity referee).

Pure-Python, exact-integer implementation of the rate-limit behavior
contract (SURVEY.md §2.4; reference algorithms.go › tokenBucket /
tokenBucketNewItem / leakyBucket / leakyBucketNewItem — reconstructed,
mount was empty).  Every device kernel is tested bit-for-bit against this
module; where the reference's float64 leaky-bucket arithmetic could not be
reproduced exactly, the contract below REDEFINES it in exact integer
"token-duration" fixed point (see `Leaky fixed point` below) and the
deviation is documented.

Contract summary
----------------

All time is int64 epoch milliseconds.  Each key's state ("item"):

- ``algorithm``, ``limit``, ``duration`` (ms or Gregorian ordinal),
  ``burst`` (leaky; 0 → limit), ``t_ms`` (token: created_at; leaky:
  updated_at), ``expire_at`` (token: reset boundary; leaky: sliding cache
  TTL), ``remaining`` and ``status`` (stored; returned for hits=0
  queries, mirroring the reference's ``rl.Status = t.Status`` early
  return).

Token bucket (reference algorithms.go › tokenBucket):

1. Missing or ``now >= expire_at`` → fresh item: remaining=limit,
   created=now, expire = now+duration (or Gregorian period end).
2. Duration change recomputes expire from created_at; if that expires the
   item now, it is re-created fresh.
3. ``RESET_REMAINING`` forces remaining=limit (and adopts the new limit).
4. Limit change adjusts in place: remaining = clamp(remaining +
   new-old, 0, new)  (equivalently new_limit - used, clamped; matches
   TestChangeLimit semantics).
5. hits=0 → pure query: returns stored status, no mutation.
   hits ≤ remaining → UNDER_LIMIT, remaining -= hits.
   hits > remaining → OVER_LIMIT, NO decrement (DRAIN_OVER_LIMIT zeroes
   remaining instead).
6. reset_time = expire_at.

Leaky fixed point (deviation from the reference, by design):

The reference stores leaky ``Remaining`` as float64 and leaks
``elapsed / (duration/limit)`` tokens.  Floating point cannot be
reproduced bit-for-bit across TPU (no f64) and host, so this contract
stores ``remaining_td = remaining × duration_eff`` ("token-duration"
units, int64) and replenishes exactly: ``remaining_td += elapsed × limit``
(clamped to ``burst × duration_eff``).  A request costs
``hits × duration_eff`` td.  Observable integer behavior (allow/deny,
``remaining`` floor, reset_time) matches the reference's within one
sub-millisecond-token rounding; allow/deny parity on integer-rate
workloads is exact.  Domain: ``limit × duration_eff < 2^63``.

- Gregorian ordinals use the calendar for token expiry; the leak rate for
  leaky uses the fixed-width approximation (GREGORIAN_APPROX_MS).
- duration change rescales td to the new denominator (whole tokens exact,
  fractional part floor-rounded).
- limit change does NOT adjust leaky remaining (the refill rate simply
  changes); burst is re-adopted from each request.
- reset_time = now + duration_eff // limit (ms until one token leaks);
  expire_at = now + duration_eff (sliding TTL).

Input clamps (applied to every request): hits < 0 → 0, limit < 0 → 0,
non-Gregorian duration < 1 → 1, burst ≤ 0 → limit.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .gregorian import gregorian_expiration, gregorian_rate_duration_ms
from .types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    RateLimitResponse,
    Status,
)


@dataclass
class Item:
    """Oracle-side mirror of one device table row."""

    __slots__ = (
        "algorithm",
        "limit",
        "duration",
        "eff_ms",
        "burst",
        "remaining",
        "t_ms",
        "expire_at",
        "status",
    )
    algorithm: int
    limit: int
    duration: int  # as given by the request (ms or Gregorian ordinal)
    eff_ms: int  # effective ms denominator the item was created/rescaled with
    burst: int
    remaining: int  # token: tokens; leaky: token-duration (td) units
    t_ms: int
    expire_at: int
    status: int


def _eff_duration_ms(duration: int, behavior: int) -> int:
    """Effective millisecond duration used for leak rate / td denominator."""
    if behavior & Behavior.DURATION_IS_GREGORIAN:
        return gregorian_rate_duration_ms(duration)
    return max(int(duration), 1)


def _token_expire(now_ms: int, created_ms: int, duration: int, behavior: int) -> int:
    if behavior & Behavior.DURATION_IS_GREGORIAN:
        return gregorian_expiration(now_ms, duration)
    return created_ms + max(int(duration), 1)


#: Input ceiling for hits/limit/burst and ms durations: keeps every td
#: fixed-point product (value × duration_eff) inside int64 —
#: 2^31 × 2^31 < 2^63.  Clamped identically by the device batch packer
#: (core/batch.py) so parity holds on adversarial inputs.  The duration
#: ceiling is ~24.8 days; calendar-scale windows are what
#: DURATION_IS_GREGORIAN exists for.
MAX_INPUT = (1 << 31) - 1


def _clamp_req(req: RateLimitRequest) -> Tuple[int, int, int, int]:
    hits = min(max(int(req.hits), 0), MAX_INPUT)
    limit = min(max(int(req.limit), 0), MAX_INPUT)
    duration = min(int(req.duration), MAX_INPUT)
    burst = int(req.burst) if int(req.burst) > 0 else limit
    burst = min(burst, MAX_INPUT)
    return hits, limit, duration, burst


def _new_token_item(req: RateLimitRequest, now_ms: int) -> Item:
    hits, limit, duration, _ = _clamp_req(req)
    return Item(
        algorithm=Algorithm.TOKEN_BUCKET,
        limit=limit,
        duration=duration,
        eff_ms=_eff_duration_ms(duration, req.behavior),
        burst=limit,
        remaining=limit,
        t_ms=now_ms,
        expire_at=_token_expire(now_ms, now_ms, duration, req.behavior),
        status=Status.UNDER_LIMIT,
    )


def _new_leaky_item(req: RateLimitRequest, now_ms: int) -> Item:
    hits, limit, duration, burst = _clamp_req(req)
    eff = _eff_duration_ms(duration, req.behavior)
    return Item(
        algorithm=Algorithm.LEAKY_BUCKET,
        limit=limit,
        duration=duration,
        eff_ms=eff,
        burst=burst,
        remaining=burst * eff,  # td units, starts full
        t_ms=now_ms,
        expire_at=now_ms + eff,
        status=Status.UNDER_LIMIT,
    )


def apply_token(item: Optional[Item], req: RateLimitRequest, now_ms: int
                ) -> Tuple[Item, RateLimitResponse]:
    hits, r_limit, r_duration, _ = _clamp_req(req)
    behavior = int(req.behavior)

    if item is None or now_ms >= item.expire_at or item.algorithm != Algorithm.TOKEN_BUCKET:
        item = _new_token_item(req, now_ms)
    else:
        # Duration change → recompute expiry from created_at; if the new
        # duration means we are already expired, start fresh.
        if r_duration != item.duration:
            new_exp = _token_expire(now_ms, item.t_ms, r_duration, behavior)
            if new_exp <= now_ms:
                item = _new_token_item(req, now_ms)
            else:
                item.duration = r_duration
                item.expire_at = new_exp
        if behavior & Behavior.RESET_REMAINING:
            item.remaining = r_limit
            item.limit = r_limit
            item.status = Status.UNDER_LIMIT
        if r_limit != item.limit:
            item.remaining = min(max(item.remaining + (r_limit - item.limit), 0), r_limit)
            item.limit = r_limit

    resp = RateLimitResponse(limit=item.limit, reset_time=item.expire_at)
    if hits == 0:
        resp.status = Status(item.status)
        resp.remaining = item.remaining
        return item, resp

    if hits <= item.remaining:
        item.remaining -= hits
        item.status = Status.UNDER_LIMIT
    else:
        if behavior & Behavior.DRAIN_OVER_LIMIT:
            item.remaining = 0
        item.status = Status.OVER_LIMIT
    resp.status = Status(item.status)
    resp.remaining = item.remaining
    return item, resp


def apply_leaky(item: Optional[Item], req: RateLimitRequest, now_ms: int
                ) -> Tuple[Item, RateLimitResponse]:
    hits, r_limit, r_duration, r_burst = _clamp_req(req)
    behavior = int(req.behavior)
    eff = _eff_duration_ms(r_duration, behavior)

    if item is None or now_ms >= item.expire_at or item.algorithm != Algorithm.LEAKY_BUCKET:
        item = _new_leaky_item(req, now_ms)
    else:
        if eff != item.eff_ms:
            # Duration (or its Gregorian interpretation) changed → rescale
            # td to the new denominator, using the denominator the item was
            # actually stored with.
            whole, frac = divmod(item.remaining, item.eff_ms)
            item.remaining = whole * eff + (frac * eff) // item.eff_ms
            item.eff_ms = eff
        item.duration = r_duration
        if behavior & Behavior.RESET_REMAINING:
            item.remaining = r_limit * eff
            item.status = Status.UNDER_LIMIT
        item.limit = r_limit
        item.burst = r_burst
        # Replenish exactly: elapsed ms × limit td, clamped to burst.
        elapsed = now_ms - item.t_ms
        cap = item.burst * eff
        item.remaining = min(item.remaining + elapsed * item.limit, cap)
        item.t_ms = now_ms

    rate = eff // item.limit if item.limit > 0 else eff
    item.expire_at = now_ms + eff
    resp = RateLimitResponse(limit=item.limit, reset_time=now_ms + rate)
    if hits == 0:
        resp.status = Status(item.status)
        resp.remaining = item.remaining // eff
        return item, resp

    hits_td = hits * eff
    if hits_td <= item.remaining:
        item.remaining -= hits_td
        item.status = Status.UNDER_LIMIT
    else:
        if behavior & Behavior.DRAIN_OVER_LIMIT:
            item.remaining = 0
        item.status = Status.OVER_LIMIT
    resp.status = Status(item.status)
    resp.remaining = item.remaining // eff
    return item, resp


class Oracle:
    """Sequential reference implementation over an unbounded key→Item map.

    The device path must produce identical responses for any request
    stream (same ``now_ms`` fed to both).  This is the `cluster/`-style
    referee used by the parity harness (SURVEY.md §4).
    """

    def __init__(self) -> None:
        self.items: Dict[str, Item] = {}

    def check(self, req: RateLimitRequest, now_ms: int) -> RateLimitResponse:
        key = req.key
        item = self.items.get(key)
        if int(req.algorithm) == Algorithm.LEAKY_BUCKET:
            item, resp = apply_leaky(item, req, now_ms)
        else:
            item, resp = apply_token(item, req, now_ms)
        self.items[key] = item
        return resp

    def check_batch(self, reqs: List[RateLimitRequest], now_ms: int
                    ) -> List[RateLimitResponse]:
        return [self.check(r, now_ms) for r in reqs]

"""Key hashing.

The rate-limit identity is the string ``name + "_" + unique_key``
(reference: gubernator.go › GetRateLimits).  We hash it once on the host
to a 64-bit value that serves both purposes the reference splits between
`hash.go` (peer picking) and the LRU map (row lookup):

- upper bits pick the shard (chip) — the consistent-hash-range analog,
- the full hash probes the device-resident open-addressing table.

FNV-1a 64 is used like the reference's default fnv1 hash (hash.go ›
ConsistantHash — reconstructed); any 64-bit hash works since both sides
only need determinism + uniformity.  Hash value 0 is remapped to 1: row 0
of the device table is reserved and key 0 is the empty-slot sentinel.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

# Optional C fast path (gubernator_tpu/ops/native); resolved once at import.
try:
    from gubernator_tpu.ops import native as _native  # type: ignore
except ImportError:
    _native = None


def fnv1a64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def mix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64-style avalanche finalizer (uint64 → uint64).

    FNV-1a's high bits cluster for short similar keys, which would skew
    hash-range shard ownership and probe strides; this spreads entropy
    across all 64 bits.  Single source of truth for the finalizer — the
    scalar path and bench stream generation both use it.  The optional C
    extension returns RAW FNV-1a (no mix, no zero-remap); the finalizer
    is always applied here.
    """
    x = x.astype(np.uint64)  # astype copies; in-place ops below are safe
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def mix64(x: int) -> int:
    """Scalar splitmix64 finalizer (same constants as mix64_np)."""
    M = 0xFFFFFFFFFFFFFFFF
    x &= M
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & M
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & M
    x ^= x >> 31
    return x


def mixed_fnv1a64(data: bytes) -> int:
    """FNV-1a + avalanche — uniform even on short similar keys (used by
    the peer-picker ring, where raw FNV clusters badly)."""
    return mix64(fnv1a64(data))


def hash_key(name: str, unique_key: str) -> int:
    """64-bit identity hash of a rate limit, never 0."""
    h = int(mix64_np(np.array([fnv1a64((name + "_" + unique_key).encode("utf-8"))],
                              dtype=np.uint64))[0])
    return h if h != 0 else 1


def hash_keys(keys: Sequence[str]) -> np.ndarray:
    """Batch hash → uint64[len(keys)], never 0."""
    if _native is not None:
        raw = _native.hash_keys(keys)  # raw FNV-1a, finalizer applied below
    else:
        raw = np.empty(len(keys), dtype=np.uint64)
        for i, k in enumerate(keys):
            raw[i] = fnv1a64(k.encode("utf-8"))
    x = mix64_np(raw)
    return np.where(x == 0, np.uint64(1), x)


def hash_request_keys(names: Sequence[str], unique_keys: Sequence[str]
                      ) -> np.ndarray:
    """Batch identity hash of (name, unique_key) pairs, never 0.

    With the native extension this skips building the joined
    ``name + "_" + key`` strings entirely (the ingest hot path)."""
    if _native is not None:
        raw = _native.hash_pairs(names, unique_keys)
        x = mix64_np(raw)
        return np.where(x == 0, np.uint64(1), x)
    return hash_keys([n + "_" + k for n, k in zip(names, unique_keys)])


def shard_of(key_hash: np.ndarray | int, num_shards: int) -> np.ndarray | int:
    """Shard index by hash range (top 32 bits), the consistent-hash-range
    analog of hash.go › ConsistantHash.Get.  Stable under fixed
    num_shards; re-sharding on membership change re-maps ranges
    (SURVEY.md §2.3).  Single formula for scalar and array paths:
    ``((h >> 32) * n) >> 32``."""
    if isinstance(key_hash, (int, np.integer)):
        return int(((int(key_hash) >> 32) * num_shards) >> 32)
    kh = key_hash.astype(np.uint64)
    return ((kh >> np.uint64(32)) * np.uint64(num_shards) >> np.uint64(32)).astype(np.int32)

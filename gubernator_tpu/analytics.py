"""Key-level analytics: heavy-hitter ledger + per-phase latency ledger.

ISSUE 4: after the wave telemetry of ISSUE 1 the serving loop's
*aggregate* health is visible, but not WHICH keys are hot, which drive
OVER_LIMIT, or where a request's milliseconds go between ingest, queue,
device and peer forward.  Hot-key skew is the dominant failure mode of
distributed limiters (PAPERS.md), and the hot-set promoter
(parallel/hotset.py) needs exactly this hotness signal.

Two pieces, both bounded-memory and OFF the caller's critical path:

- ``HeavyHitterSketch``: a columnar Space-Saving ledger of ``width``
  counters (GUBER_SKETCH_WIDTH, default 4×K) reporting the top ``K``
  keys (GUBER_TOPK, default 256).  Exact when the key domain fits in
  ``width``; otherwise every reported count over-estimates by at most
  its per-key ``err`` field, itself bounded by ``total_weight/width``
  (the classic Space-Saving guarantee).  Per key it tracks hits,
  OVER_LIMIT count, last-seen wall time, and the key NAME when a wave
  carried one (object-lane taps; pure-columnar wire waves only know
  the 64-bit khash).

- ``PhaseLedger``: per-phase duration attribution (ingest, pack,
  queue_wait, device, resolve, build, peer_flush) feeding both the
  ``gubernator_phase_duration{phase=...}`` histograms and the
  ``GET /debug/phases`` percentile snapshot.  The in-wave phases
  (pack, device, resolve) partition the existing
  ``gubernator_dispatcher_wave_duration`` exactly (asserted by
  tests/test_telemetry.py).

``KeyAnalytics`` owns both plus the tap queue: the dispatcher enqueues
cheap column COPIES after each wave resolves, and a single worker
thread does all unique/aggregate/sketch work, draining the queue in
paced batches (one vectorized fold per ``BATCH_INTERVAL_S`` window) —
a full queue drops the wave (counted) rather than ever blocking a
caller.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

#: phase label set (OBSERVABILITY.md › phase catalog).  ``IN_WAVE``
#: phases partition wave_duration; the rest attribute time outside the
#: wave (job queue wait, wire ingest, response build, peer flush).
IN_WAVE_PHASES = ("pack", "device", "resolve")
PHASES = ("ingest", "pack", "queue_wait", "device", "resolve", "build",
          "peer_flush", "broadcast", "snapshot", "restore",
          "global_fold")


def _env_int(name: str, default: int, lo: int = 1) -> int:
    raw = os.environ.get(name, "")
    if raw:
        try:
            return max(int(raw), lo)
        except ValueError:
            pass  # malformed: keep the default
    return default


class HeavyHitterSketch:
    """Space-Saving heavy hitters over 64-bit key hashes, columnar.

    ``width`` counters total; ``topk()`` reports the heaviest ``k``.
    Storage is parallel numpy columns (count/err/over/last/khash) with
    a sorted-hash index rebuilt lazily per wave, so a whole wave folds
    in with vectorized ops — no per-key Python loop on the columnar
    path (the dict-of-slots + min-scan variant cost ~40 ms per
    1000-req Zipf wave; this is ~0.2 ms, which matters on small hosts
    where the worker thread competes with serving for cores).

    Admission when full follows EXACT sequential Space-Saving
    semantics (each newcomer evicts the then-minimum slot and inherits
    its count as the overestimate bound ``err``), simulated for a
    whole wave with a sorted-victims/FIFO merge instead of a heap —
    see the comment at the admission step.  The classic guarantees
    hold, all deterministic:

    - exact (every ``err`` == 0) while the observed key domain fits in
      ``width``;
    - per tracked key: ``true <= count`` and ``count - true <= err``;
    - tracked counts sum to ``total_weight`` exactly, hence
      ``err <= error_bound()`` (the current minimum)
      ``<= total_weight/width`` by pigeonhole — and any key whose true
      count exceeds ``total_weight/width`` is guaranteed tracked.

    NOT thread-safe: KeyAnalytics serializes access on its worker
    thread (snapshot readers take its lock).
    """

    def __init__(self, k: int = 256, width: Optional[int] = None):
        self.k = max(int(k), 1)
        self.width = max(int(width) if width else 4 * self.k, self.k)
        w = self.width
        self._cnt = np.zeros(w, np.int64)
        self._err = np.zeros(w, np.int64)
        self._over = np.zeros(w, np.int64)
        self._last = np.zeros(w, np.int64)
        self._kh = np.zeros(w, np.uint64)
        self._used = 0
        self._sorted_kh = np.empty(0, np.uint64)
        self._sorted_slot = np.empty(0, np.int64)
        self._dirty = False  # membership changed since last reindex
        self.total_weight = 0
        #: bounded khash → "name_unique_key" side table: names seen on
        #: object-lane waves resolve keys that later go hot through the
        #: columnar wire lanes (which only carry hashes)
        self._names: Dict[int, str] = {}
        self._names_cap = max(8 * self.width, 4096)

    def __len__(self) -> int:
        return self._used

    # ---- ingest ---------------------------------------------------------

    def _reindex(self) -> None:
        if self._dirty or self._sorted_kh.size != self._used:
            order = np.argsort(self._kh[:self._used])
            self._sorted_kh = self._kh[:self._used][order]
            self._sorted_slot = order.astype(np.int64)
            self._dirty = False

    def update(self, khash: np.ndarray, hits: np.ndarray,
               over: np.ndarray, t_ms: int,
               names: Optional[List[Optional[str]]] = None) -> None:
        """Fold one wave's columns in.  ``khash`` uint64, ``hits``
        weights (clamped >= 1 so hits=0 status queries still register
        presence), ``over`` truthy where the decision was OVER_LIMIT.
        ``names``, when given, aligns with ``khash``."""
        n = len(khash)
        if n == 0:
            return
        w = np.maximum(np.asarray(hits, np.int64), 1)
        kh = np.asarray(khash, np.uint64)
        ob = np.asarray(over, bool)
        # sort-and-reduceat aggregation (np.unique + ufunc.at is ~2×
        # slower; this update is the analytics worker's hot loop).
        # Weight-1 waves — the common columnar shape — skip the
        # argsort permutation entirely: counts are plain run lengths
        # of the sorted hashes, and the (sparse) over-limit rows
        # aggregate separately and scatter in by binary search.
        if names is None and int(w.max()) == 1:
            ks = np.sort(kh)
            starts = np.nonzero(np.concatenate(
                ([True], ks[1:] != ks[:-1])))[0]
            uniq = ks[starts]
            wsum = np.diff(np.append(starts, ks.size))
            osum = np.zeros(uniq.size, np.int64)
            if ob.any():
                kho = np.sort(kh[ob])
                so = np.nonzero(np.concatenate(
                    ([True], kho[1:] != kho[:-1])))[0]
                osum[np.searchsorted(uniq, kho[so])] = \
                    np.diff(np.append(so, kho.size))
        else:
            o = ob.astype(np.int64)
            sort = np.argsort(kh, kind="stable")
            ks = kh[sort]
            starts = np.nonzero(np.concatenate(
                ([True], ks[1:] != ks[:-1])))[0]
            uniq = ks[starts]
            wsum = np.add.reduceat(w[sort], starts)
            osum = np.add.reduceat(o[sort], starts)
            if names is not None:
                # object-lane waves only (small): remember each unique
                # key's name so columnar taps resolve it at report time
                rep = sort[starts]  # any occurrence names the key
                for j in range(uniq.size):
                    name = names[int(rep[j])]
                    if name is not None:
                        self._note_name(int(uniq[j]), name)
        self.total_weight += int(wsum.sum())
        # tracked keys: one sorted-membership probe, vectorized folds
        self._reindex()
        if self._sorted_kh.size:
            pos = np.minimum(np.searchsorted(self._sorted_kh, uniq),
                             self._sorted_kh.size - 1)
            tracked = self._sorted_kh[pos] == uniq
            slots = self._sorted_slot[pos[tracked]]
            self._cnt[slots] += wsum[tracked]
            self._over[slots] += osum[tracked]
            self._last[slots] = t_ms
        else:
            tracked = np.zeros(uniq.size, bool)
        m = int(uniq.size - tracked.sum())
        if m == 0:
            return
        new_kh = uniq[~tracked]
        new_w = wsum[~tracked]
        new_o = osum[~tracked]
        free = self.width - self._used
        if free > 0:
            take = min(free, m)
            sl = np.arange(self._used, self._used + take)
            self._kh[sl] = new_kh[:take]
            self._cnt[sl] = new_w[:take]
            self._err[sl] = 0
            self._over[sl] = new_o[:take]
            self._last[sl] = t_ms
            self._used += take
            self._dirty = True
            if take == m:
                return
            new_kh, new_w, new_o = (new_kh[take:], new_w[take:],
                                    new_o[take:])
            m -= take
        # EXACT sequential Space-Saving admission (each newcomer
        # evicts the then-minimum slot and inherits its count as the
        # error bound).  Arrival order within a wave is ours to
        # choose, so split by weight: the few heavy newcomers run the
        # exact two-way merge; the weight-1 tail — the dominant churn
        # shape — admits via closed-form water-filling with no
        # per-item loop at all.  Either way the counts sum to the
        # total observed weight, hence err <= min <= total/width.
        heavy = new_w > 1
        if heavy.any():
            self._admit_merge(new_kh[heavy], new_w[heavy],
                              new_o[heavy], t_ms)
        light = ~heavy
        if light.any():
            self._admit_level(new_kh[light], new_o[light], t_ms)

    def _admit_merge(self, new_kh, new_w, new_o, t_ms: int) -> None:
        """Sequential Space-Saving for arbitrary weights, simulated as
        a two-way merge: processing newcomers in ascending-weight
        order makes both the popped minima v_1 <= v_2 <= ... and the
        re-inserted values v_j + w_j nondecreasing, so the "heap" is
        just the sorted victim counts + a FIFO of intra-wave
        re-insertions.  A slot popped from the FIFO re-evicts an
        earlier newcomer of this same wave (its assignment is simply
        overwritten).  Evicted keys' over-limit tallies do NOT carry
        over, so `over` stays exact per tracked period."""
        order = np.argsort(new_w, kind="stable")
        new_kh, new_w, new_o = new_kh[order], new_w[order], new_o[order]
        sort_idx = np.argsort(self._cnt[: self._used])
        scnt = self._cnt[: self._used][sort_idx].tolist()
        sslot = sort_idx.tolist()
        ns = len(scnt)
        si = qi = 0
        qv: list = []  # FIFO as append-only lists + head index (qi):
        qs: list = []  # stays sorted, so no heap is ever needed
        assign: Dict[int, int] = {}  # slot → newcomer idx (last wins)
        inherited: Dict[int, int] = {}  # slot → evicted count
        for j, wj in enumerate(new_w.tolist()):
            if qi < len(qv) and (si >= ns or qv[qi] <= scnt[si]):
                v, slot = qv[qi], qs[qi]
                qi += 1
            else:
                v, slot = scnt[si], sslot[si]
                si += 1
            assign[slot] = j
            inherited[slot] = v
            qv.append(v + wj)
            qs.append(slot)
        slots = np.fromiter(assign.keys(), np.int64, len(assign))
        js = np.fromiter(assign.values(), np.int64, len(assign))
        vs = np.fromiter(inherited.values(), np.int64, len(inherited))
        self._kh[slots] = new_kh[js]
        self._cnt[slots] = vs + new_w[js]
        self._err[slots] = vs
        self._over[slots] = new_o[js]
        self._last[slots] = t_ms
        self._dirty = True

    def _admit_level(self, new_kh, new_o, t_ms: int) -> None:
        """Weight-1 newcomers via exact water-filling: s pops of
        "evict the minimum, reinsert min+1" ARE s increments of the
        global minimum, so the final counts are the level-fill of the
        sorted counts — raise the lowest t0 counts to a common level L
        (the first r of them to L+1) — computed in closed form.
        Raised slots take newcomer keys with err = count - 1; the
        s - raised singletons admitted-then-re-evicted inside the wave
        vanish, exactly as sequential processing would have them."""
        s = len(new_kh)
        used = self._used
        cnt = self._cnt[:used]
        order = np.argsort(cnt)
        c = cnt[order]
        csum = np.cumsum(c)
        # cost[i] = lifting slots 0..i to level c[i]; nondecreasing
        cost = (np.arange(1, used + 1) * c) - csum
        t0 = int(np.searchsorted(cost, s, side="right"))
        pool = s + int(csum[t0 - 1])
        level = pool // t0
        r = pool - level * t0
        newvals = np.full(t0, level, np.int64)
        newvals[:r] += 1
        changed = newvals > c[:t0]
        nraised = int(changed.sum())
        slots = order[:t0][changed]
        self._cnt[slots] = newvals[changed]
        self._err[slots] = newvals[changed] - 1
        self._kh[slots] = new_kh[:nraised]
        self._over[slots] = new_o[:nraised]
        self._last[slots] = t_ms
        self._dirty = True

    def _note_name(self, kh: int, name: str) -> None:
        names = self._names
        if kh not in names and len(names) >= self._names_cap:
            # bounded: drop an arbitrary half when full (plain dicts
            # pop in insertion order, so this sheds the oldest names)
            for old in list(names)[: self._names_cap // 2]:
                del names[old]
        names[kh] = name

    # ---- reporting ------------------------------------------------------

    def error_bound(self) -> int:
        """Worst-case overestimate for a newly admitted key: the
        current minimum tracked count (<= total_weight/width).  0
        while the ledger has free slots (everything exact)."""
        if self._used < self.width:
            return 0
        return int(self._cnt[: self._used].min())

    def count_of(self, khash: int) -> int:
        """Tracked count for one key hash (0 when untracked) — the
        hot-set promoter's feed (ROADMAP: promotion driven by the
        sketch's signal instead of ad-hoc counting).  An overestimate
        by at most the key's ``err``, which only makes promotion
        eager, never starved."""
        self._reindex()
        if not self._sorted_kh.size:
            return 0
        kh = np.uint64(khash)
        pos = int(np.searchsorted(self._sorted_kh, kh))
        if pos >= self._sorted_kh.size or self._sorted_kh[pos] != kh:
            return 0
        return int(self._cnt[self._sorted_slot[pos]])

    def topk(self, k: Optional[int] = None) -> List[dict]:
        k = self.k if k is None else max(int(k), 1)
        k = min(k, self._used)
        cnt = self._cnt[: self._used]
        if k < self._used:
            part = np.argpartition(cnt, self._used - k)[self._used - k:]
            order = part[np.argsort(cnt[part])[::-1]]
        else:
            order = np.argsort(cnt)[::-1]
        out = []
        for s in order[:k]:
            kh = int(self._kh[s])
            out.append({"khash": kh, "key": self._names.get(kh),
                        "hits": int(self._cnt[s]),
                        "err": int(self._err[s]),
                        "over_limit": int(self._over[s]),
                        "last_seen_ms": int(self._last[s])})
        return out

    # ---- fleet merge surface (ISSUE 19) ---------------------------------

    def merge_entries(self, entries: List[dict],
                      total_weight: Optional[int] = None) -> None:
        """Fold another sketch's REPORTED rows (``topk()`` dicts, khash
        as int or ``0x…`` hex) into this one — the fleet watchtower's
        merge surface.  Reuses the exact two-way Space-Saving merge:
        tracked keys add counts AND error bounds; untracked keys fill
        free slots (keeping their remote ``err``) or run
        ``_admit_merge``, after which the remote ``err`` of each
        SURVIVING newcomer is added on top of the inherited eviction
        bound.  The merged sketch obeys the summed-stream guarantee:
        ``true <= count`` and ``count - true <= err`` against the union
        stream.  When both sides saw disjoint key sets that fit in
        ``width`` the merge is exact (all ``err`` unchanged), which is
        what the fleet byte-equality test pins."""
        rows = []
        for e in entries:
            kh = e.get("khash")
            if isinstance(kh, str):
                kh = int(kh, 16)
            hits = int(e.get("hits", 0))
            if hits <= 0:
                continue
            rows.append((int(kh), hits, int(e.get("err", 0)),
                         int(e.get("over_limit", 0)),
                         int(e.get("last_seen_ms", 0)),
                         e.get("key")))
        if total_weight is not None:
            self.total_weight += int(total_weight)
        elif rows:
            self.total_weight += sum(r[1] for r in rows)
        if not rows:
            return
        kh = np.array([r[0] for r in rows], np.uint64)
        w = np.array([r[1] for r in rows], np.int64)
        er = np.array([r[2] for r in rows], np.int64)
        ov = np.array([r[3] for r in rows], np.int64)
        ls = np.array([r[4] for r in rows], np.int64)
        for r in rows:
            if r[5] is not None:
                self._note_name(r[0], r[5])
        # aggregate duplicate khashes (defensive: topk() never repeats
        # a hash, but merged docs from a retrying fetcher might)
        sort = np.argsort(kh, kind="stable")
        ks = kh[sort]
        starts = np.nonzero(np.concatenate(
            ([True], ks[1:] != ks[:-1])))[0]
        uniq = ks[starts]
        wsum = np.add.reduceat(w[sort], starts)
        ersum = np.add.reduceat(er[sort], starts)
        ovsum = np.add.reduceat(ov[sort], starts)
        lsmax = np.maximum.reduceat(ls[sort], starts)
        # tracked probe: counts add, error bounds add (both remotes'
        # overestimates can stack on the same key)
        self._reindex()
        if self._sorted_kh.size:
            pos = np.minimum(np.searchsorted(self._sorted_kh, uniq),
                             self._sorted_kh.size - 1)
            tracked = self._sorted_kh[pos] == uniq
            slots = self._sorted_slot[pos[tracked]]
            self._cnt[slots] += wsum[tracked]
            self._err[slots] += ersum[tracked]
            self._over[slots] += ovsum[tracked]
            np.maximum.at(self._last, slots, lsmax[tracked])
        else:
            tracked = np.zeros(uniq.size, bool)
        if int(tracked.sum()) == uniq.size:
            return
        new_kh = uniq[~tracked]
        new_w = wsum[~tracked]
        new_er = ersum[~tracked]
        new_o = ovsum[~tracked]
        new_ls = lsmax[~tracked]
        free = self.width - self._used
        if free > 0:
            take = min(free, len(new_kh))
            sl = np.arange(self._used, self._used + take)
            self._kh[sl] = new_kh[:take]
            self._cnt[sl] = new_w[:take]
            self._err[sl] = new_er[:take]  # keep the remote bound
            self._over[sl] = new_o[:take]
            self._last[sl] = new_ls[:take]
            self._used += take
            self._dirty = True
            if take == len(new_kh):
                return
            new_kh, new_w, new_er, new_o, new_ls = (
                new_kh[take:], new_w[take:], new_er[take:],
                new_o[take:], new_ls[take:])
        t_ms = int(new_ls.max())
        self._admit_merge(new_kh, new_w, new_o, t_ms)
        # surviving newcomers inherited an eviction bound from
        # _admit_merge; their remote err stacks on top (the remote
        # count they brought was itself an overestimate)
        self._reindex()
        pos = np.minimum(np.searchsorted(self._sorted_kh, new_kh),
                         self._sorted_kh.size - 1)
        alive = self._sorted_kh[pos] == new_kh
        slots = self._sorted_slot[pos[alive]]
        self._err[slots] += new_er[alive]
        np.maximum.at(self._last, slots, new_ls[alive])

    def canonical_bytes(self) -> bytes:
        """Deterministic byte form of the tracked state — khash-sorted
        ``(khash, cnt, err, over)`` rows as JSON.  ``last_seen_ms`` is
        a wall-clock artifact, not sketch state, so it is excluded;
        two sketches that tracked the same multiset of decisions
        byte-equal regardless of when they saw them (the fleet
        merge-exactness pin in tests/test_fleet.py)."""
        u = self._used
        rows = sorted(zip(self._kh[:u].tolist(),
                          self._cnt[:u].tolist(),
                          self._err[:u].tolist(),
                          self._over[:u].tolist()))
        return json.dumps({"width": self.width, "k": self.k,
                           "total_weight": self.total_weight,
                           "rows": rows},
                          separators=(",", ":")).encode()


class PhaseLedger:
    """Thread-safe per-phase duration aggregation: cumulative count/sum
    plus a bounded recent-sample window for percentile snapshots
    (prometheus histograms can't answer percentile queries)."""

    def __init__(self, maxlen: int = 4096):
        self._mu = threading.Lock()
        self._agg: Dict[str, list] = {}  # phase → [count, total_s]
        self._recent: Dict[str, deque] = {}
        self._maxlen = maxlen

    def observe(self, phase: str, seconds: float) -> None:
        with self._mu:
            a = self._agg.get(phase)
            if a is None:
                a = self._agg[phase] = [0, 0.0]
                self._recent[phase] = deque(maxlen=self._maxlen)
            a[0] += 1
            a[1] += seconds
            self._recent[phase].append(seconds)

    def mean(self, phase: str) -> Optional[float]:
        """Cheap mean seconds per sample for one phase (None before any
        sample) — the dispatcher's admission control projects queue
        waits from these (ISSUE 5) without paying snapshot()'s
        percentile math."""
        with self._mu:
            a = self._agg.get(phase)
            return (a[1] / a[0]) if a and a[0] else None

    def recent_p99(self, phase: str) -> Optional[float]:
        """p99 seconds over the bounded recent window of one phase
        (None before any sample) — the SLO engine's decision-latency
        feed; cheaper than a full snapshot() every tick."""
        with self._mu:
            d = self._recent.get(phase)
            if not d:
                return None
            xs = np.asarray(d, float)
        return float(np.percentile(xs, 99))

    def snapshot(self) -> Dict[str, dict]:
        with self._mu:
            out = {}
            for phase, (count, total) in self._agg.items():
                xs = np.asarray(self._recent[phase], float)
                out[phase] = {
                    "count": count,
                    "total_ms": round(total * 1e3, 3),
                    "p50_ms": round(float(np.percentile(xs, 50)) * 1e3, 4),
                    "p99_ms": round(float(np.percentile(xs, 99)) * 1e3, 4),
                    "max_ms": round(float(xs.max()) * 1e3, 4),
                }
            return out


def _read_varint(data, pos: int):
    shift = result = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint overflow")


def _skip_field(data, wt: int, pos: int) -> int:
    if wt == 0:
        _, pos = _read_varint(data, pos)
    elif wt == 1:
        pos += 8
    elif wt == 5:
        pos += 4
    else:
        raise ValueError(f"wire type {wt}")
    return pos


def iter_wire_names(data) -> List[tuple]:
    """(name, unique_key) per request TLV of a serialized
    GetRateLimitsReq — a tolerant pure-Python walk (field 1 = repeated
    RateLimitReq; inside it field 1 = name, field 2 = unique_key).
    Runs on the analytics worker ONLY for waves carrying khashes the
    tenant cache hasn't seen, so steady-state traffic never parses."""
    out: List[tuple] = []
    pos, end = 0, len(data)
    while pos < end:
        tag, pos = _read_varint(data, pos)
        if tag & 7 != 2:
            pos = _skip_field(data, tag & 7, pos)
            continue
        ln, pos = _read_varint(data, pos)
        body_end = pos + ln
        if tag >> 3 == 1:
            name = uniq = ""
            p = pos
            while p < body_end:
                t, p = _read_varint(data, p)
                if t & 7 != 2:
                    p = _skip_field(data, t & 7, p)
                    continue
                sl, p = _read_varint(data, p)
                if t >> 3 == 1:
                    name = bytes(data[p:p + sl]).decode("utf-8",
                                                        "replace")
                elif t >> 3 == 2:
                    uniq = bytes(data[p:p + sl]).decode("utf-8",
                                                        "replace")
                p += sl
            if name:
                out.append((name, uniq))
        pos = body_end
    return out


class TenantLedger:
    """Bounded-cardinality per-tenant RED ledger (ISSUE 11).

    A tenant IS a key prefix (ROADMAP › multi-tenant QoS): the id is
    the key name up to the first ``delim`` (the whole name when the
    delimiter is absent).  At most ``max_tenants`` distinct ids get
    their own bucket; every later newcomer folds into ``__other__``
    (bucket 0), so label cardinality, memory, and the /debug/tenants
    payload are all bounded no matter how adversarial the key mix is.

    Conservation is structural, not statistical: every attributed row
    lands in EXACTLY one bucket (a real tenant, or ``__other__`` for
    overflow and unresolvable khashes), so per-tenant counts sum to
    the totals row exactly — asserted under the 16-thread chaos soak
    by tests/test_slo_tenants.py.

    Thread-safe (own leaf lock); the analytics worker does the bulk
    vectorized folds, flag taps trickle in from serving threads.
    """

    OTHER = "__other__"
    FIELDS = ("requests", "hits", "over_limit", "errors", "degraded",
              "shed")

    def __init__(self, delim: Optional[str] = None,
                 max_tenants: Optional[int] = None):
        if delim is None:
            delim = os.environ.get("GUBER_TENANT_DELIM", "/") or "/"
        self.delim = delim
        self.max_tenants = (max_tenants if max_tenants is not None
                            else _env_int("GUBER_TENANT_MAX", 64))
        self._mu = threading.Lock()
        self._idx: Dict[str, int] = {self.OTHER: 0}  # guarded-by: self._mu
        self._tenant_names: List[str] = [self.OTHER]  # guarded-by: self._mu
        #: per-bucket [requests, hits, over, errors, degraded, shed]
        self._counts: List[list] = [[0] * 6]  # guarded-by: self._mu
        self._overflowed = False  # guarded-by: self._mu

    def tenant_of(self, name: str) -> str:
        """Raw prefix extraction — no bucket assignment, no bounding.
        Safe from any thread; used for event-field hints."""
        i = name.find(self.delim)
        return name if i < 0 else name[:i]

    def index_of(self, name: str, pre_split: bool = False) -> int:
        """Bucket index for a key name (or an already-extracted tenant
        id when ``pre_split``), assigning a new bucket while room
        remains and folding overflow into ``__other__``."""
        tenant = name if pre_split else self.tenant_of(name)
        with self._mu:
            i = self._idx.get(tenant)
            if i is not None:
                return i
            if len(self._tenant_names) > self.max_tenants:
                self._overflowed = True
                return 0
            i = len(self._tenant_names)
            self._idx[tenant] = i
            self._tenant_names.append(tenant)
            self._counts.append([0] * 6)
            return i

    def fold(self, tidx: np.ndarray, hits: np.ndarray,
             over: np.ndarray) -> None:
        """Vectorized bulk attribution of one drained batch: one
        bincount per column, applied to every touched bucket."""
        nb = len(self._tenant_names)  # lock-free: buckets only grow; every tidx was assigned against a ledger of <= nb buckets
        req = np.bincount(tidx, minlength=nb)
        h = np.bincount(tidx, weights=np.asarray(hits, np.float64),
                        minlength=nb)
        o = np.bincount(tidx, weights=np.asarray(over, np.float64),
                        minlength=nb)
        touched = np.nonzero(req)[0]
        with self._mu:
            for b in touched:
                c = self._counts[b]
                c[0] += int(req[b])
                c[1] += int(h[b])
                c[2] += int(o[b])

    def add(self, idx: int, field: str, n: int = 1) -> None:
        f = self.FIELDS.index(field)
        with self._mu:
            self._counts[idx][f] += int(n)

    def totals(self) -> Dict[str, int]:
        with self._mu:
            sums = [sum(c[f] for c in self._counts)
                    for f in range(6)]
        return dict(zip(self.FIELDS, sums))

    def snapshot(self) -> dict:
        with self._mu:
            tenants = {name: dict(zip(self.FIELDS, counts))
                       for name, counts in zip(self._tenant_names,
                                               self._counts)}
            overflowed = self._overflowed
        totals = {f: sum(t[f] for t in tenants.values())
                  for f in self.FIELDS}
        return {"delim": self.delim, "max_tenants": self.max_tenants,
                "overflowed": overflowed,
                "tenant_count": len(tenants),
                "tenants": tenants, "totals": totals}

    def red(self, kind: str) -> Dict[str, tuple]:
        """Cumulative (bad, total) per tenant for the SLO engine's
        per-tenant groups: ``errors`` → (errors + degraded, requests);
        ``shed`` → (shed, requests + shed)."""
        with self._mu:
            out = {}
            for name, c in zip(self._tenant_names, self._counts):
                if kind == "shed":
                    bad, total = c[5], c[0] + c[5]
                else:
                    bad, total = c[3] + c[4], c[0]
                if total:
                    out[name] = (bad, total)
            return out


class CostModel:
    """Online α-β collective cost model: T(bytes) = α + β·bytes per
    (phase, device-count) bucket, the AllReduce time model from
    "Revisiting the Time Cost Model of AllReduce" (PAPERS.md) that the
    hierarchical-reconcile ROADMAP item needs per level.

    Each ``global_fold`` / ``broadcast`` / ``peer_flush`` phase record
    contributes one (bytes, seconds) sample; the fit is closed-form
    least squares over five running sums — no history kept, no deps,
    O(1) per sample.  Thread-safe (own leaf lock).
    """

    def __init__(self):
        self._mu = threading.Lock()
        #: (phase, ndev) → [n, Σx, Σy, Σxx, Σxy]   guarded-by: self._mu
        self._b: Dict[tuple, list] = {}

    def add(self, phase: str, nbytes: int, ndev: int,
            seconds: float) -> None:
        x, y = float(nbytes), float(seconds)
        with self._mu:
            b = self._b.get((phase, int(ndev)))
            if b is None:
                b = self._b[(phase, int(ndev))] = [0, 0.0, 0.0, 0.0,
                                                   0.0]
            b[0] += 1
            b[1] += x
            b[2] += y
            b[3] += x * x
            b[4] += x * y

    @staticmethod
    def _solve(b: list) -> Optional[dict]:
        n, sx, sy, sxx, sxy = b
        if n < 2:
            return None
        det = n * sxx - sx * sx
        if det <= 1e-12 * max(n * sxx, 1.0):
            # degenerate (all samples one size): β unidentifiable,
            # report the mean as pure α
            return {"n": int(n), "alpha_s": sy / n,
                    "beta_s_per_byte": 0.0, "mean_bytes": sx / n}
        beta = (n * sxy - sx * sy) / det
        alpha = (sy - beta * sx) / n
        return {"n": int(n), "alpha_s": alpha,
                "beta_s_per_byte": beta, "mean_bytes": sx / n}

    def fit(self, phase: str, ndev: int) -> Optional[dict]:
        with self._mu:
            b = self._b.get((phase, int(ndev)))
            b = list(b) if b else None
        return self._solve(b) if b else None

    def predict(self, phase: str, ndev: int,
                nbytes: int) -> Optional[float]:
        f = self.fit(phase, ndev)
        if f is None:
            return None
        return f["alpha_s"] + f["beta_s_per_byte"] * float(nbytes)

    def snapshot(self) -> dict:
        """The ``GET /debug/costmodel`` document: every bucket's fitted
        constants (α in seconds, β in seconds/byte) + sample counts."""
        with self._mu:
            items = [(k, list(v)) for k, v in self._b.items()]
        buckets = []
        for (phase, ndev), b in sorted(items):
            f = self._solve(b)
            row = {"phase": phase, "ndev": ndev, "samples": int(b[0])}
            if f is not None:
                row.update({"alpha_us": round(f["alpha_s"] * 1e6, 3),
                            "beta_ns_per_byte":
                                round(f["beta_s_per_byte"] * 1e9, 6),
                            "mean_bytes": round(f["mean_bytes"], 1)})
            buckets.append(row)
        return {"model": "T = alpha + beta * bytes",
                "buckets": buckets}


class _Flush:
    """Queue sentinel: the worker sets the event when it reaches it."""

    def __init__(self):
        self.done = threading.Event()


class KeyAnalytics:
    """The analytics subsystem: tap queue + worker + sketch + phases.

    Taps copy the wave's (khash, hits, status) columns — a few KB — and
    enqueue; ``tap_reqs`` enqueues the request/response object lists
    (the worker hashes names there, recovering key names).  A full
    queue DROPS the wave and counts it: analytics must never apply
    backpressure to the serving path.
    """

    #: worker pacing: after folding a drained batch, rest this long.
    #: Everything queued in the window folds in ONE vectorized update,
    #: amortizing the per-update fixed costs — and bounding the
    #: worker's GIL duty cycle, which on small hosts otherwise convoys
    #: the serving thread's C sections.
    BATCH_INTERVAL_S = 0.1

    #: top-K gauge refresh cadence: the label-set diff walks every
    #: tracked key, so it runs on this timer (and on flush/scrape),
    #: never per fold.
    PUBLISH_INTERVAL_S = 2.0

    def __init__(self, metrics=None, k: Optional[int] = None,
                 width: Optional[int] = None, queue_cap: int = 512,
                 clock=time.time):
        self.metrics = metrics
        #: per-phase histogram children resolved once — .labels() per
        #: sample is a lock + dict walk on the serving path
        self._phase_hist: Dict[str, object] = {}
        self._clock = clock
        k = k if k is not None else _env_int("GUBER_TOPK", 256)
        width = (width if width is not None
                 else _env_int("GUBER_SKETCH_WIDTH", 4 * k))
        self._mu = threading.Lock()  # guards sketch + counters
        self.sketch = HeavyHitterSketch(k=k, width=width)  # guarded-by: self._mu
        self.phases = PhaseLedger()  # internally locked (own _mu)
        #: per-tenant RED ledger (ISSUE 11); None disables attribution
        #: entirely (the bench A/B detaches it the way it detaches
        #: the whole analytics plane)
        self._tenants: Optional[TenantLedger] = TenantLedger()
        #: α-β collective cost model; taps go straight in (leaf lock,
        #: samples arrive from reconcile/flush threads, never hot)
        self.costmodel = CostModel()
        #: khash → tenant bucket index, learned from named taps and
        #: wire-name learn items.  Worker-thread-owned writes; GIL-
        #: atomic .get() reads serve event-field hints.  lock-free
        self._kh_tenant: Dict[int, int] = {}
        self._kh_cap = max(8 * width, 4096)
        # lazily rebuilt sorted lookup for vectorized fold attribution
        # (worker-thread only)
        self._kh_sorted = np.empty(0, np.uint64)
        self._kh_tidx = np.empty(0, np.int64)
        self._kh_dirty = False
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_cap)
        self._waves = 0  # guarded-by: self._mu
        self._dropped = 0  # guarded-by: self._mu
        self._pub_mu = threading.Lock()  # serializes gauge refreshes
        self._published: Dict[str, float] = {}  # guarded-by: self._pub_mu
        self._last_publish = 0.0  # guarded-by: self._pub_mu
        self._closing = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="key-analytics")
        self._thread.start()

    # ---- taps (serving path; must stay O(copy) and non-blocking) -------

    def tap_packed(self, khash, hits, status) -> bool:
        """Columnar wave tap: copies the three columns NOW (the caller's
        arrays may be pool-leased or shared result views) and enqueues.
        Returns False when the queue was full (wave dropped)."""
        item = ("cols",
                np.array(khash, np.uint64, copy=True),
                np.array(hits, np.int64, copy=True),
                np.array(np.asarray(status) == 1, bool),
                int(self._clock() * 1000))
        return self._put(item)

    def tap_reqs(self, reqs, resps) -> bool:
        """Object-lane tap: the worker extracts names/hits/status (and
        hashes the keys) off the serving path."""
        if not reqs:
            return True
        return self._put(("reqs", list(reqs), list(resps),
                          int(self._clock() * 1000)))

    def tap_wire_names(self, data, khash=None, raw: bool = False
                       ) -> bool:
        """Tenant learn tap for the columnar wire lanes, which carry
        only khashes: enqueue the (immutable) wire bytes plus the
        lane's khash view so the WORKER can map new khashes to tenant
        ids — zero copies, zero parsing on the serving path.  ``raw``
        marks a pre-mix khash (parse output); the worker applies the
        finalizer itself.  FIFO ordering guarantees the learn lands
        before the wave's own "cols"/"dev" item is folded."""
        if self._tenants is None:
            return True
        return self._put(("learn", data, khash, raw))

    def tap_flag(self, field: str, n: int = 1,
                 tenant: Optional[str] = None,
                 khash: Optional[int] = None,
                 name: Optional[str] = None) -> bool:
        """Exceptional-outcome attribution (``errors`` / ``degraded``
        / ``shed``): cheap enqueue from the serving path, resolved to
        a tenant bucket on the worker (explicit tenant id → khash
        cache → key name → ``__other__``)."""
        if self._tenants is None:
            return True
        return self._put(("flag", field, int(n), tenant, khash, name))

    def tap_cost(self, phase: str, nbytes: int, ndev: int,
                 seconds: float) -> None:
        """One collective cost sample (leaf-locked, direct — callers
        are reconcile ticks and flush completions, never wave-rate)."""
        self.costmodel.add(phase, nbytes, ndev, seconds)

    def tap_device(self, tap) -> bool:
        """Fused-engine wave tap (ISSUE 8): ``tap`` is the [4, B] int64
        device array the fused serving program emitted alongside its
        decisions — rows (khash bit-viewed, hits, over, served).  NO
        host copy happens here: the jax array is a future; the worker
        thread's np.asarray is where the device→host transfer (and any
        blocking on the wave) lands, strictly off the serving path.
        Returns False when the queue was full (wave dropped)."""
        return self._put(("dev", tap, int(self._clock() * 1000)))

    @staticmethod
    def _dev_to_cols(item):
        """Materialize a device tap on the WORKER thread → a "cols"
        item (padding / invalid / table-full rows gated out by the
        kernel-emitted ``served`` row).  None when empty or the array
        failed to materialize (a dead device must not kill the
        worker)."""
        try:
            arr = np.asarray(item[1])
            served = arr[3] != 0
            if not served.any():
                return None
            return ("cols", arr[0][served].view(np.uint64),
                    arr[1][served], arr[2][served] != 0, int(item[2]))
        except Exception:  # pragma: no cover - analytics only
            import logging

            logging.getLogger("gubernator_tpu.analytics").exception(
                "device tap materialize")
            return None

    def _put(self, item) -> bool:
        try:
            self._q.put_nowait(item)
        except queue.Full:
            with self._mu:
                self._dropped += 1
            if self.metrics is not None:
                self.metrics.analytics_dropped.inc()
            return False
        return True

    # ---- phase attribution ---------------------------------------------

    def observe_phase(self, phase: str, seconds: float,
                      exemplar=None) -> None:
        """One phase sample → histogram + /debug/phases ledger.
        ``exemplar`` (ISSUE 12): a recent sampled trace's label dict,
        attached to the histogram observation so a slow-phase bucket
        links to one concrete trace (openmetrics exposition)."""
        from .metrics import observe_with_exemplar

        seconds = max(seconds, 0.0)
        self.phases.observe(phase, seconds)
        m = self.metrics
        if m is not None:
            child = self._phase_hist.get(phase)
            if child is None:  # benign race: labels() is idempotent
                child = self._phase_hist[phase] = \
                    m.phase_duration.labels(phase=phase)
            observe_with_exemplar(child, seconds, exemplar)

    # ---- worker ---------------------------------------------------------

    def _run(self) -> None:
        q = self._q
        while True:
            item = q.get()
            cols: list = []
            while True:
                if item is None:
                    self._fold_cols(cols)
                    return
                if isinstance(item, _Flush):
                    self._fold_cols(cols)
                    cols = []
                    item.done.set()
                elif item[0] == "cols":
                    cols.append(item)
                elif item[0] == "dev":
                    # fused-engine device tap: the device→host copy
                    # happens HERE, on the worker
                    c = self._dev_to_cols(item)
                    if c is not None:
                        cols.append(c)
                elif item[0] == "learn":
                    # tenant-cache learn MUST precede the fold of any
                    # cols queued behind it (FIFO), and folding the
                    # ones queued AHEAD of it later is harmless — so
                    # apply immediately, no barrier
                    self._safe_learn(item)
                elif item[0] == "flag":
                    self._safe_flag(item)
                else:
                    # object-lane (named) tap: fold queued columns
                    # first so wave order is preserved
                    self._fold_cols(cols)
                    cols = []
                    self._safe_apply(item)
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
            self._fold_cols(cols)
            if not self._closing:
                time.sleep(self.BATCH_INTERVAL_S)

    def _fold_cols(self, cols: list) -> None:
        """Everything the drain window collected folds in ONE sketch
        update (one unique/sort/admission pass for the whole burst)."""
        if not cols:
            return
        try:
            if len(cols) == 1:
                _, khash, hits, over, t_ms = cols[0]
            else:
                khash = np.concatenate([c[1] for c in cols])
                hits = np.concatenate([c[2] for c in cols])
                over = np.concatenate([c[3] for c in cols])
                t_ms = cols[-1][4]
            with self._mu:
                self.sketch.update(khash, hits, over, t_ms)
                self._waves += len(cols)
            if self._tenants is not None:
                self._fold_tenants(np.asarray(khash, np.uint64),
                                   np.asarray(hits, np.int64),
                                   np.asarray(over, bool))
            if self.metrics is not None:
                self.metrics.analytics_waves.inc(len(cols))
            self._maybe_publish()
        except Exception:  # pragma: no cover - must never die
            import logging

            logging.getLogger("gubernator_tpu.analytics").exception(
                "analytics fold")

    def _safe_apply(self, item) -> None:
        try:
            self._apply(item)
        except Exception:  # pragma: no cover - must never die
            import logging

            logging.getLogger("gubernator_tpu.analytics").exception(
                "analytics tap apply")

    def _apply(self, item) -> None:
        _, reqs, resps, t_ms = item
        from .hashing import hash_request_keys

        khash = hash_request_keys([r.name for r in reqs],
                                  [r.unique_key for r in reqs])
        hits = np.fromiter((int(r.hits) for r in reqs), np.int64,
                           len(reqs))
        over = np.fromiter((int(r.status) == 1 for r in resps),
                           bool, len(resps))
        names = [f"{r.name}_{r.unique_key}" for r in reqs]
        with self._mu:
            self.sketch.update(khash, hits, over, t_ms, names=names)
            self._waves += 1
        tl = self._tenants
        if tl is not None:
            # learn khash → tenant (object lanes carry names), then
            # attribute through the same fold path as the wire lanes
            tidx = np.fromiter(
                (tl.index_of(r.name) for r in reqs), np.int64,
                len(reqs))
            for i in range(len(reqs)):
                self._kh_note(int(khash[i]), int(tidx[i]))
            tl.fold(tidx, hits, over)
            for i, r in enumerate(resps):
                if getattr(r, "error", ""):
                    tl.add(int(tidx[i]), "errors", 1)
        if self.metrics is not None:
            self.metrics.analytics_waves.inc()
        self._maybe_publish()

    # ---- tenant attribution (worker thread) -----------------------------

    def _kh_note(self, kh: int, tidx: int) -> None:
        cache = self._kh_tenant
        if kh in cache:
            if cache[kh] != tidx:
                cache[kh] = tidx
                self._kh_dirty = True
            return
        if len(cache) >= self._kh_cap:
            # bounded like the sketch's name table: shed the oldest
            # half (plain dicts pop in insertion order); affected keys
            # re-learn on their next named/wire appearance and fold
            # into __other__ meanwhile — conservation holds either way
            for old in list(cache)[: self._kh_cap // 2]:
                del cache[old]
        cache[kh] = tidx
        self._kh_dirty = True

    def _kh_lookup_arrays(self):
        if self._kh_dirty or self._kh_sorted.size != len(self._kh_tenant):
            kh = np.fromiter(self._kh_tenant.keys(), np.uint64,
                             len(self._kh_tenant))
            ti = np.fromiter(self._kh_tenant.values(), np.int64,
                             len(self._kh_tenant))
            order = np.argsort(kh)
            self._kh_sorted = kh[order]
            self._kh_tidx = ti[order]
            self._kh_dirty = False
        return self._kh_sorted, self._kh_tidx

    def _fold_tenants(self, khash, hits, over) -> None:
        """Attribute one folded batch to tenant buckets: vectorized
        searchsorted against the learned khash cache; khashes the
        cache can't resolve land in ``__other__`` (bucket 0) so every
        row is counted exactly once."""
        tl = self._tenants
        ks, ti = self._kh_lookup_arrays()
        if ks.size:
            pos = np.minimum(np.searchsorted(ks, khash), ks.size - 1)
            known = ks[pos] == khash
            tidx = np.where(known, ti[pos], 0)
        else:
            tidx = np.zeros(len(khash), np.int64)
        tl.fold(tidx, hits, over)

    def _safe_learn(self, item) -> None:
        try:
            self._apply_learn(item)
        except Exception:  # pragma: no cover - must never die
            import logging

            logging.getLogger("gubernator_tpu.analytics").exception(
                "tenant learn")

    def _apply_learn(self, item) -> None:
        tl = self._tenants
        if tl is None:
            return
        _, data, kh, raw = item
        if kh is not None and len(self._kh_tenant):
            khm = np.asarray(kh)
            if khm.dtype != np.uint64:
                khm = khm.view(np.uint64) if khm.dtype == np.int64 \
                    else khm.astype(np.uint64)
            if raw:
                from .hashing import mix64_np

                khm = mix64_np(khm)
            ks, _ = self._kh_lookup_arrays()
            pos = np.minimum(np.searchsorted(ks, khm), ks.size - 1)
            if bool((ks[pos] == khm).all()):
                return  # steady state: every khash known, no parse
        pairs = iter_wire_names(data)
        if not pairs:
            return
        from .hashing import hash_request_keys

        khash = hash_request_keys([p[0] for p in pairs],
                                  [p[1] for p in pairs])
        for i, (name, _uniq) in enumerate(pairs):
            self._kh_note(int(khash[i]), tl.index_of(name))

    def _safe_flag(self, item) -> None:
        try:
            tl = self._tenants
            if tl is None:
                return
            _, field, n, tenant, khash, name = item
            if tenant is not None:
                idx = tl.index_of(tenant, pre_split=True)
            elif khash is not None and khash in self._kh_tenant:
                idx = self._kh_tenant[khash]
            elif name is not None:
                idx = tl.index_of(name)
            else:
                idx = 0
            tl.add(idx, field, n)
        except Exception:  # pragma: no cover - must never die
            import logging

            logging.getLogger("gubernator_tpu.analytics").exception(
                "tenant flag")

    def tenant_hint(self, khash: Optional[int] = None,
                    name: Optional[str] = None) -> Optional[str]:
        """Best-effort tenant id for event fields: khash → learned
        bucket name (GIL-atomic dict read of worker-owned state),
        else the raw prefix of ``name``.  Never assigns buckets, so
        it is safe (and cheap) from any serving thread."""
        tl = self._tenants
        if tl is None:
            return None
        if khash is not None:
            idx = self._kh_tenant.get(int(khash))
            if idx is not None:
                try:
                    return tl._tenant_names[idx]
                except IndexError:  # pragma: no cover - benign race
                    return None
        if name is not None:
            return tl.tenant_of(name)
        return None

    def _maybe_publish(self) -> None:
        now = time.monotonic()
        with self._pub_mu:
            # check-then-set under the lock: the scrape thread's
            # republish() writes the same stamp (guarded-by sweep found
            # this as a racy double-publish window)
            due = now - self._last_publish >= self.PUBLISH_INTERVAL_S
            if due:
                self._last_publish = now
        if due:
            self._publish()

    def republish(self) -> None:
        """Scrape-time gauge refresh (daemon /metrics handler): the
        label churn costs the scraper, never the analytics worker."""
        with self._pub_mu:
            self._last_publish = time.monotonic()
        self._publish()

    def _publish(self) -> None:
        """Refresh gubernator_topkey_overlimit_total for the CURRENT
        top-K only: labels of departed keys are removed first, so the
        family's cardinality is bounded by K at every scrape — never
        per-key labels over the whole key space."""
        if self.metrics is None:
            return
        with self._mu:
            top = self.sketch.topk()
        fresh = {}
        for e in top:
            label = e["key"] or f"0x{e['khash']:016x}"
            fresh[label] = float(e["over_limit"])
        gauge = self.metrics.topkey_overlimit
        with self._pub_mu:
            for label in list(self._published):
                if label not in fresh:
                    try:
                        gauge.remove(label)
                    except KeyError:  # pragma: no cover - already gone
                        pass
            for label, val in fresh.items():
                gauge.labels(key=label).set(val)
            self._published = fresh
        self._publish_tenants()

    def _publish_tenants(self) -> None:
        """gubernator_tenant_* gauge refresh: cardinality is bounded
        by the ledger itself (GUBER_TENANT_MAX + __other__), and
        buckets never depart, so no label removal pass is needed."""
        tl = self._tenants
        m = self.metrics
        if tl is None or m is None:
            return
        gauges = (m.tenant_requests, m.tenant_hits,
                  m.tenant_over_limit, m.tenant_errors,
                  m.tenant_degraded, m.tenant_shed)
        snap = tl.snapshot()
        for tenant, counts in snap["tenants"].items():
            for gauge, field in zip(gauges, TenantLedger.FIELDS):
                gauge.labels(tenant=tenant).set(float(counts[field]))

    # ---- reporting ------------------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every tap enqueued so far has been applied (and
        the gauge republished) — tests and snapshot callers."""
        f = _Flush()
        try:
            self._q.put(f, timeout=timeout)
        except queue.Full:
            return False
        ok = f.done.wait(timeout)
        if ok:
            self._publish()
        return ok

    def sketch_count(self, khash: int) -> int:
        """Thread-safe tracked-count read for one key hash (0 when
        untracked) — the hot-set promotion feed (instance.py ›
        _count_toward_promotion) and the tiered store's admission rank
        (tiering.py)."""
        with self._mu:
            return self.sketch.count_of(khash)

    def sketch_counts(self, khashes) -> List[int]:
        """Batched :meth:`sketch_count` — ONE lock acquisition for a
        probe window's worth of victim-candidate ranks (tiering.py ›
        _pick_victim picks the coldest device row to evict)."""
        with self._mu:
            return [self.sketch.count_of(int(k)) for k in khashes]

    def stats(self) -> dict:
        with self._mu:
            return {"k": self.sketch.k, "width": self.sketch.width,
                    "waves_tapped": self._waves,
                    "taps_dropped": self._dropped,
                    "tracked_keys": len(self.sketch),
                    "queue_depth": self._q.qsize()}

    def mem_stats(self) -> dict:
        """Memory-ledger probe feed (ISSUE 13): the sketch's host
        bytes are its five width-length columns, live at all times."""
        with self._mu:
            sk = self.sketch
            nbytes = int(sk._cnt.nbytes + sk._err.nbytes
                         + sk._over.nbytes + sk._last.nbytes
                         + sk._kh.nbytes)
            return {"bytes": nbytes, "width": sk.width,
                    "used": len(sk),
                    "total_weight": int(sk.total_weight)}

    def rank_distribution(self, limit: int = 4096) -> List[int]:
        """Space-Saving rank distribution: tracked counts, descending —
        the hot table's marginal-hit-density curve for the memory
        ledger's advisor (memledger.py › advise).  Rank r's count is
        the observed demand a cache of r+1 rows would capture at the
        margin; the advisor extrapolates past ``limit``."""
        with self._mu:
            used = len(self.sketch)
            cnt = np.sort(self.sketch._cnt[:used])[::-1]
        return [int(v) for v in cnt[:max(int(limit), 1)]]

    def topkeys_snapshot(self, limit: Optional[int] = None) -> dict:
        """The ``GET /debug/topkeys`` document (owner resolution is the
        daemon's job — it knows the ring)."""
        with self._mu:
            top = self.sketch.topk(limit)
            bound = self.sketch.error_bound()
            total = self.sketch.total_weight
        out = self.stats()
        out.update({"total_hits_observed": total,
                    "admission_error_bound": bound,
                    "keys": [dict(e, khash=f"0x{e['khash']:016x}")
                             for e in top]})
        return out

    def phases_snapshot(self) -> dict:
        return {"phases": self.phases.snapshot()}

    def tenants_snapshot(self) -> dict:
        """The ``GET /debug/tenants`` document."""
        tl = self._tenants
        if tl is None:
            return {"enabled": False}
        out = tl.snapshot()
        out["enabled"] = True
        return out

    def tenant_red(self, kind: str) -> Dict[str, tuple]:
        """Per-tenant cumulative (bad, total) feed for the SLO
        engine's tenant groups (empty when attribution is off)."""
        tl = self._tenants
        return tl.red(kind) if tl is not None else {}

    def tenant_totals(self) -> Dict[str, int]:
        tl = self._tenants
        if tl is None:
            return {}
        return tl.totals()

    def costmodel_snapshot(self) -> dict:
        """The ``GET /debug/costmodel`` document."""
        return self.costmodel.snapshot()

    def close(self) -> None:
        self._closing = True
        try:
            self._q.put_nowait(None)
        except queue.Full:  # drain enough to deliver the poison pill
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._q.put(None)
        self._thread.join(timeout=5)

"""Sharded multi-chip engine: key-ranged tables under shard_map.

The reference forwards non-owned keys to their owner over gRPC
(gubernator.go › GetRateLimits fan-out → peer_client.go batches —
reconstructed).  Here every chip owns a hash range; the host routes each
request to its owner's sub-batch and one shard_map program applies all
sub-batches simultaneously — the "forwarding hop" is a host-side array
permutation plus one ICI-synchronized step instead of N² RPC streams.

Decision semantics are identical to single-chip: each key's state lives
on exactly one shard, so owner-applies-hits parity is exact.
"""
from __future__ import annotations

import logging
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..hashing import shard_of
from ..types import RateLimitRequest, RateLimitResponse, Status
from ..core.batch import (RequestBatch, WaveBufferPool, empty_batch,
                          pack_requests)
from ..core.step import decide_batch_impl, _insert, _lookup, _probe_slots
from ..core.table import TableState, init_table
from .mesh import (SHARD_AXIS, XLA_EXEC_MU, make_mesh, shard_map,
                   shard_table, table_sharding)

log = logging.getLogger("gubernator_tpu.sharded")

try:  # fused C++ wire ingest (ops/_native.cpp); optional
    from ..ops import native as _wire_native
except ImportError:  # pragma: no cover - unbuilt extension
    _wire_native = None

#: TableState value columns addressable by row programs (all but `key`).
VALUE_COLS = tuple(f for f in TableState._fields if f != "key")


class PrepackedWave:
    """One fused-ingest wave: a leased packed upload pair with rows
    [0, n) already parsed/clamped/hashed in C++ (pack_wire_wave), plus
    the per-request metadata the serving lanes gate on.  The holder
    owns the lease until ``ShardedEngine.check_prepacked`` consumes it
    (or must release it explicitly on a fallback path)."""

    __slots__ = ("lease", "n", "khash", "khash_raw", "behavior_or",
                 "tlv_off", "tlv_len")

    def __init__(self, lease, n, khash, khash_raw, behavior_or,
                 tlv_off, tlv_len):
        self.lease = lease
        self.n = n
        self.khash = khash
        self.khash_raw = khash_raw
        self.behavior_or = behavior_or
        self.tlv_off = tlv_off
        self.tlv_len = tlv_len


def autogrow_limit_per_shard(total_rows: int, n_shards: int,
                             cap_local: int) -> int:
    """Config's cache_autogrow_max (TOTAL rows, an upper bound) → the
    per-shard ceiling ShardedEngine takes: rounded DOWN to a power of
    two (a memory bound must never be exceeded), floored at the current
    capacity (a bound below it just disables growth)."""
    if total_rows <= 0:
        return 0
    agl = max(total_rows // n_shards, cap_local)
    return 1 << (agl.bit_length() - 1)


def make_gather_rows(mesh):
    """jit program: probe-lookup a [n·B] key block per shard, return
    (found mask, value columns) — the owner-side read for GLOBAL
    broadcasts (global.go › runBroadcasts collecting changed items)."""

    def _gather(state, keys):
        slots = _probe_slots(keys, state.key.shape[0])
        row, _ = _lookup(state.key, slots, keys)
        found = (keys != 0) & (row >= 0)
        cols = tuple(
            getattr(state, f).at[jnp.where(found, row, 0)].get()
            for f in VALUE_COLS)
        return found, cols

    return jax.jit(shard_map(
        _gather, mesh=mesh, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(SHARD_AXIS)))


def make_remove_rows(mesh):
    """jit program: probe-lookup a [n·B] key block and clear matched
    rows (key + expire → 0).  The Cache.Remove analog (cache.go) —
    used by the Store-backed admin path."""

    def _remove(state, keys):
        slots = _probe_slots(keys, state.key.shape[0])
        row, _ = _lookup(state.key, slots, keys)
        found = (keys != 0) & (row >= 0)
        wrow = jnp.where(found, row, state.key.shape[0])
        return state._replace(
            key=state.key.at[wrow].set(jnp.uint64(0), mode="drop"),
            expire_at=state.expire_at.at[wrow].set(jnp.int64(0),
                                                   mode="drop"),
        ), found

    return jax.jit(shard_map(
        _remove, mesh=mesh, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS))))


def make_upsert_rows(mesh):
    """jit program: find-or-insert a [n·B] key block per shard and
    overwrite the value columns — the replica-side write for GLOBAL
    broadcasts (gubernator.go › UpdatePeerGlobals → cache.Add analog).
    Returns (new_state, placed mask)."""

    def _upsert(state, keys, cols):
        cap = state.key.shape[0]
        valid = keys != 0
        slots = _probe_slots(keys, cap)
        tkey, row, _ = _insert(state.key, slots, keys, valid,
                               jnp.full(keys.shape, -1, jnp.int32))
        placed = valid & (row >= 0)
        wrow = jnp.where(placed, row, cap)
        new = {"key": tkey}
        for f, col in zip(VALUE_COLS, cols):
            new[f] = getattr(state, f).at[wrow].set(col, mode="drop")
        return TableState(**new), placed

    sharded = shard_map(
        _upsert, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)))
    return jax.jit(sharded)


def make_grow(mesh, cap_new: int):
    """jit program: re-place every live row of a [cap_old] shard table
    into a fresh [cap_new] table, entirely on device — the reshard path
    for capacity changes (ROUND_NOTES gap: the host-mediated
    snapshot/restore loop is shard-count independent but streams the
    whole table through host memory; this is one device program).

    Key→shard ownership depends only on the mesh size (hashing.shard_of),
    so capacity changes never move rows across shards: the program is a
    per-shard probe re-insertion plus a psum'd dropped-row count (rows
    whose probe window in the target is exhausted — common when
    shrinking into high occupancy, rare but possible even when growing
    from a full table; best-effort like restore, and callers surface
    the count: a dropped key resets, which is inside the reference's
    LRU-eviction contract but must be observable).
    """

    def _grow(state):
        cap_old = state.key.shape[0]
        key = state.key
        valid = key != 0
        slots = _probe_slots(key, cap_new)
        tkey, row, _ = _insert(jnp.zeros(cap_new, jnp.uint64), slots, key,
                               valid, jnp.full(cap_old, -1, jnp.int32))
        placed = valid & (row >= 0)
        wrow = jnp.where(placed, row, cap_new)
        # init_table is shard_map-safe (no device placement; its guards
        # are host-side trace-time checks) and the single source of
        # truth for column defaults
        fresh = init_table(cap_new)
        new = {"key": tkey}
        for f in VALUE_COLS:
            new[f] = getattr(fresh, f).at[wrow].set(getattr(state, f),
                                                    mode="drop")
        dropped = lax.psum((valid & (~placed)).sum(dtype=jnp.int64),
                           SHARD_AXIS)
        return TableState(**new), dropped

    return jax.jit(shard_map(
        _grow, mesh=mesh, in_specs=P(SHARD_AXIS),
        out_specs=(P(SHARD_AXIS), P())))


def responses_from_columns(cols, errors=None):
    """(status, limit, remaining, reset, full) columns + optional
    per-request error strings → RateLimitResponse objects.  THE response
    contract, shared by the engine's object lane and the dispatcher's
    merged-wave path."""
    st, lim, rem, rst, full = cols
    # one bulk conversion to Python ints: per-element numpy scalar
    # indexing costs ~µs each and this loop runs per request
    st_l = np.asarray(st).tolist()
    lim_l = np.asarray(lim).tolist()
    rem_l = np.asarray(rem).tolist()
    rst_l = np.asarray(rst).tolist()
    full_l = np.asarray(full).tolist()
    out: List[RateLimitResponse] = []
    for i in range(len(st_l)):
        if errors is not None and errors[i]:
            out.append(RateLimitResponse(error=errors[i]))
        elif full_l[i]:
            # probe window exhausted by LIVE keys even after the sweep
            # retry (and auto-grow, if enabled) inside check_packed
            out.append(RateLimitResponse(error="rate limit table full"))
        else:
            out.append(RateLimitResponse(
                # attribute lookup, not Status(...): the enum
                # constructor costs ~µs and this is per request
                status=Status.OVER_LIMIT if st_l[i]
                else Status.UNDER_LIMIT,
                limit=lim_l[i], remaining=rem_l[i],
                reset_time=rst_l[i]))
    return out


def make_sharded_step(mesh, donate: bool = False):
    """jit-compiled sharded step: (state, batch, now) → (state, outputs).

    state/batch arrays are globally [n·cap_local] / [n·B] with block d on
    device d; outputs keep that layout; counters are psum-reduced across
    the mesh (the only collective on the hot path — metrics, not data).

    ``donate`` aliases the table in/out (see core/step.py ›
    decide_batch_donated for the trade-off); callers must then thread
    state linearly.
    """
    S = SHARD_AXIS

    def _step(state, batch, now):
        state, out = decide_batch_impl(state, batch, now)
        over = lax.psum(out.over_count, S)
        ins = lax.psum(out.insert_count, S)
        return state, (out.status, out.remaining, out.reset_time, out.limit,
                       out.err), (over, ins)

    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(P(S), P(S), P()),
        out_specs=(P(S), P(S), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


#: Packed-transfer wire layout for the serving step: every RequestBatch
#: int64 column rides one [8, B] int64 upload (key bit-viewed; row 7 is
#: the per-request arrival time), the int32/bool columns one [3, B]
#: int32 upload, and all five outputs one [5, B] int64 download.  A
#: device call then costs 2 uploads + 1 download instead of 10 + 5 —
#: per-transfer latency (PCIe doorbells, or milliseconds over a
#: tunneled link) dominates these tiny arrays, not bandwidth.
PACK64 = ("key", "hits", "limit", "duration", "eff_ms", "greg_end",
          "burst", "now")
PACK32 = ("behavior", "algorithm", "valid")


def pack_wave_host(b: RequestBatch) -> tuple[np.ndarray, np.ndarray]:
    """RequestBatch of numpy columns → ([8,B] i64, [3,B] i32)."""
    B = len(b.key)
    a64 = np.empty((len(PACK64), B), np.int64)
    a64[0] = np.asarray(b.key).view(np.int64)
    for i, f in enumerate(PACK64[1:], start=1):
        a64[i] = getattr(b, f)
    a32 = np.empty((len(PACK32), B), np.int32)
    a32[0] = b.behavior
    a32[1] = b.algorithm
    a32[2] = b.valid
    return a64, a32


def make_sharded_step_packed(mesh, donate: bool = False):
    """The serving twin of make_sharded_step over the packed wire layout
    (see PACK64/PACK32): (state, a64, a32, now) → (state, [5,B] i64
    outputs, (over, insert) counters)."""
    S = SHARD_AXIS

    def _step(state, a64, a32, now):
        batch = RequestBatch(
            key=lax.bitcast_convert_type(a64[0], jnp.uint64),
            hits=a64[1], limit=a64[2], duration=a64[3], eff_ms=a64[4],
            greg_end=a64[5], burst=a64[6], now=a64[7],
            behavior=a32[0], algorithm=a32[1], valid=a32[2] != 0)
        state, out = decide_batch_impl(state, batch, now)
        packed = jnp.stack([
            out.status.astype(jnp.int64), out.remaining, out.reset_time,
            out.limit, out.err.astype(jnp.int64)])
        over = lax.psum(out.over_count, S)
        ins = lax.psum(out.insert_count, S)
        return state, packed, (over, ins)

    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(P(S), P(None, S), P(None, S), P()),
        out_specs=(P(S), P(None, S), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


class ShardedEngine:
    """Host dispatcher over a sharded table: the multi-chip analog of the
    reference's V1Instance request router (gubernator.go ›
    GetRateLimits → picker.Get → local/forward split)."""

    #: capability flags the dispatcher reads (ISSUE 8): fused engines
    #: (parallel/pallas_engine.py › FusedServingMixin) flip both — the
    #: wave's pack mark collapses into the `device` phase and the
    #: dispatcher's host-side column taps are skipped (the fused step
    #: emits the tap columns on device).  The classic engine keeps the
    #: classic phase partition and host taps.
    fused_serving = False
    fused_tap = False

    def __init__(self, mesh=None, capacity_per_shard: int = 1 << 16,
                 batch_per_shard: int = 1024,
                 auto_grow_limit: int = 0,
                 wave_buckets: Sequence[int] | None = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n = self.mesh.shape[SHARD_AXIS]
        self.cap_local = capacity_per_shard
        self.B = batch_per_shard
        #: Wave-size buckets for check_packed: a pass picks the smallest
        #: bucket covering its busiest shard, so a lone client batch
        #:  rides the small fast program while dispatcher-coalesced
        #: bursts amortize launch cost in one big wave instead of
        #: ceil(n/B) small ones (the front-door throughput lever —
        #: VERDICT r1 item 5).  Each bucket is one compiled program;
        #: warmup() pre-compiles them all.
        import os as _os
        env_buckets = _os.environ.get("GUBER_WAVE_BUCKETS", "")
        if wave_buckets:
            self.wave_buckets = tuple(sorted(set(wave_buckets)))
        elif env_buckets:
            self.wave_buckets = tuple(sorted(
                {int(x) for x in env_buckets.split(",") if x.strip()}))
        else:
            self.wave_buckets = (batch_per_shard, batch_per_shard * 8)
        #: per-shard capacity ceiling for on-device auto-grow when probe
        #: windows stay exhausted after a sweep (0 = disabled).  The
        #: reference's LRU never fails an insert; with auto-grow on,
        #: neither do we until this bound.
        self.auto_grow_limit = auto_grow_limit
        self._init_table_and_step()
        self._batch_sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
        self._mat_sharding = NamedSharding(self.mesh, P(None, SHARD_AXIS))
        self._repl = NamedSharding(self.mesh, P())
        self.over_count = 0
        self.insert_count = 0
        self.sweep_count = 0
        self.live_rows = -1  # set by the fused Pallas sweep
        self._gather = None  # lazily-built row programs
        self._upsert = None
        self._remove = None
        self._pallas_sweep_fn = None
        self._grow_fns: dict = {}  # cap_new → compiled grow program
        self.dropped_rows = 0  # rows lost to grow/restore re-placement
        #: reusable packed-upload matrices, one ring per wave width
        #: (core/batch.py): leased in _fill_packed, released right
        #: after the launch consumes them (jax copies host operands at
        #: dispatch).  V1Instance binds its Metrics here for the
        #: hit/miss/leak counters.
        self.wave_pool = WaveBufferPool()
        #: bound TierController (tiering.py) when GUBER_TIER_COLD=1 —
        #: check_packed pre-masks cold-resident rows out of the device
        #: wave and serves them (plus residual table-full rows) from
        #: the host cold tier on the way out
        self.tier = None  # lock-free: set once at instance wiring, read-only after

    def _init_table_and_step(self) -> None:
        """Build self.state + self._step (subclass hook: the Pallas
        serving engine swaps in its bucketized table + kernel step).

        The serving step aliases the table in/out by default
        (GUBER_STEP_DONATE=0 opts out): clean-step cold columns pass
        through copy-free and row scatters update in place (see
        core/step.py › decide_batch_donated).  Measured on a real v5e
        (tools/tpu_session.py, 2026-07-31): donate 0.573 ms/step vs
        copy 209 ms at CAP 2^21 — non-donated scatters serialize on
        TPU — and donate also wins 6.3× on CPU (PERF.md §5)."""
        import os as _os

        self.state = shard_table(self.mesh, self.cap_local)
        self._step = make_sharded_step_packed(
            self.mesh,
            donate=_os.environ.get("GUBER_STEP_DONATE", "1") == "1")

    def sweep(self, now_ms: int) -> None:
        """Reclaim expired rows on every shard (elementwise on the
        sharded arrays — no collective).  The eviction analog of the
        reference's LRU + expired-entry handling (lrucache.go).

        The fused Pallas kernel (same semantics + live count in one
        streaming pass, validated bit-exact on v5e; ops/pallas_sweep.py)
        runs by default on TPU backends; GUBER_PALLAS_SWEEP=1/0 forces
        it on/off (off-TPU it would run in the slow interpret mode)."""
        import os

        use_pallas = os.environ.get(
            "GUBER_PALLAS_SWEEP",
            "1" if jax.default_backend() == "tpu" else "0") == "1"
        if use_pallas and self.cap_local % 1024 == 0:
            self.state, live = self._pallas_sweep(now_ms)
            self.live_rows = int(live)
        else:
            from ..core.table import occupancy, sweep_expired

            with XLA_EXEC_MU:
                self.state = sweep_expired(self.state, np.int64(now_ms))
                if self.auto_grow_limit:
                    self.live_rows = int(occupancy(self.state))
        self.sweep_count += 1
        # Proactive growth: open-addressing probe windows start
        # exhausting on unlucky keys well before the table is full
        # (~2% per insert at 60% load with 8 probes), so with auto-grow
        # enabled double capacity once LIVE occupancy crosses 60% on
        # the sweep tick — off the serving path, so request latency
        # never pays for the grow (reactive growth in check_* stays as
        # the backstop when traffic outruns the sweep interval).
        if (self.auto_grow_limit
                and self.cap_local * 2 <= self.auto_grow_limit
                and self.live_rows > 0.6 * self.cap_local * self.n):
            dropped = self.grow(self.cap_local * 2)
            if dropped:
                log.warning("proactive grow to %d/shard dropped %d "
                            "live rows", self.cap_local, dropped)

    def _pallas_sweep(self, now_ms: int):
        """shard_map'd fused sweep: per-shard Pallas pass + psum'd live
        count.  Interpret mode off-TPU (Mosaic kernels are TPU-only)."""
        if self._pallas_sweep_fn is None:
            from ..ops.pallas_sweep import sweep_expired_pallas

            interpret = jax.default_backend() != "tpu"

            def _one(state, now):
                st, live = sweep_expired_pallas(state, now,
                                                interpret=interpret)
                return st, lax.psum(live, SHARD_AXIS)

            # check_vma=False: pallas_call's out_shape carries no
            # varying-mesh-axes annotation
            self._pallas_sweep_fn = jax.jit(shard_map(
                _one, mesh=self.mesh, in_specs=(P(SHARD_AXIS), P()),
                out_specs=(P(SHARD_AXIS), P()), check_vma=False))
        with XLA_EXEC_MU:
            return self._pallas_sweep_fn(self.state,
                                         jnp.asarray(now_ms, jnp.int64))

    @staticmethod
    def _arrival_order(batch: RequestBatch) -> np.ndarray:
        """Request indices in arrival-time order (earliest requests
        take the earliest waves, so same-key requests split across
        waves apply in time order).  The common serving shape — a wave
        whose ``now`` column is already non-decreasing (one caller, or
        dispatcher-merged jobs queued in clock order) — skips the
        argsort: an O(n) monotonicity check replaces the O(n log n)
        sort on the per-wave host path."""
        now_col = np.asarray(batch.now)
        n = len(now_col)
        if n <= 1 or (now_col[1:] >= now_col[:-1]).all():
            return np.arange(n, dtype=np.int64)
        return np.argsort(now_col, kind="stable")

    def _build_waves(self, khash: np.ndarray, pending: np.ndarray):
        """Route ``pending`` request indices into device waves.

        Returns [(idx, slots, bw_w)]: original indices, block slots, and
        the wave's bucket size.  Stable sorts keep request order inside
        a shard (sequential parity for duplicate keys).  Waves split at
        the largest bucket per shard; each wave then rides the smallest
        bucket covering its own densest shard, so a coalesced burst
        takes one big launch and its overflow tail a small one — never
        a second nearly-empty big launch (see wave_buckets)."""
        shard = shard_of(khash[pending], self.n)
        order = np.argsort(shard, kind="stable")
        s_sorted = shard[order]
        starts = np.searchsorted(s_sorted, np.arange(self.n), "left")
        posin = np.arange(len(pending)) - starts[s_sorted]
        Bw = self.wave_buckets[-1]
        wave_id = posin // Bw
        waves = []
        for w in range(int(wave_id.max()) + 1 if len(pending) else 0):
            m = wave_id == w
            idx = pending[order[m]]
            wcnt = int(np.bincount(s_sorted[m], minlength=self.n).max())
            bw_w = next((b for b in self.wave_buckets if wcnt <= b),
                        self.wave_buckets[-1])
            slots = s_sorted[m].astype(np.int64) * bw_w + posin[m] % Bw
            waves.append((idx, slots, bw_w))
        return waves

    def _fill_packed(self, batch: RequestBatch, idx, slots, bw_w,
                     mslot=None):
        """Scatter a wave's requests straight into a LEASED pair of
        packed wire matrices (one [8, n·Bw] i64 + one [3, n·Bw] i32
        from ``wave_pool``): fuses the old glob-fill + pack_wave_host
        into a single set of writes, without the per-wave allocation
        the old path paid (at a fast device step — TPU: ~0.2 ms — the
        host-side copies and allocator churn ARE the serving ceiling).
        Returns (a64, a32, lease, mblk); the caller must
        ``lease.release()`` once the launch has consumed the buffers,
        on every path.  ``mslot`` (ISSUE 8, fused engines only) is the
        per-request mesh-GLOBAL slot column; it rides a plain -1-filled
        block array (``mblk``), not the lease — mesh waves are the
        GLOBAL minority, pooling them would tax every wave.
        Padding rows keep empty_batch semantics: zeros everywhere,
        eff_ms 1, valid false."""
        lease = self.wave_pool.lease(self.n * bw_w)
        a64, a32 = lease.a64, lease.a32
        a64[PACK64.index("eff_ms")] = 1
        a64[0][slots] = np.asarray(batch.key).view(np.int64)[idx]
        for i, f in enumerate(PACK64[1:], start=1):
            a64[i][slots] = np.asarray(getattr(batch, f))[idx]
        for i, f in enumerate(PACK32):
            a32[i][slots] = np.asarray(getattr(batch, f))[idx]
        mblk = None
        if mslot is not None:
            mblk = np.full(self.n * bw_w, -1, np.int32)
            mblk[slots] = np.asarray(mslot)[idx]
        return a64, a32, lease, mblk

    def launch_packed(self, batch: RequestBatch, khash: np.ndarray,
                      now_ms: int, mslot=None):
        """Pipeline phase 1 of check_packed: route and LAUNCH the waves
        without blocking on device results, so the dispatcher can
        overlap the next wave's host work with this one's device time.
        Returns an opaque token for ``sync_packed``.  State threads
        through the launches, so later launches are ordered after these
        device-side regardless of when anyone syncs.  ``mslot`` rides
        the token so the sync-side retry keeps the rows' lanes.

        Cold-tier rows (tiering.py) ride the wave invalid and their
        indices ride the token: the SYNC side re-dispatches them
        through check_packed under the engine lock — serving them here
        would let a promotion that lands between launch and sync read
        a row this lane already consumed."""
        tier = self.tier
        cold_idx = None
        if tier is not None:
            kh = np.asarray(khash)
            ov = np.asarray(batch.valid) & (kh != 0)
            cm = tier.resident_mask(kh) & ov
            if mslot is not None:
                cm &= np.asarray(mslot) < 0
            if cm.any():
                cold_idx = np.nonzero(cm)[0]
                batch = batch._replace(
                    valid=np.asarray(batch.valid) & ~cm)
        pending = self._arrival_order(batch)
        launched = []
        for idx, slots, bw_w in self._build_waves(khash, pending):
            a64, a32, lease, mblk = self._fill_packed(batch, idx, slots,
                                                      bw_w, mslot)
            try:
                # positional mblk only when a mesh lane exists: tests
                # and profilers wrap _launch_arrays with the classic
                # 3-arg signature
                packed, counters = (
                    self._launch_arrays(a64, a32, now_ms) if mblk is None
                    else self._launch_arrays(a64, a32, now_ms, mblk))
            finally:
                lease.release()  # the launch copied the host operands
            launched.append((idx, slots, packed, counters))
        return (batch, khash, now_ms, launched, mslot, cold_idx)

    def sync_packed(self, token, engine_lock=None) -> tuple:
        """Pipeline phase 2: block on the launched waves and assemble
        the response columns (same contract as check_packed).  Reading
        launched outputs needs no lock (state isn't touched); the
        table-full RETRY path re-enters check_packed, which mutates
        state, so it runs under ``engine_lock`` when one is given.  A
        retried row applies after any wave launched meanwhile —
        acceptable: erred rows never mutated state, retries are the
        table-full corner, and the device clamps per-key time
        monotonically."""
        batch, khash, now_ms, launched, mslot, cold_idx = token
        n = len(khash)
        status = np.zeros(n, np.int32)
        rem_o = np.zeros(n, np.int64)
        rst_o = np.zeros(n, np.int64)
        lim_o = np.zeros(n, np.int64)
        full = np.zeros(n, bool)
        err_idx: List[int] = []
        for idx, slots, packed, counters in launched:
            o_st, o_rem, o_rst, o_lim, o_err = self._finish_wave(
                packed, counters)
            status[idx] = o_st[slots]
            rem_o[idx] = o_rem[slots]
            rst_o[idx] = o_rst[slots]
            lim_o[idx] = o_lim[slots]
            werr = o_err[slots]
            if werr.any():
                err_idx.extend(idx[werr].tolist())
        if err_idx:
            import contextlib

            ei = np.asarray(sorted(err_idx))
            sub = type(batch)(*[np.asarray(c)[ei] for c in batch])
            msub = None if mslot is None else np.asarray(mslot)[ei]
            with (engine_lock if engine_lock is not None
                  else contextlib.nullcontext()):
                r_st, r_lim, r_rem, r_rst, r_full = self.check_packed(
                    sub, khash[ei], now_ms, mslot=msub)
            status[ei] = r_st
            lim_o[ei] = r_lim
            rem_o[ei] = r_rem
            rst_o[ei] = r_rst
            full[ei] = r_full
        if cold_idx is not None and len(cold_idx):
            import contextlib

            # cold-tier rows rode the waves invalid (see launch_packed):
            # re-dispatch just them through check_packed, which serves
            # from whichever tier the key is in NOW — exact even when a
            # promotion landed between our launch and this sync
            ci = np.asarray(cold_idx)
            sub = type(batch)(*[np.asarray(c)[ci] for c in batch])
            sub = sub._replace(valid=np.ones(len(ci), bool))
            msub = None if mslot is None else np.asarray(mslot)[ci]
            with (engine_lock if engine_lock is not None
                  else contextlib.nullcontext()):
                c_st, c_lim, c_rem, c_rst, c_full = self.check_packed(
                    sub, khash[ci], now_ms, mslot=msub)
            status[ci] = c_st
            lim_o[ci] = c_lim
            rem_o[ci] = c_rem
            rst_o[ci] = c_rst
            full[ci] = c_full
        return status, lim_o, rem_o, rst_o, full

    def warmup(self, now_ms: int = 1) -> None:
        """Pre-compile every wave-bucket step program (all-invalid rows:
        no state change).  Daemons call this before serving so a first
        coalesced burst never eats a cold compile inside an RPC."""
        for bw in self.wave_buckets:
            self._run_wave(empty_batch(self.n * bw), now_ms)

    def _launch_arrays(self, a64: np.ndarray, a32: np.ndarray,
                       now_ms: int, mblk=None):
        """Dispatch one packed wave without blocking on its results: 2
        uploads + the step (async on the device stream; state threads
        through, so later launches are ordered after this one
        device-side).  ``mblk`` (mesh-GLOBAL slot block) is a fused-
        engine operand — the classic step has no mesh lane and ignores
        it (only fused engines are ever handed mesh-routed rows).

        On a 1-shard mesh the packed matrices go to the jitted call as
        raw numpy: explicit device_put with a NamedSharding pays
        ~0.5 ms of shard_args machinery per call (measured, CPU) for a
        placement that is identical anyway.  Multi-shard meshes keep
        the explicit sharded put — there it is what makes each device
        receive 1/n of the bytes instead of a full replica."""
        with XLA_EXEC_MU:
            # process-wide execute gate (mesh.py): cross-ENGINE
            # concurrent executions wedge this image's XLA:CPU; the
            # per-instance engine lock can't see other instances
            if self.n > 1:
                a64 = jax.device_put(a64, self._mat_sharding)
                a32 = jax.device_put(a32, self._mat_sharding)
            self.state, packed, counters = self._step(
                self.state, a64, a32, np.int64(now_ms))
        return packed, counters

    def _launch_wave(self, glob: RequestBatch, now_ms: int):
        """RequestBatch form of _launch_arrays (warmup, row programs)."""
        return self._launch_arrays(*pack_wave_host(glob), now_ms)

    def _finish_wave(self, packed, counters):
        """Block on a launched wave's outputs (1 download) and fold its
        counters.  Returns (status, remaining, reset, limit, table_full)
        host arrays in [n·Bw] block order."""
        out = np.asarray(packed)
        self.over_count += int(counters[0])
        self.insert_count += int(counters[1])
        return out[0], out[1], out[2], out[3], out[4] != 0

    def _run_wave(self, glob: RequestBatch, now_ms: int):
        """One device launch over the packed wire layout: 2 uploads, the
        step, 1 download.  Returns (status, remaining, reset, limit,
        table_full) host arrays in [n·B] block order."""
        return self._finish_wave(*self._launch_wave(glob, now_ms))

    # ---- fused wire lane (ops/_native.cpp › pack_wire_wave) ------------

    def prepack_wire(self, data: bytes, now_ms: int):
        """Fused C++ wire ingest: one pass from request wire bytes to a
        LEASED pair of packed wave-upload matrices — parse, validate,
        clamp (bit-identical to pack_columns), key-hash (mixed,
        zero-remapped) and fill, with zero intermediate numpy columns.

        Single-shard meshes only (block order == request order, so the
        wave needs no shard routing or slot scatter); multi-shard and
        anything the C++ lane can't model (pb2 framing, Gregorian rows,
        n over the largest bucket) returns None and the caller takes
        the classic parse → pack_columns path.

        Returns a PrepackedWave whose lease the caller OWNS: every
        return path must end in check_prepacked (which releases it) or
        an explicit ``pre.lease.release()``."""
        if self.n != 1 or _wire_native is None:
            return None
        cnt = _wire_native.count_req_items(data)
        if not cnt:
            return None
        bw = next((b for b in self.wave_buckets if cnt <= b), None)
        if bw is None:
            return None  # oversize: classic path splits into waves
        lease = self.wave_pool.lease(bw)
        res = _wire_native.pack_wire_wave(data, now_ms, lease.a64,
                                          lease.a32)
        if res is None:
            lease.release()
            return None
        n, khash, khash_raw, behavior_or, tlv_off, tlv_len = res
        return PrepackedWave(lease, n, khash, khash_raw, behavior_or,
                             tlv_off, tlv_len)

    def check_prepacked(self, pre: "PrepackedWave", now_ms: int) -> tuple:
        """Launch + resolve a prepacked wave.  Returns the check_packed
        5-tuple (status i32, limit, remaining, reset, table_full) over
        rows [0, pre.n) — block order IS request order on the 1-shard
        mesh, so no slot gather happens.  Releases the lease on every
        path.  Table-full rows ride the classic sweep-retry path (the
        erred rows never mutated state, so re-running just them through
        check_packed is the same recovery check_batch performs)."""
        n = pre.n
        lease = pre.lease
        # cold-tier rows (tiering.py) must not reach the device insert:
        # zero their valid flag in the leased matrices, then route them
        # through the same check_packed rebuild the table-full retry
        # uses (check_packed serves them from the cold tier)
        tier = self.tier
        cold_i = None
        if tier is not None:
            kh_n = np.asarray(pre.khash[:n], np.uint64)
            cm = (tier.resident_mask(kh_n) & (kh_n != 0)
                  & (lease.a32[2][:n] != 0))
            if cm.any():
                cold_i = np.nonzero(cm)[0]
                lease.a32[2][cold_i] = 0
        try:
            # retry needs the request columns; snapshot them from the
            # lease ONLY if the cheap error scan demands it (below)
            launched = self._launch_arrays(lease.a64, lease.a32, now_ms)
            o_st, o_rem, o_rst, o_lim, o_err = self._finish_wave(
                *launched)
            err = o_err[:n]
            if not err.any() and cold_i is None:
                lease.release()
                lease = None
                return (o_st[:n].astype(np.int32), o_lim[:n], o_rem[:n],
                        o_rst[:n], err)
            # rare path: probe windows exhausted (or cold-tier rows) —
            # rebuild those rows as a RequestBatch from the still-leased
            # matrices and push them through check_packed (sweep-retry/
            # auto-grow/cold serve live there; non-erred rows already
            # applied, so only this subset re-runs)
            ei = np.nonzero(err)[0]
            if cold_i is not None:
                lease.a32[2][cold_i] = 1  # restore valid for the rebuild
                ei = np.unique(np.concatenate([ei, cold_i]))
            a64, a32 = lease.a64, lease.a32
            sub = RequestBatch(
                key=a64[0][ei].view(np.uint64),
                hits=a64[1][ei].copy(), limit=a64[2][ei].copy(),
                duration=a64[3][ei].copy(), eff_ms=a64[4][ei].copy(),
                greg_end=a64[5][ei].copy(),
                behavior=a32[0][ei].copy(), algorithm=a32[1][ei].copy(),
                burst=a64[6][ei].copy(), valid=a32[2][ei] != 0,
                now=a64[7][ei].copy())
            khash_sub = pre.khash[ei]
            lease.release()
            lease = None
            status = o_st[:n].astype(np.int32)
            lim_o = o_lim[:n].copy()
            rem_o = o_rem[:n].copy()
            rst_o = o_rst[:n].copy()
            full = np.zeros(n, bool)
            if err.any():  # cold-only subsets skip the expiry sweep
                self.sweep(now_ms)
            r_st, r_lim, r_rem, r_rst, r_full = self.check_packed(
                sub, khash_sub, now_ms)
            status[ei] = r_st
            lim_o[ei] = r_lim
            rem_o[ei] = r_rem
            rst_o[ei] = r_rst
            full[ei] = r_full
            return status, lim_o, rem_o, rst_o, full
        finally:
            if lease is not None:
                lease.release()

    def check_batch(self, reqs: Sequence[RateLimitRequest], now_ms: int
                    ) -> List[RateLimitResponse]:
        """Object-lane entry: pack, run the columnar path, assemble
        RateLimitResponse objects.  One wave/retry/auto-grow code path
        for both lanes (check_packed is the single implementation)."""
        from ..hashing import hash_request_keys

        khash = hash_request_keys([r.name for r in reqs],
                                  [r.unique_key for r in reqs])
        batch, errs = pack_requests(reqs, now_ms, size=len(reqs),
                                    key_hashes=khash)
        cols = self.check_packed(batch, khash, now_ms)
        return responses_from_columns(cols, errs)

    def check_packed(self, batch: RequestBatch, khash: np.ndarray,
                     now_ms: int, mslot=None) -> tuple:
        """Columnar twin of ``check_batch``: full-length numpy columns in,
        response columns out — no per-request Python objects (the C++
        wire-ingest lane).  Returns (status i32[n], limit i64[n],
        remaining i64[n], reset_time i64[n], table_full bool[n]).

        Invalid rows (batch.valid False) come back zeroed; the caller
        owns their error strings.  Same wave routing, duplicate-order,
        and sweep-retry semantics as check_batch.  ``mslot`` (ISSUE 8):
        per-request mesh-GLOBAL replica slot, -1 for sharded rows —
        only fused engines receive it (instance.py gates on
        ``engine.mesh_bound``).
        """
        n = len(khash)
        status = np.zeros(n, np.int32)
        rem_o = np.zeros(n, np.int64)
        rst_o = np.zeros(n, np.int64)
        lim_o = np.zeros(n, np.int64)
        full = np.zeros(n, bool)
        # tiered store (tiering.py): cold-resident rows must NOT hit
        # the device table (a non-full table would insert them fresh —
        # a state fork); ride the wave invalid and serve from the cold
        # tier in the resolve below.  Mesh-pinned rows (mslot >= 0) are
        # never cold: the pin seed pops the cold copy.
        tier = self.tier
        cold_mask = None
        orig_valid = None
        if tier is not None:
            kh = np.asarray(khash)
            orig_valid = np.asarray(batch.valid) & (kh != 0)
            cold_mask = tier.resident_mask(kh) & orig_valid
            if mslot is not None:
                cold_mask &= np.asarray(mslot) < 0
            if cold_mask.any():
                batch = batch._replace(
                    valid=np.asarray(batch.valid) & ~cold_mask)
        # earliest requests take the earliest waves: same-key requests
        # split across waves then apply in arrival-time order (within a
        # wave the device's (row, now) sort handles it)
        pending = self._arrival_order(batch)
        retried = False
        while len(pending):
            err_idx: List[int] = []
            for idx, slots, bw_w in self._build_waves(khash, pending):
                a64, a32, lease, mblk = self._fill_packed(
                    batch, idx, slots, bw_w, mslot)
                try:
                    # see launch_packed: 3-arg call when no mesh lane
                    launched = (
                        self._launch_arrays(a64, a32, now_ms)
                        if mblk is None
                        else self._launch_arrays(a64, a32, now_ms, mblk))
                finally:
                    lease.release()  # launch copied the host operands
                o_st, o_rem, o_rst, o_lim, o_err = self._finish_wave(
                    *launched)
                status[idx] = o_st[slots]
                rem_o[idx] = o_rem[slots]
                rst_o[idx] = o_rst[slots]
                lim_o[idx] = o_lim[slots]
                werr = o_err[slots]
                if werr.any():
                    err_idx.extend(idx[werr].tolist())
            if err_idx and not retried:
                # probe windows clogged with expired rows: sweep once and
                # retry those requests (check_batch does the same)
                retried = True
                self.sweep(now_ms)
                pending = np.asarray(sorted(err_idx))
            elif err_idx and self._try_auto_grow([False]):
                pending = np.asarray(sorted(err_idx))
            else:
                full[err_idx] = True
                for i in err_idx:
                    status[i] = 0
                    rem_o[i] = 0
                    rst_o[i] = 0
                    lim_o[i] = 0
                pending = np.empty(0, np.int64)
        if tier is not None:
            # cold lane: pre-masked cold-resident rows plus residual
            # table-full rows (brand-new keys, device table saturated —
            # the tier turns table-full into find-or-create on host)
            return tier.resolve(self, batch, khash, now_ms,
                                (status, lim_o, rem_o, rst_o, full),
                                cold_mask, orig_valid, mslot=mslot)
        return status, lim_o, rem_o, rst_o, full

    def _try_auto_grow(self, grew: list) -> bool:
        """Grow 2× (once per wave) if under auto_grow_limit.  Returns
        True when the caller should retry at the larger capacity."""
        if not self.auto_grow_limit \
                or self.cap_local * 2 > self.auto_grow_limit:
            return False
        if not grew[0]:
            dropped = self.grow(self.cap_local * 2)
            if dropped:
                # a dropped row is a silent counter reset — allowed by
                # the LRU-eviction contract, never allowed to be quiet
                log.warning("auto-grow to %d/shard dropped %d live rows "
                            "(probe-window exhaustion)",
                            self.cap_local, dropped)
            grew[0] = True
        return True

    def grow(self, new_cap_per_shard: int) -> int:
        """Re-place all live rows into a [new_cap_per_shard] table on
        device (see make_grow).  Returns the dropped-row count (non-zero
        only when shrinking into high occupancy).  Subsequent step/row
        programs recompile automatically for the new shape."""
        if new_cap_per_shard & (new_cap_per_shard - 1) \
                or new_cap_per_shard <= 0:
            raise ValueError(
                f"capacity must be a power of two, got {new_cap_per_shard}")
        fn = self._grow_fns.get(new_cap_per_shard)
        if fn is None:
            fn = make_grow(self.mesh, new_cap_per_shard)
            self._grow_fns[new_cap_per_shard] = fn
        with XLA_EXEC_MU:
            self.state, dropped = fn(self.state)
        self.cap_local = new_cap_per_shard
        self.dropped_rows += int(dropped)
        return int(dropped)

    # ---- row-level access (GLOBAL replication + Store hooks) -----------

    def _route_waves(self, khash: np.ndarray):
        """Yield (indices, block_slots) waves: each wave maps ≤B keys per
        shard into the [n·B] block layout."""
        shard = shard_of(khash, self.n)
        pending = list(range(len(khash)))
        while pending:
            fill = [0] * self.n
            wave, rest, slots = [], [], []
            for i in pending:
                s = int(shard[i])
                if fill[s] < self.B:
                    slots.append(s * self.B + fill[s])
                    fill[s] += 1
                    wave.append(i)
                else:
                    rest.append(i)
            yield wave, slots
            pending = rest

    def gather_rows(self, khash: np.ndarray) -> tuple[np.ndarray, dict]:
        """(found mask, value-column dict) for the given key hashes."""
        if self._gather is None:
            self._gather = make_gather_rows(self.mesh)
        m = len(khash)
        found = np.zeros(m, bool)
        out = {f: np.zeros(m, np.asarray(getattr(self.state, f)).dtype)
               for f in VALUE_COLS}
        for wave, slots in self._route_waves(khash):
            keys = np.zeros(self.n * self.B, np.uint64)
            keys[slots] = khash[wave]
            with XLA_EXEC_MU:
                f, cols = self._gather(
                    self.state,
                    jax.device_put(keys, self._batch_sharding))
            f = np.asarray(f)
            found[wave] = f[slots]
            for name, col in zip(VALUE_COLS, cols):
                out[name][wave] = np.asarray(col)[slots]
        return found, out

    def upsert_rows(self, khash: np.ndarray, cols: dict) -> int:
        """Find-or-insert rows and overwrite their state; returns the
        number of rows placed (others dropped: shard probe window full)."""
        if self._upsert is None:
            self._upsert = make_upsert_rows(self.mesh)
        placed_total = 0
        for wave, slots in self._route_waves(khash):
            keys = np.zeros(self.n * self.B, np.uint64)
            keys[slots] = khash[wave]
            block_cols = []
            for f in VALUE_COLS:
                dt = np.asarray(cols[f]).dtype
                blk = np.zeros(self.n * self.B, dt)
                blk[slots] = cols[f][wave]
                block_cols.append(jax.device_put(blk, self._batch_sharding))
            with XLA_EXEC_MU:
                self.state, placed = self._upsert(
                    self.state,
                    jax.device_put(keys, self._batch_sharding),
                    tuple(block_cols))
            placed_total += int(np.asarray(placed)[slots].sum())
        return placed_total

    def remove_rows(self, khash: np.ndarray) -> int:
        """Delete rows by key hash (Cache.Remove analog); returns the
        number of rows actually removed."""
        if self._remove is None:
            self._remove = make_remove_rows(self.mesh)
        removed = 0
        for wave, slots in self._route_waves(khash):
            keys = np.zeros(self.n * self.B, np.uint64)
            keys[slots] = khash[wave]
            with XLA_EXEC_MU:
                self.state, found = self._remove(
                    self.state,
                    jax.device_put(keys, self._batch_sharding))
            removed += int(np.asarray(found)[slots].sum())
        return removed

    def occupancy(self) -> int:
        """Live (non-empty) rows right now — health/metrics surface."""
        from ..core.table import occupancy

        # under XLA_EXEC_MU: an eager device reduction; health checks
        # and the memory-ledger probes call this from their own threads
        # while other in-process engines serve (see mesh.py)
        with XLA_EXEC_MU:
            return int(occupancy(self.state))

    def occupancy_nowait(self) -> int | None:
        """Non-blocking occupancy for tick-cadence samplers (the memory
        ledger): None when the device gate is contended.  A sampler
        holding the engine lock must never WAIT on XLA_EXEC_MU — in
        multi-engine processes that convoys every serving wave behind
        another engine's in-flight program; the caller reuses its last
        sample instead."""
        if not XLA_EXEC_MU.acquire(blocking=False):
            return None
        try:
            from ..core.table import occupancy

            return int(occupancy(self.state))
        finally:
            XLA_EXEC_MU.release()

    def probe_occupant_keys(self, kh: int) -> np.ndarray:
        """The resident key hashes in ``kh``'s probe window (up to
        PROBES entries, 0 = free slot) — the tier controller's eviction
        candidate read: any of these keys, once demoted, frees a slot
        ``kh`` itself can take (same probe formula as the device kernel,
        core/step.py › _probe_slots)."""
        from ..core.step import PROBES

        k = np.uint64(kh)
        stride = (k >> np.uint64(17)) | np.uint64(1)
        local = ((k + np.arange(PROBES, dtype=np.uint64) * stride)
                 & np.uint64(self.cap_local - 1))
        shard = int(shard_of(np.array([k], np.uint64), self.n)[0])
        slots = (shard * self.cap_local + local).astype(np.int64)
        with XLA_EXEC_MU:
            keys = np.asarray(
                jnp.take(self.state.key, jnp.asarray(slots), axis=0))
        return keys.astype(np.uint64)

    def each(self):
        """Iterate live rows as store.CacheItem objects (Cache.Each
        analog) — a host-side snapshot walk, for admin/debug tooling."""
        from ..store import items_from_arrays

        yield from items_from_arrays(self.snapshot())

    # ---- checkpoint/resume (store.py › Loader array fast path) ---------

    def snapshot(self) -> dict:
        """Device table → host column dict of live rows (Loader.save
        input).  The analog of the reference's cache.Each() drain at
        shutdown (store.go › Loader — reconstructed)."""
        from ..store import table_to_arrays

        return table_to_arrays(self.state)

    def restore(self, arrays: dict) -> int:
        """Insert snapshot rows into the (fresh) sharded table.

        Host-side cold path: routes each row to its owner shard, places
        it at its first free probe slot (same probe sequence as the
        device kernel), then uploads the table once.  Returns rows
        restored; rows that don't fit (capacity shrank) are dropped with
        a count, mirroring the reference's best-effort Loader.Load.
        """
        from ..core.step import PROBES

        host = {f: np.asarray(getattr(self.state, f)).copy()
                for f in self.state._fields}
        cap = self.cap_local
        keys = arrays["key"].astype(np.uint64)
        shard = shard_of(keys, self.n)
        stride = (keys >> np.uint64(17)) | np.uint64(1)
        placed = 0
        unplaced: List[int] = []
        for i in range(len(keys)):
            base = int(shard[i]) * cap
            k = keys[i]
            for p in range(PROBES):
                slot = base + int((k + np.uint64(p) * stride[i])
                                  & np.uint64(cap - 1))
                if host["key"][slot] == 0 or host["key"][slot] == k:
                    for f in host:
                        if f != "key":
                            host[f][slot] = arrays[f][i]
                    host["key"][slot] = k
                    placed += 1
                    break
            else:
                unplaced.append(i)
        if unplaced and self.tier is not None:
            # tiered restore: rows the device table can't hold land in
            # the cold tier instead of being dropped — the snapshot
            # round-trip keeps every row in exactly one tier
            placed += self.tier.adopt_rows(arrays, unplaced)
        sh = table_sharding(self.mesh)
        from ..core.table import TableState, init_table

        self.state = TableState(**{
            f: jax.device_put(v, sh) for f, v in host.items()})
        # device_put of an aligned host column is zero-copy on this
        # image's XLA:CPU without pinning the numpy owner — once `host`
        # dies the allocator reuses the table's backing memory and live
        # rows turn into heap garbage (state lost across restart, and
        # worse: ~1.6k phantom rows evicting real ones).  Pin the
        # columns for the engine's lifetime; the donated step keeps
        # writing the state into these same buffers, so the cost is one
        # table copy (~cap×9×8 bytes), not a leak per wave.
        self._restore_host_pin = host
        return placed

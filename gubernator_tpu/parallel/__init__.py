"""Multi-chip parallelism: key-space sharding over a device mesh and
GLOBAL-behavior replication via ICI collectives (SURVEY.md §2.3).

Replaces the reference's peer fan-out (hash.go peer picking +
peer_client.go gRPC forwarding + global.go broadcast goroutines) with
sharded tables under shard_map and psum delta reconciliation — inside a
pod there are no "peers", just mesh axes.
"""
from .mesh import make_mesh, shard_table, table_sharding  # noqa: F401
from .sharded import ShardedEngine, make_sharded_step  # noqa: F401
from .hotset import HotSetEngine  # noqa: F401

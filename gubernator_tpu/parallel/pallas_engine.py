"""Pallas serving engine: the hand-scheduled decision kernel as a
deployable step mode (SURVEY §2.2; VERDICT r3 item 1's escalation —
"make the Pallas kernel the serving mode at large CAP: it owns its
scatters").

``PallasServingEngine`` is a drop-in ``ShardedEngine`` whose per-shard
table is the kernel's bucketized AoS layout (``[rows, 32] int32``,
8-slot buckets — ops/pallas_step.py) instead of SoA columns, and whose
step is the Mosaic kernel under ``shard_map``.  Everything above the
step — wave routing, dispatcher coalescing, the wire lanes, metrics —
is inherited unchanged; the engine protocol (gather/upsert/remove
rows, snapshot/restore, sweep) is re-implemented on the bucket layout
so V1Instance features (Store read/write-through, stateful handover,
checkpoint/resume) keep working.

Domain: the kernel serves TOKEN and LEAKY rows whose counters are
< 2^30 and (leaky) eff < 2^31.  Out-of-domain rows are scoped PER ROW
(``pallas_value_domain_mask``): they are excluded from the device step
and surfaced as unservable (``table_full`` True) — never silently
truncated into wrong decisions, and never allowed to fail the other
callers the dispatcher coalesced into the same wave.  The gate covers
both serving paths (check_packed and the pipelined launch/sync pair).
(Per-key time monotonicity is guaranteed upstream: the engine's wave
builder sorts pending requests by arrival time.)

Not supported in this mode (documented trade-offs, not gaps a caller
can trip silently): on-device auto-grow (bucket-full rows err and
surface as table_full exactly like a full SoA probe window; callers
see the same retry semantics), and the fused SoA Pallas sweep (this
mode's sweep is a plain vectorized expire-clear over rows).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.batch import RequestBatch
from ..ops import pallas_step as ps
from .mesh import SHARD_AXIS
from .sharded import PACK32, PACK64, ShardedEngine

#: SoA column → (word extractor) mapping used by snapshot/gather.
_I64_PAIRS = {"duration": (ps.W_DLO, ps.W_DHI),
              "eff_ms": (ps.W_ELO, ps.W_EHI),
              "t_ms": (ps.W_TLO, ps.W_THI),
              "expire_at": (ps.W_XLO, ps.W_XHI)}


def _join_u64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return ((hi.astype(np.uint32).astype(np.uint64) << np.uint64(32))
            | lo.astype(np.uint32).astype(np.uint64))


def _join_i64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return _join_u64(hi, lo).astype(np.int64)


def _split_np(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    u = x.astype(np.uint64)
    return ((u >> np.uint64(32)).astype(np.uint32).astype(np.int32),
            u.astype(np.uint32).astype(np.int32))


def _rows_to_columns(rows: np.ndarray) -> dict:
    """[N, WORDS] int32 bucket rows → SoA column dict (live rows only),
    in the store/Loader format (store.py › table_to_arrays).

    ``burst`` is emitted as ``limit``: the kernel does not store burst
    because oracle.apply_leaky overwrites item.burst from the request
    before every read — the column is dead state everywhere except a
    snapshot round-trip, and limit is its every-step value for token
    rows (leaky rows re-adopt the request burst on first touch).
    """
    key = _join_u64(rows[:, ps.W_KHI], rows[:, ps.W_KLO])
    live = key != 0
    r = rows[live]
    key = key[live]
    alg = r[:, ps.W_ALG].astype(np.int64)
    status = r[:, ps.W_STATUS].astype(np.int64)
    limit = r[:, ps.W_LIMIT].astype(np.int64)
    remaining = np.where(
        alg == 1,
        _join_i64(r[:, ps.W_TDHI], r[:, ps.W_TDLO]),
        r[:, ps.W_REM].astype(np.int64))
    out = {"key": key,
           "meta": (alg | ((status & 1) << 1)).astype(np.int32),
           "limit": limit, "burst": limit, "remaining": remaining}
    for name, (wlo, whi) in _I64_PAIRS.items():
        out[name] = _join_i64(r[:, whi], r[:, wlo])
    return out


def _columns_to_row_words(arrays: dict, i: int) -> np.ndarray | None:
    """One snapshot row → 32 int32 words, or None if the row is outside
    the kernel domain (counters >= 2^30 / leaky eff >= 2^31) — dropped
    with a count by the caller, mirroring best-effort Loader.Load."""
    meta = int(arrays["meta"][i])
    alg = meta & 1
    limit = int(arrays["limit"][i])
    rem = int(arrays["remaining"][i])
    eff = int(arrays["eff_ms"][i])
    if limit >= ps.VALUE_BOUND:
        return None
    if alg == 1 and not (1 <= eff < ps.EFF_BOUND):
        return None
    if alg == 0 and rem >= ps.VALUE_BOUND:
        return None
    w = np.zeros(ps.WORDS, np.int32)
    khi, klo = _split_np(np.asarray([arrays["key"][i]], np.uint64))
    w[ps.W_KLO], w[ps.W_KHI] = klo[0], khi[0]
    w[ps.W_STATUS] = (meta >> 1) & 1
    w[ps.W_LIMIT] = limit
    w[ps.W_ALG] = alg
    if alg == 1:
        tdhi, tdlo = _split_np(np.asarray([rem], np.int64))
        w[ps.W_TDLO], w[ps.W_TDHI] = tdlo[0], tdhi[0]
    else:
        w[ps.W_REM] = rem
    for name, (wlo, whi) in _I64_PAIRS.items():
        hi, lo = _split_np(np.asarray([int(arrays[name][i])], np.int64))
        w[wlo], w[whi] = lo[0], hi[0]
    return w


def make_pallas_step_packed(mesh, interpret: bool = False):
    """shard_map twin of make_sharded_step_packed over the kernel:
    (rows, a64, a32, now) → (rows, [5,B] i64 outputs, counters).  The
    table is always donated — the kernel owns its scatters in-place."""
    S = SHARD_AXIS

    def _step(rows, a64, a32, now):
        batch = RequestBatch(
            key=lax.bitcast_convert_type(a64[0], jnp.uint64),
            hits=a64[1], limit=a64[2], duration=a64[3], eff_ms=a64[4],
            greg_end=a64[5], burst=a64[6], now=a64[7],
            behavior=a32[0], algorithm=a32[1], valid=a32[2] != 0)
        tbl, out = ps.decide_batch_pallas_impl(
            ps.PallasTable(rows=rows), batch, now, interpret=interpret)
        packed = jnp.stack([
            out.status.astype(jnp.int64), out.remaining, out.reset_time,
            out.limit, out.err.astype(jnp.int64)])
        over = lax.psum(out.over_count, S)
        ins = lax.psum(out.insert_count, S)
        return tbl.rows, packed, (over, ins)

    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(P(S, None), P(None, S), P(None, S), P()),
        out_specs=(P(S, None), P(None, S), P()),
        check_vma=False)  # pallas_call out_shape carries no vma
    return jax.jit(sharded, donate_argnums=(0,))


class PallasServingEngine(ShardedEngine):
    """ShardedEngine over the kernel's bucketized table (module doc)."""

    def _init_table_and_step(self) -> None:
        if self.cap_local < ps.SLOTS or (self.cap_local
                                         & (self.cap_local - 1)):
            raise ValueError("rows per shard must be a power of two "
                             f">= {ps.SLOTS}")
        sh = NamedSharding(self.mesh, P(SHARD_AXIS, None))
        self.state = jax.device_put(
            jnp.zeros((self.n * self.cap_local, ps.WORDS), jnp.int32),
            sh)
        # interpret everywhere the Mosaic kernel can't compile natively
        # (same gate as sharded.py's fused sweep)
        self._interpret = jax.default_backend() != "tpu"
        self._step = make_pallas_step_packed(self.mesh,
                                             interpret=self._interpret)
        self._rows_sharding = sh

    # ---- serving -------------------------------------------------------

    def _mask_out_of_domain(self, batch):
        """Invalidate rows outside the kernel's value domain; returns
        (masked batch, ood index array or None)."""
        mask = ps.pallas_value_domain_mask(batch)
        v = np.asarray(batch.valid)
        ood = v & ~mask
        if not ood.any():
            return batch, None
        return (batch._replace(valid=jnp.asarray(v & mask)),
                np.nonzero(ood)[0])

    @staticmethod
    def _merge_ood(cols, ood):
        """Out-of-domain rows come back as unservable (table_full) with
        zeroed outputs — scoped to the offending rows, the same shape a
        full probe window presents."""
        if ood is None:
            return cols
        st, lim, rem, rst, full = cols
        full = np.array(full, copy=True)
        full[ood] = True
        return st, lim, rem, rst, full

    def check_packed(self, batch, khash, now_ms: int) -> tuple:
        batch, ood = self._mask_out_of_domain(batch)
        return self._merge_ood(
            super().check_packed(batch, khash, now_ms), ood)

    def launch_packed(self, batch, khash, now_ms: int):
        # the pipelined dispatcher path calls launch/sync directly —
        # the domain gate must cover it too
        batch, ood = self._mask_out_of_domain(batch)
        return (super().launch_packed(batch, khash, now_ms), ood)

    def sync_packed(self, token, engine_lock=None) -> tuple:
        inner, ood = token
        return self._merge_ood(
            super().sync_packed(inner, engine_lock=engine_lock), ood)

    def _try_auto_grow(self, grew: list) -> bool:
        return False  # no on-device grow for the bucket layout (doc)

    def grow(self, new_cap_per_shard: int) -> int:
        raise NotImplementedError(
            "pallas serving mode has no on-device grow; size rows up "
            "front (bucket-full rows err as table_full)")

    # ---- sweep ---------------------------------------------------------

    def sweep(self, now_ms: int) -> None:
        """Expire-clear over bucket rows: zero every slot whose
        expire_at <= now (whole row, so leaky td state can't leak into
        a future occupant).  Elementwise per shard — no collective."""
        if not hasattr(self, "_sweep_fn"):
            S = SHARD_AXIS

            def _one(rows, now):
                exp = (rows[:, ps.W_XHI].astype(jnp.int64) << 32) | (
                    rows[:, ps.W_XLO].astype(jnp.int64)
                    & jnp.int64(0xFFFFFFFF))
                live = ((rows[:, ps.W_KLO] != 0)
                        | (rows[:, ps.W_KHI] != 0))
                expired = live & (now >= exp)
                rows = jnp.where(expired[:, None], jnp.int32(0), rows)
                n_live = lax.psum((live & ~expired).sum(dtype=jnp.int64),
                                  S)
                return rows, n_live

            self._sweep_fn = jax.jit(shard_map(
                _one, mesh=self.mesh, in_specs=(P(S, None), P()),
                out_specs=(P(S, None), P()), check_vma=False),
                donate_argnums=(0,))
        self.state, live = self._sweep_fn(
            self.state, jnp.asarray(now_ms, jnp.int64))
        self.live_rows = int(live)
        self.sweep_count += 1

    # ---- row ops (bucket-level, cold path) -----------------------------

    def _bucket_indices(self, khash: np.ndarray) -> np.ndarray:
        """[m, SLOTS] global row indices of each key's bucket."""
        from ..hashing import shard_of

        nb = self.cap_local // ps.SLOTS
        shard = shard_of(khash, self.n).astype(np.int64)
        bucket = (khash & np.uint64(nb - 1)).astype(np.int64)
        base = shard * self.cap_local + bucket * ps.SLOTS
        return base[:, None] + np.arange(ps.SLOTS)[None, :]

    def _fetch_buckets(self, idx: np.ndarray) -> np.ndarray:
        """Gather [m, SLOTS, WORDS] bucket copies to host."""
        take = jnp.asarray(idx.reshape(-1))
        # .copy(): np.asarray of a jax array is a read-only view and
        # the callers mutate these buckets in place
        return np.asarray(jnp.take(self.state, take, axis=0)).reshape(
            idx.shape[0], ps.SLOTS, ps.WORDS).copy()

    def _write_buckets(self, idx: np.ndarray, rows: np.ndarray) -> None:
        flat_idx = jnp.asarray(idx.reshape(-1))
        flat_rows = jnp.asarray(rows.reshape(-1, ps.WORDS))
        # duplicate buckets in one call carry identical content (the
        # caller mutates a shared host copy per bucket), so last-write
        # equivalence holds even without a uniqueness promise
        if not hasattr(self, "_write_fn"):
            # cached: a fresh lambda per call would retrace+recompile
            # the scatter on every store write-through
            self._write_fn = jax.jit(lambda s, i, r: s.at[i].set(r),
                                     donate_argnums=(0,))
        self.state = self._write_fn(self.state, flat_idx, flat_rows)

    def gather_rows(self, khash: np.ndarray) -> tuple[np.ndarray, dict]:
        m = len(khash)
        found = np.zeros(m, bool)
        cols = {f: np.zeros(m, np.int64) for f in
                ("meta", "limit", "duration", "eff_ms", "burst",
                 "remaining", "t_ms", "expire_at")}
        cols["meta"] = cols["meta"].astype(np.int32)
        if m == 0:
            return found, cols
        idx = self._bucket_indices(khash)
        buckets = self._fetch_buckets(idx)
        khi, klo = _split_np(khash)
        for i in range(m):
            b = buckets[i]
            hit = np.nonzero((b[:, ps.W_KLO] == klo[i])
                             & (b[:, ps.W_KHI] == khi[i]))[0]
            if not hit.size:
                continue
            found[i] = True
            cvt = _rows_to_columns(b[hit[:1]])
            for f in cols:
                cols[f][i] = cvt[f][0]
        return found, cols

    def upsert_rows(self, khash: np.ndarray, cols: dict) -> int:
        if len(khash) == 0:
            return 0
        arrays = dict(cols)
        arrays["key"] = khash.astype(np.uint64)
        idx = self._bucket_indices(khash)
        # ONE batched device fetch, then one shared host copy per
        # distinct bucket so multiple keys upserted into the same
        # bucket see each other's claims (a per-key fetch would cost a
        # blocking device round trip per bucket)
        all_buckets = self._fetch_buckets(idx)
        bucket_cache: dict = {}
        placed = 0
        khi, klo = _split_np(khash)
        for i in range(len(khash)):
            key0 = int(idx[i, 0])
            if key0 not in bucket_cache:
                bucket_cache[key0] = all_buckets[i]
            b = bucket_cache[key0]
            w = _columns_to_row_words(arrays, i)
            if w is None:
                self.dropped_rows += 1
                continue
            hit = np.nonzero((b[:, ps.W_KLO] == klo[i])
                             & (b[:, ps.W_KHI] == khi[i]))[0]
            if hit.size:
                slot = hit[0]
            else:
                empty = np.nonzero((b[:, ps.W_KLO] == 0)
                                   & (b[:, ps.W_KHI] == 0))[0]
                if not empty.size:
                    self.dropped_rows += 1
                    continue
                slot = empty[0]
            b[slot] = w
            placed += 1
        if bucket_cache:
            bases = np.asarray(sorted(bucket_cache), np.int64)
            rows = np.stack([bucket_cache[int(k)] for k in bases])
            self._write_buckets(
                bases[:, None] + np.arange(ps.SLOTS)[None, :], rows)
        return placed

    def remove_rows(self, khash: np.ndarray) -> int:
        if len(khash) == 0:
            return 0
        idx = self._bucket_indices(khash)
        buckets = self._fetch_buckets(idx)
        khi, klo = _split_np(khash)
        removed = 0
        dirty = []
        for i in range(len(khash)):
            b = buckets[i]
            hit = np.nonzero((b[:, ps.W_KLO] == klo[i])
                             & (b[:, ps.W_KHI] == khi[i]))[0]
            if hit.size:
                b[hit] = 0
                removed += 1
                dirty.append(i)
        if dirty:
            d = np.asarray(dirty)
            self._write_buckets(idx[d], buckets[d])
        return removed

    def occupancy(self) -> int:
        if not hasattr(self, "_occ_fn"):
            self._occ_fn = jax.jit(lambda r: (
                (r[:, ps.W_KLO] != 0) | (r[:, ps.W_KHI] != 0)
            ).sum(dtype=jnp.int64))
        return int(self._occ_fn(self.state))

    # ---- checkpoint/resume ---------------------------------------------

    def snapshot(self) -> dict:
        return _rows_to_columns(np.asarray(self.state))

    def restore(self, arrays: dict) -> int:
        host = np.asarray(self.state).copy()
        keys = arrays["key"].astype(np.uint64)
        idx = self._bucket_indices(keys)
        khi, klo = _split_np(keys)
        placed = 0
        for i in range(len(keys)):
            b = host[idx[i]]
            w = _columns_to_row_words(arrays, i)
            if w is None:
                self.dropped_rows += 1
                continue
            hit = np.nonzero((b[:, ps.W_KLO] == klo[i])
                             & (b[:, ps.W_KHI] == khi[i]))[0]
            slot = None
            if hit.size:
                slot = hit[0]
            else:
                empty = np.nonzero((b[:, ps.W_KLO] == 0)
                                   & (b[:, ps.W_KHI] == 0))[0]
                if empty.size:
                    slot = empty[0]
            if slot is None:
                self.dropped_rows += 1
                continue
            host[idx[i, slot]] = w
            placed += 1
        self.state = jax.device_put(jnp.asarray(host),
                                    self._rows_sharding)
        return placed

"""Pallas serving engine: the hand-scheduled decision kernel as a
deployable step mode (SURVEY §2.2; VERDICT r3 item 1's escalation —
"make the Pallas kernel the serving mode at large CAP: it owns its
scatters").

``PallasServingEngine`` is a drop-in ``ShardedEngine`` whose per-shard
table is the kernel's bucketized AoS layout (``[rows, 32] int32``,
8-slot buckets — ops/pallas_step.py) instead of SoA columns, and whose
step is the Mosaic kernel under ``shard_map``.  Everything above the
step — wave routing, dispatcher coalescing, the wire lanes, metrics —
is inherited unchanged; the engine protocol (gather/upsert/remove
rows, snapshot/restore, sweep) is re-implemented on the bucket layout
so V1Instance features (Store read/write-through, stateful handover,
checkpoint/resume) keep working.

Domain: the kernel serves TOKEN and LEAKY rows whose counters are
< 2^30 and (leaky) eff < 2^31.  Out-of-domain rows are scoped PER ROW
(``pallas_value_domain_mask``): they are excluded from the device step
and surfaced as unservable (``table_full`` True) — never silently
truncated into wrong decisions, and never allowed to fail the other
callers the dispatcher coalesced into the same wave.  The gate covers
both serving paths (check_packed and the pipelined launch/sync pair).
(Per-key time monotonicity is guaranteed upstream: the engine's wave
builder sorts pending requests by arrival time.)

Not supported in this mode (documented trade-offs, not gaps a caller
can trip silently): on-device auto-grow (bucket-full rows err and
surface as table_full exactly like a full SoA probe window; callers
see the same retry semantics), and the fused SoA Pallas sweep (this
mode's sweep is a plain vectorized expire-clear over rows).
"""
from __future__ import annotations

import logging

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.batch import RequestBatch
from ..core.step import decide_batch_impl
from ..ops import pallas_step as ps
from .mesh import SHARD_AXIS, XLA_EXEC_MU, shard_map
from .sharded import PACK32, PACK64, ShardedEngine

log = logging.getLogger("gubernator_tpu.pallas_engine")

#: SoA column → (word extractor) mapping used by snapshot/gather.
_I64_PAIRS = {"duration": (ps.W_DLO, ps.W_DHI),
              "eff_ms": (ps.W_ELO, ps.W_EHI),
              "t_ms": (ps.W_TLO, ps.W_THI),
              "expire_at": (ps.W_XLO, ps.W_XHI)}


def _join_u64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return ((hi.astype(np.uint32).astype(np.uint64) << np.uint64(32))
            | lo.astype(np.uint32).astype(np.uint64))


def _join_i64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return _join_u64(hi, lo).astype(np.int64)


def _split_np(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    u = x.astype(np.uint64)
    return ((u >> np.uint64(32)).astype(np.uint32).astype(np.int32),
            u.astype(np.uint32).astype(np.int32))


def _rows_to_columns(rows: np.ndarray) -> dict:
    """[N, WORDS] int32 bucket rows → SoA column dict (live rows only),
    in the store/Loader format (store.py › table_to_arrays).

    ``burst`` is emitted as ``limit``: the kernel does not store burst
    because oracle.apply_leaky overwrites item.burst from the request
    before every read — the column is dead state everywhere except a
    snapshot round-trip, and limit is its every-step value for token
    rows (leaky rows re-adopt the request burst on first touch).
    """
    key = _join_u64(rows[:, ps.W_KHI], rows[:, ps.W_KLO])
    live = key != 0
    r = rows[live]
    key = key[live]
    alg = r[:, ps.W_ALG].astype(np.int64)
    status = r[:, ps.W_STATUS].astype(np.int64)
    limit = r[:, ps.W_LIMIT].astype(np.int64)
    remaining = np.where(
        alg == 1,
        _join_i64(r[:, ps.W_TDHI], r[:, ps.W_TDLO]),
        r[:, ps.W_REM].astype(np.int64))
    out = {"key": key,
           "meta": (alg | ((status & 1) << 1)).astype(np.int32),
           "limit": limit, "burst": limit, "remaining": remaining}
    for name, (wlo, whi) in _I64_PAIRS.items():
        out[name] = _join_i64(r[:, whi], r[:, wlo])
    return out


def _columns_to_words_batch(arrays: dict, keys: np.ndarray
                            ) -> tuple[np.ndarray, np.ndarray]:
    """All snapshot rows at once → ([n, WORDS] int32 kernel rows,
    [n] bool in-domain mask).  Vectorized: restore/upsert at serving
    scale (1M–10M rows) must not walk rows in Python (VERDICT r4
    weak #2 — the old per-row loop made checkpoint-resume minutes).

    A row is out of domain (mask False; the caller drops it with a
    count, mirroring best-effort Loader.Load) when limit >= 2^30,
    token remaining >= 2^30, leaky eff outside [1, 2^31), or leaky
    remaining outside [0, 2^30 * eff).  The last check exists because
    leaky remaining is stored in td units (remaining x eff) and feeds
    the kernel's restoring divider, whose quotient is only one-word
    when td < 2^30 * eff; an XLA-engine snapshot clamps leaky burst
    only to TD_BOUND // eff (oracle.py), so its td can reach ~2^61 —
    such rows must drop here, not serve garbage quotients (ADVICE r4)."""
    n = len(keys)
    meta = np.asarray(arrays["meta"], np.int64)
    alg = meta & 1
    limit = np.asarray(arrays["limit"], np.int64)
    rem = np.asarray(arrays["remaining"], np.int64)
    eff = np.asarray(arrays["eff_ms"], np.int64)
    leaky = alg == 1
    valid = limit < ps.VALUE_BOUND
    valid &= ~leaky | ((eff >= 1) & (eff < ps.EFF_BOUND))
    valid &= leaky | (rem < ps.VALUE_BOUND)
    # max(eff, 1): dodge a 0-multiply only on rows already invalid
    valid &= ~leaky | ((rem >= 0)
                       & (rem < ps.VALUE_BOUND * np.maximum(eff, 1)))
    w = np.zeros((n, ps.WORDS), np.int32)
    khi, klo = _split_np(keys.astype(np.uint64))
    w[:, ps.W_KLO], w[:, ps.W_KHI] = klo, khi
    w[:, ps.W_STATUS] = ((meta >> 1) & 1).astype(np.int32)
    # invalid rows are filtered before placement; zeroing their values
    # here just keeps the int64→int32 casts in-range
    w[:, ps.W_LIMIT] = np.where(valid, limit, 0).astype(np.int32)
    w[:, ps.W_ALG] = alg.astype(np.int32)
    tdhi, tdlo = _split_np(np.where(valid & leaky, rem, 0))
    w[:, ps.W_TDLO], w[:, ps.W_TDHI] = tdlo, tdhi
    w[:, ps.W_REM] = np.where(valid & ~leaky, rem, 0).astype(np.int32)
    for name, (wlo, whi) in _I64_PAIRS.items():
        hi, lo = _split_np(np.asarray(arrays[name], np.int64))
        w[:, wlo], w[:, whi] = lo, hi
    return w, valid


def _dedupe_last(keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(keep indices, occurrence counts): each key's LAST occurrence's
    values at its FIRST occurrence's position — exactly a sequential
    walk's outcome: the first occurrence claims the slot (bucket-full
    priority), later occurrences overwrite it in place.  ``counts``
    lets callers keep the sequential placed/dropped accounting, where
    EVERY occurrence of a key counts (an operator reading 'restored
    N/M' must not see collapsed duplicates as data loss).  Callers
    pass only IN-DOMAIN rows: a sequential walk validates per
    occurrence, so an invalid late duplicate must not shadow an
    earlier valid write."""
    _, first_idx, counts = np.unique(keys, return_index=True,
                                     return_counts=True)
    _, last_rev = np.unique(keys[::-1], return_index=True)
    last_idx = len(keys) - 1 - last_rev  # aligned: both sorted by key
    order = np.argsort(first_idx)
    return last_idx[order], counts[order]


def _place_into_buckets(buckets: np.ndarray, group_id: np.ndarray,
                        klo: np.ndarray, khi: np.ndarray,
                        words: np.ndarray) -> np.ndarray:
    """Insert-or-update each row into its bucket, fully vectorized.

    ``buckets`` is [g, SLOTS, WORDS] — one host copy per DISTINCT
    bucket — mutated in place; ``group_id[i]`` names row i's bucket.
    Keys must be distinct (callers dedupe last-write-wins).  Existing
    keys update their slot; new keys take empty slots in caller order,
    rows sharing a bucket getting distinct empties via rank-in-group.
    Returns the [n] bool mask of rows that found a slot.  Only the two
    key columns are materialized per row (not whole buckets), so peak
    extra memory is O(n * SLOTS) words even at 10M rows."""
    sklo = buckets[:, :, ps.W_KLO][group_id]  # [n, SLOTS] pre-write
    skhi = buckets[:, :, ps.W_KHI][group_id]
    hit = (sklo == klo[:, None]) & (skhi == khi[:, None])
    placed = hit.any(axis=1)
    slot = hit.argmax(axis=1)
    new = np.nonzero(~placed)[0]
    if new.size:
        order = new[np.argsort(group_id[new], kind="stable")]
        sg = group_id[order]
        start = np.r_[True, sg[1:] != sg[:-1]]
        rank = np.arange(sg.size) - np.nonzero(start)[0][
            np.cumsum(start) - 1]
        empty = (sklo[order] == 0) & (skhi[order] == 0)
        # row with rank r in its bucket takes the (r+1)-th empty slot
        sel = empty & (np.cumsum(empty, axis=1) == (rank + 1)[:, None])
        got = sel.any(axis=1)
        placed[order[got]] = True
        slot[order[got]] = sel.argmax(axis=1)[got]
    # all (group_id, slot) pairs are distinct — hits sit at distinct
    # occupied slots (distinct keys), news at distinct empties — so
    # this fancy assignment has no write collisions
    buckets[group_id[placed], slot[placed]] = words[placed]
    return placed


def _batch_from_packed(a64, a32) -> RequestBatch:
    """Packed wire matrices → RequestBatch (the PACK64/PACK32 layout)."""
    return RequestBatch(
        key=lax.bitcast_convert_type(a64[0], jnp.uint64),
        hits=a64[1], limit=a64[2], duration=a64[3], eff_ms=a64[4],
        greg_end=a64[5], burst=a64[6], now=a64[7],
        behavior=a32[0], algorithm=a32[1], valid=a32[2] != 0)


def _pack_outputs(out) -> jax.Array:
    return jnp.stack([
        out.status.astype(jnp.int64), out.remaining, out.reset_time,
        out.limit, out.err.astype(jnp.int64)])


def make_pallas_step_packed(mesh, interpret: bool = False):
    """shard_map twin of make_sharded_step_packed over the kernel:
    (rows, a64, a32, now) → (rows, [5,B] i64 outputs, counters).  The
    table is always donated — the kernel owns its scatters in-place."""
    S = SHARD_AXIS

    def _step(rows, a64, a32, now):
        batch = _batch_from_packed(a64, a32)
        tbl, out = ps.decide_batch_pallas_impl(
            ps.PallasTable(rows=rows), batch, now, interpret=interpret)
        packed = _pack_outputs(out)
        over = lax.psum(out.over_count, S)
        ins = lax.psum(out.insert_count, S)
        return tbl.rows, packed, (over, ins)

    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(P(S, None), P(None, S), P(None, S), P()),
        out_specs=(P(S, None), P(None, S), P()),
        check_vma=False)  # pallas_call out_shape carries no vma
    return jax.jit(sharded, donate_argnums=(0,))


# ---- the fused serving step (ISSUE 8) ----------------------------------
#
# ONE device program per wave: hash-probe/slot-resolve, token- and
# leaky-bucket update, over-limit decision, the heavy-hitter tap columns
# (ops/pallas_step.py › fused_tap_columns — analytics drains the device
# array, no host-side column copies), and — when the mesh-GLOBAL tier is
# bound — the home-shard replica decision PLUS the scatter-add into the
# shard's active hit accumulator, which deletes meshglobal's separate
# serving dispatch: a wave that mixes plain and mesh-GLOBAL rows costs
# one launch instead of two.
#
# ``flavor`` picks the decision kernel the program embeds:
#   "pallas" — the Mosaic bucket-table kernel (the TPU serving engine;
#              interpret-mode off-TPU, parity/testing only);
#   "xla"    — core/step.py's compiled XLA step over the SoA table (the
#              CPU opt-in: compiled — not interpret — small-shape
#              kernels with identical decisions by construction).


def _make_serve(flavor: str, interpret: bool, tile: int):
    if flavor == "pallas":
        def _serve(state, batch, now):
            tbl, out = ps.decide_batch_pallas_impl(
                ps.PallasTable(rows=state), batch, now,
                interpret=interpret, tile=tile)
            return tbl.rows, out
        return _serve
    if flavor != "xla":
        raise ValueError(f"unknown fused-step flavor {flavor!r}")

    def _serve(state, batch, now):
        return decide_batch_impl(state, batch, now)

    return _serve


def make_fused_step_packed(mesh, *, flavor: str, interpret: bool = False,
                           tile: int = 0, donate: bool = True):
    """(state, a64, a32, now) → (state, packed [5,B] i64, tap [4,B]
    i64, (over, insert)) — the fused program for waves with no
    mesh-GLOBAL rows.  State layout follows ``flavor`` (bucket rows vs
    SoA TableState)."""
    S = SHARD_AXIS
    serve = _make_serve(flavor, interpret, tile)

    def _step(state, a64, a32, now):
        batch = _batch_from_packed(a64, a32)
        state, out = serve(state, batch, now)
        packed = _pack_outputs(out)
        tap = ps.fused_tap_columns(batch, out)
        over = lax.psum(out.over_count, S)
        ins = lax.psum(out.insert_count, S)
        return state, packed, tap, (over, ins)

    state_spec = P(S, None) if flavor == "pallas" else P(S)
    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(state_spec, P(None, S), P(None, S), P()),
        out_specs=(state_spec, P(None, S), P(None, S), P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_fused_mesh_step_packed(mesh, *, flavor: str, mesh_cap: int,
                                interpret: bool = False, tile: int = 0):
    """The mesh-GLOBAL fused program (GUBER_GLOBAL_MODE=mesh with a
    fused engine): rows whose ``mslot`` is >= 0 decide on the key's
    HOME-shard replica of the mesh-GLOBAL table and scatter-add their
    applied hits into that shard's ACTIVE accumulator (the conservation
    ledger meshglobal's reconcile fold psums); all other rows take the
    serving kernel.  One launch serves both lanes — the separate
    meshglobal serving dispatch is deleted.

    Host routing already sends every request to ``shard_of(khash)``,
    which IS the mesh tier's home-shard function, so a mesh row always
    lands on the shard whose replica row is exact.

    (state, mstate, acc, a64, a32, mslot, now) →
    (state, mstate, acc, packed, tap, (over, insert, mesh_hits)).
    """
    S = SHARD_AXIS
    serve = _make_serve(flavor, interpret, tile)

    def _step(state, mstate, acc, a64, a32, mslot, now):
        batch = _batch_from_packed(a64, a32)
        mesh_rows = mslot >= 0
        main = batch._replace(valid=batch.valid & (~mesh_rows))
        state, out = serve(state, main, now)
        # mesh lane: home replica decide (bit-identical to the
        # owner-sharded path — same decide_batch_impl, same row state)
        mst = jax.tree.map(lambda x: x[0], mstate)
        a = acc[0]
        mb = batch._replace(valid=batch.valid & mesh_rows)
        mst, mout = decide_batch_impl(mst, mb, now)
        ok = mb.valid & (~mout.err)
        applied = jnp.where(ok, jnp.maximum(batch.hits, 0),
                            jnp.int64(0))
        # pinned slot comes straight from the host slot map (mslot) —
        # no re-probe; erred rows never mutated state so they don't
        # accumulate either (exactly meshglobal's step contract)
        a = a.at[jnp.where(ok, mslot, mesh_cap)].add(applied,
                                                     mode="drop")
        # merge the two lanes row-wise
        from ..core.step import StepOutput

        merged = StepOutput(
            status=jnp.where(mesh_rows, mout.status, out.status),
            remaining=jnp.where(mesh_rows, mout.remaining,
                                out.remaining),
            reset_time=jnp.where(mesh_rows, mout.reset_time,
                                 out.reset_time),
            limit=jnp.where(mesh_rows, mout.limit, out.limit),
            err=jnp.where(mesh_rows, mout.err, out.err),
            over_count=out.over_count + mout.over_count,
            insert_count=out.insert_count + mout.insert_count)
        packed = _pack_outputs(merged)
        tap = ps.fused_tap_columns(batch, merged)
        over = lax.psum(merged.over_count, S)
        ins = lax.psum(merged.insert_count, S)
        mesh_hits = lax.psum(applied.sum(), S)
        return (state, jax.tree.map(lambda x: x[None], mst), a[None],
                packed, tap, (over, ins, mesh_hits))

    state_spec = P(S, None) if flavor == "pallas" else P(S)
    sharded = shard_map(
        _step, mesh=mesh,
        in_specs=(state_spec, P(S), P(S), P(None, S), P(None, S),
                  P(S), P()),
        out_specs=(state_spec, P(S), P(S), P(None, S), P(None, S),
                   P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,))


class FusedServingMixin:
    """Fused gather–decide–scatter serving (ISSUE 8): the engine's step
    is ONE device program per wave that also emits the heavy-hitter tap
    columns on device and, when the mesh-GLOBAL tier is bound, folds
    the replica decision + accumulator scatter into the same launch.

    The dispatcher reads the two class flags: ``fused_serving`` makes
    the PhaseLedger's pack/device/resolve partition collapse into a
    ``device`` phase that absorbs what fusion deletes, and
    ``fused_tap`` suppresses its host-side per-wave column copies (the
    engine delivered the device tap at launch).
    """

    #: dispatcher collapses the wave's pack mark into `device`
    fused_serving = True
    #: dispatcher skips host-side column taps (device tap instead)
    fused_tap = True
    #: decision-kernel flavor the fused program embeds (subclass sets)
    _flavor = "xla"

    def _fused_setup(self) -> None:
        #: analytics sink for device taps + instance metrics registry:
        #: both single-assigned at instance wiring BEFORE serving
        #: starts, then read-only on the launch path
        self.tap_sink = None  # lock-free: set once pre-serving, read-only after
        self.metrics_ref = None  # lock-free: set once pre-serving, read-only after
        #: bound MeshGlobalEngine (GUBER_GLOBAL_MODE=mesh): a single
        #: reference swap — a wave racing an unbind serves one more
        #: mesh wave, which the tier's state lock keeps exact
        self._mge = None  # lock-free: single ref swap; state mutations under mge._state_mu
        self._mesh_step = None  # lock-free: launch path only (engine lock serializes)
        self._tap_mute = False  # lock-free: engine calls serialized by the engine lock
        self.fused_wave_count = 0  # lock-free: launch path only (engine lock serializes)
        self.mesh_fused_hits = 0  # lock-free: sync path only (engine lock serializes)

    # ---- mesh-GLOBAL binding -------------------------------------------

    def bind_mesh(self, mge) -> None:
        """Attach the mesh-GLOBAL tier: waves whose ``mslot`` column
        marks pinned rows serve them on the home replica + accumulator
        INSIDE the fused program (instance.py wires this when
        GUBER_GLOBAL_MODE=mesh and the engine is fused)."""
        if mge.n != self.n:
            raise ValueError("mesh-GLOBAL tier and serving engine must "
                             "share the device mesh")
        self._mesh_step = None
        self._mge = mge

    def unbind_mesh(self) -> None:
        """Detach (mesh stand-down): subsequent waves serve every row
        on the sharded path; a wave already launched finishes under the
        tier's state lock first."""
        self._mge = None

    @property
    def mesh_bound(self) -> bool:
        return self._mge is not None

    def _ensure_mesh_step(self, mge):
        if self._mesh_step is None:
            self._mesh_step = make_fused_mesh_step_packed(
                self.mesh, flavor=self._flavor, mesh_cap=mge.capacity,
                interpret=getattr(self, "_interpret", False),
                tile=getattr(self, "_tile", 0))
        return self._mesh_step

    def warmup_mesh_fused(self, now_ms: int = 1) -> None:
        """Pre-compile the fused mesh program for every wave bucket —
        an all-invalid wave whose one marked mesh row is invalid
        (nothing moves, the scatter drops) — so the first GLOBAL
        caller never pays the compile (same contract as warmup)."""
        mge = self._mge
        if mge is None:
            return
        from ..core.batch import empty_batch
        from .sharded import pack_wave_host

        for bw in self.wave_buckets:
            a64, a32 = pack_wave_host(empty_batch(self.n * bw))
            mblk = np.full(self.n * bw, -1, np.int32)
            mblk[0] = 0  # invalid row: compiles the mesh lane only
            self._finish_wave(*self._launch_arrays(a64, a32, now_ms,
                                                   mblk))

    # ---- fused launch ---------------------------------------------------

    def _deliver_tap(self, tap) -> None:
        """Hand the device tap array to analytics (no host copy here:
        np.asarray happens on the analytics worker thread)."""
        self.fused_wave_count += 1
        m = self.metrics_ref
        if m is not None:
            m.pallas_fused_waves.inc()
        sink = self.tap_sink
        if sink is not None and not self._tap_mute:
            try:
                sink(tap)
            except Exception:  # noqa: BLE001 - analytics only
                log.exception("fused tap delivery")

    def check_batch(self, reqs, now_ms: int):
        # object-lane waves are tapped by the dispatcher WITH key names
        # (the sketch's name side table); mute the device tap for this
        # call so the wave isn't double-counted.  Engine calls are
        # serialized by the dispatcher's engine lock, so the plain
        # attribute is effectively single-threaded.
        self._tap_mute = True
        try:
            return super().check_batch(reqs, now_ms)
        finally:
            self._tap_mute = False

    def _launch_arrays(self, a64, a32, now_ms: int, mblk=None):
        """One fused launch: decisions + device tap (+ mesh-GLOBAL
        replica decide and accumulator scatter when bound and the wave
        carries pinned rows)."""
        mge = self._mge
        if (mge is None or mblk is None
                or not bool((np.asarray(mblk) >= 0).any())):
            with XLA_EXEC_MU:
                if self.n > 1:
                    a64 = jax.device_put(a64, self._mat_sharding)
                    a32 = jax.device_put(a32, self._mat_sharding)
                self.state, packed, tap, counters = self._step(
                    self.state, a64, a32, np.int64(now_ms))
            self._deliver_tap(tap)
            return packed, counters
        step = self._ensure_mesh_step(mge)

        def _go(mstate, acc):
            nonlocal a64, a32, mblk
            with XLA_EXEC_MU:
                if self.n > 1:
                    a64 = jax.device_put(a64, self._mat_sharding)
                    a32 = jax.device_put(a32, self._mat_sharding)
                    mblk = jax.device_put(mblk, self._batch_sharding)
                (st, mst, acc2, packed, tap,
                 counters) = step(self.state, mstate, acc, a64, a32,
                                  mblk, np.int64(now_ms))
            self.state = st
            return mst, acc2, (packed, tap, counters)

        packed, tap, counters = mge.run_fused(_go)
        self._deliver_tap(tap)
        return packed, counters

    def _finish_wave(self, packed, counters):
        cols = super()._finish_wave(packed, counters[:2])
        if len(counters) > 2:
            mh = int(counters[2])
            if mh:
                # conservation ledger: the fused scatter's applied mesh
                # hits ARE the injected side of meshglobal's
                # folded == injected oracle
                self.mesh_fused_hits += mh
                mge = self._mge
                if mge is not None:
                    mge.note_injected(mh)
                m = self.metrics_ref
                if m is not None:
                    m.pallas_mesh_fused_hits.inc(mh)
        return cols


class PallasServingEngine(FusedServingMixin, ShardedEngine):
    """ShardedEngine over the kernel's bucketized table (module doc)."""

    _flavor = "pallas"

    def _init_table_and_step(self) -> None:
        if self.cap_local < ps.SLOTS or (self.cap_local
                                         & (self.cap_local - 1)):
            raise ValueError("rows per shard must be a power of two "
                             f">= {ps.SLOTS}")
        sh = NamedSharding(self.mesh, P(SHARD_AXIS, None))
        self.state = jax.device_put(
            jnp.zeros((self.n * self.cap_local, ps.WORDS), jnp.int32),
            sh)
        # interpret everywhere the Mosaic kernel can't compile natively
        # (same gate as sharded.py's fused sweep)
        self._interpret = jax.default_backend() != "tpu"
        self._tile = ps.pallas_tile()
        self._step = make_fused_step_packed(
            self.mesh, flavor="pallas", interpret=self._interpret,
            tile=self._tile)
        self._fused_setup()
        self._rows_sharding = sh

        # ONE fused program serves occupancy AND the saturation
        # watermark, compiled (and warmed) here so the first
        # health_check doesn't pay a jit under the engine lock while
        # serving waves wait on it
        def _occ_sat(r):
            live = (r[:, ps.W_KLO] != 0) | (r[:, ps.W_KHI] != 0)
            per_bucket = live.reshape(-1, ps.SLOTS).sum(
                axis=1, dtype=jnp.int32)
            return (live.sum(dtype=jnp.int64),
                    (per_bucket == ps.SLOTS).sum(dtype=jnp.int64))

        self._occ_sat_fn = jax.jit(_occ_sat)
        jax.block_until_ready(self._occ_sat_fn(self.state))

    # ---- serving -------------------------------------------------------

    def _mask_out_of_domain(self, batch, mslot=None):
        """Invalidate rows outside the kernel's value domain; returns
        (masked batch, ood index array or None).  Mesh-GLOBAL rows
        (mslot >= 0) are exempt: they decide on the replica table's XLA
        math inside the fused program, which has the full int64
        domain."""
        mask = ps.pallas_value_domain_mask(batch)
        if mslot is not None:
            mask = mask | (np.asarray(mslot) >= 0)
        v = np.asarray(batch.valid)
        ood = v & ~mask
        if not ood.any():
            return batch, None
        return (batch._replace(valid=jnp.asarray(v & mask)),
                np.nonzero(ood)[0])

    @staticmethod
    def _merge_ood(cols, ood):
        """Out-of-domain rows come back as unservable (table_full) with
        zeroed outputs — scoped to the offending rows, the same shape a
        full probe window presents."""
        if ood is None:
            return cols
        st, lim, rem, rst, full = cols
        full = np.array(full, copy=True)
        full[ood] = True
        return st, lim, rem, rst, full

    def check_packed(self, batch, khash, now_ms: int,
                     mslot=None) -> tuple:
        batch, ood = self._mask_out_of_domain(batch, mslot)
        cols = self._merge_ood(
            super().check_packed(batch, khash, now_ms, mslot=mslot),
            ood)
        tier = self.tier
        if tier is None or ood is None:
            return cols
        # tiered store: the kernel can't serve out-of-domain values but
        # the host cold tier can — exactly.  Only keys with NO device
        # row are eligible (cold-serving a device-resident key would
        # fork its state); the rest keep the table_full error.
        kh = np.asarray(khash)
        found, _ = self.gather_rows(kh[ood])
        elig = ood[~found]
        if not len(elig):
            return cols
        need = np.zeros(len(kh), bool)
        need[elig] = True
        return tier.resolve(self, batch, khash, now_ms, cols,
                            None, need, mslot=mslot)

    def launch_packed(self, batch, khash, now_ms: int, mslot=None):
        # the pipelined dispatcher path calls launch/sync directly —
        # the domain gate must cover it too
        batch, ood = self._mask_out_of_domain(batch, mslot)
        return (super().launch_packed(batch, khash, now_ms,
                                      mslot=mslot), ood)

    def sync_packed(self, token, engine_lock=None) -> tuple:
        inner, ood = token
        return self._merge_ood(
            super().sync_packed(inner, engine_lock=engine_lock), ood)

    def _try_auto_grow(self, grew: list) -> bool:
        return False  # no on-device grow for the bucket layout (doc)

    def grow(self, new_cap_per_shard: int) -> int:
        raise NotImplementedError(
            "pallas serving mode has no on-device grow; size rows up "
            "front (bucket-full rows err as table_full)")

    # ---- tiered store hooks (tiering.py) -------------------------------

    def tier_row_admissible(self, row) -> bool:
        """Admission domain gate: a cold row whose values exceed the
        kernel's packed-word domain must STAY cold — upsert_rows would
        silently drop it, and the migration would lose the row."""
        cols = {f: np.array([v], np.int64) for f, v in zip(
            ("meta", "limit", "duration", "eff_ms", "burst",
             "remaining", "t_ms", "expire_at"), row)}
        cols["meta"] = cols["meta"].astype(np.int32)
        _, valid = _columns_to_words_batch(cols, np.array([1], np.uint64))
        return bool(valid[0])

    def probe_occupant_keys(self, kh: int) -> np.ndarray:
        """Eviction-candidate read for the tier controller: the
        bucketized layout's probe window IS the key's bucket, so the
        occupants are the bucket's resident keys (0 = free slot)."""
        b = self._fetch_buckets(
            self._bucket_indices(np.array([kh], np.uint64)))[0]
        lo = b[:, ps.W_KLO].astype(np.uint64) & np.uint64(0xFFFFFFFF)
        hi = b[:, ps.W_KHI].astype(np.uint64) & np.uint64(0xFFFFFFFF)
        return (hi << np.uint64(32)) | lo

    # ---- sweep ---------------------------------------------------------

    def sweep(self, now_ms: int) -> None:
        """Expire-clear over bucket rows: zero every slot whose
        expire_at <= now (whole row, so leaky td state can't leak into
        a future occupant).  Elementwise per shard — no collective."""
        if not hasattr(self, "_sweep_fn"):
            S = SHARD_AXIS

            def _one(rows, now):
                exp = (rows[:, ps.W_XHI].astype(jnp.int64) << 32) | (
                    rows[:, ps.W_XLO].astype(jnp.int64)
                    & jnp.int64(0xFFFFFFFF))
                live = ((rows[:, ps.W_KLO] != 0)
                        | (rows[:, ps.W_KHI] != 0))
                expired = live & (now >= exp)
                rows = jnp.where(expired[:, None], jnp.int32(0), rows)
                n_live = lax.psum((live & ~expired).sum(dtype=jnp.int64),
                                  S)
                return rows, n_live

            self._sweep_fn = jax.jit(shard_map(
                _one, mesh=self.mesh, in_specs=(P(S, None), P()),
                out_specs=(P(S, None), P()), check_vma=False),
                donate_argnums=(0,))
        self.state, live = self._sweep_fn(
            self.state, jnp.asarray(now_ms, jnp.int64))
        self.live_rows = int(live)
        self.sweep_count += 1

    # ---- row ops (bucket-level, cold path) -----------------------------

    def _bucket_base(self, khash: np.ndarray) -> np.ndarray:
        """[m] global row index of each key's bucket start."""
        from ..hashing import shard_of

        nb = self.cap_local // ps.SLOTS
        shard = shard_of(khash, self.n).astype(np.int64)
        bucket = (khash & np.uint64(nb - 1)).astype(np.int64)
        return shard * self.cap_local + bucket * ps.SLOTS

    def _bucket_indices(self, khash: np.ndarray) -> np.ndarray:
        """[m, SLOTS] global row indices of each key's bucket."""
        return (self._bucket_base(khash)[:, None]
                + np.arange(ps.SLOTS)[None, :])

    def _fetch_buckets(self, idx: np.ndarray) -> np.ndarray:
        """Gather [m, SLOTS, WORDS] bucket copies to host."""
        take = jnp.asarray(idx.reshape(-1))
        # .copy(): np.asarray of a jax array is a read-only view and
        # the callers mutate these buckets in place
        return np.asarray(jnp.take(self.state, take, axis=0)).reshape(
            idx.shape[0], ps.SLOTS, ps.WORDS).copy()

    def _write_buckets(self, idx: np.ndarray, rows: np.ndarray) -> None:
        flat_idx = jnp.asarray(idx.reshape(-1))
        flat_rows = jnp.asarray(rows.reshape(-1, ps.WORDS))
        # duplicate buckets in one call carry identical content (the
        # caller mutates a shared host copy per bucket), so last-write
        # equivalence holds even without a uniqueness promise
        if not hasattr(self, "_write_fn"):
            # cached: a fresh lambda per call would retrace+recompile
            # the scatter on every store write-through
            self._write_fn = jax.jit(lambda s, i, r: s.at[i].set(r),
                                     donate_argnums=(0,))
        self.state = self._write_fn(self.state, flat_idx, flat_rows)

    def gather_rows(self, khash: np.ndarray) -> tuple[np.ndarray, dict]:
        m = len(khash)
        found = np.zeros(m, bool)
        cols = {f: np.zeros(m, np.int64) for f in
                ("meta", "limit", "duration", "eff_ms", "burst",
                 "remaining", "t_ms", "expire_at")}
        cols["meta"] = cols["meta"].astype(np.int32)
        if m == 0:
            return found, cols
        idx = self._bucket_indices(khash)
        buckets = self._fetch_buckets(idx)
        khi, klo = _split_np(khash)
        for i in range(m):
            b = buckets[i]
            hit = np.nonzero((b[:, ps.W_KLO] == klo[i])
                             & (b[:, ps.W_KHI] == khi[i]))[0]
            if not hit.size:
                continue
            found[i] = True
            cvt = _rows_to_columns(b[hit[:1]])
            for f in cols:
                cols[f][i] = cvt[f][0]
        return found, cols

    def _prepared_rows(self, khash: np.ndarray, cols: dict
                       ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Shared upsert/restore front half: convert all rows, drop
        (and count) out-of-domain ones, then dedupe the survivors
        (keeping per-key occurrence counts for sequential-equivalent
        accounting).  Validate-before-dedupe order matters: a
        sequential walk checks each occurrence, so an invalid late
        duplicate never shadows an earlier valid write."""
        keys = np.asarray(khash).astype(np.uint64)
        words, valid = _columns_to_words_batch(cols, keys)
        self.dropped_rows += int((~valid).sum())
        keys, words = keys[valid], words[valid]
        counts = np.ones(len(keys), np.int64)
        if keys.size:
            keep, counts = _dedupe_last(keys)
            if len(keep) != len(keys):
                keys, words = keys[keep], words[keep]
        return keys, words, counts

    def _grouped_bucket_view(self, keys: np.ndarray
                             ) -> tuple[np.ndarray, np.ndarray]:
        """(uidx [g, SLOTS] distinct-bucket row indices, group_id [n])
        — so keys sharing a bucket resolve against ONE image."""
        ubase, group_id = np.unique(self._bucket_base(keys),
                                    return_inverse=True)
        return ubase[:, None] + np.arange(ps.SLOTS)[None, :], group_id

    def upsert_rows(self, khash: np.ndarray, cols: dict) -> int:
        if len(khash) == 0:
            return 0
        keys, words, counts = self._prepared_rows(khash, cols)
        if keys.size == 0:
            return 0
        # ONE batched device fetch of the distinct buckets (a per-key
        # fetch would cost a blocking device round trip per bucket)
        uidx, group_id = self._grouped_bucket_view(keys)
        buckets = self._fetch_buckets(uidx)
        khi, klo = _split_np(keys)
        placed = _place_into_buckets(buckets, group_id, klo, khi, words)
        self.dropped_rows += int(counts[~placed].sum())  # bucket full
        if not placed.any():
            return 0  # saturated buckets: skip the no-op device write
        self._write_buckets(uidx, buckets)
        return int(counts[placed].sum())

    def remove_rows(self, khash: np.ndarray) -> int:
        if len(khash) == 0:
            return 0
        idx = self._bucket_indices(khash)
        buckets = self._fetch_buckets(idx)
        khi, klo = _split_np(khash)
        removed = 0
        dirty = []
        for i in range(len(khash)):
            b = buckets[i]
            hit = np.nonzero((b[:, ps.W_KLO] == klo[i])
                             & (b[:, ps.W_KHI] == khi[i]))[0]
            if hit.size:
                b[hit] = 0
                removed += 1
                dirty.append(i)
        if dirty:
            d = np.asarray(dirty)
            self._write_buckets(idx[d], buckets[d])
        return removed

    def occupancy(self) -> int:
        with XLA_EXEC_MU:
            return int(self._occ_sat_fn(self.state)[0])

    def occupancy_nowait(self) -> int | None:
        """See ShardedEngine.occupancy_nowait — bucket-layout flavor."""
        if not XLA_EXEC_MU.acquire(blocking=False):
            return None
        try:
            return int(self._occ_sat_fn(self.state)[0])
        finally:
            XLA_EXEC_MU.release()

    def bucket_saturation(self) -> tuple[int, int]:
        """(full_buckets, total_buckets) — the capacity-safety
        watermark for this mode.  A FULL 8-slot bucket is the unit of
        unservability here: with no on-device grow, any NEW key hashing
        into one errs as table_full, so 'how many buckets are full' is
        the operative early warning, not total occupancy (a table can
        be 40% occupied yet have hot buckets saturated).  Exported as
        gubernator_pallas_bucket_saturation; VERDICT r4 item 6."""
        total = (self.n * self.cap_local) // ps.SLOTS
        with XLA_EXEC_MU:
            return int(self._occ_sat_fn(self.state)[1]), total

    def occupancy_and_saturation(self) -> tuple[int, int, int]:
        """(live_rows, full_buckets, total_buckets) in ONE device call
        — health_check refreshes both gauges under the engine lock, so
        it must not pay two round trips there."""
        with XLA_EXEC_MU:
            occ, full = self._occ_sat_fn(self.state)
        return (int(occ), int(full),
                (self.n * self.cap_local) // ps.SLOTS)

    # ---- checkpoint/resume ---------------------------------------------

    def snapshot(self) -> dict:
        return _rows_to_columns(np.asarray(self.state))

    def restore(self, arrays: dict) -> int:
        """Vectorized (no per-row Python): a 1M-row snapshot restores
        in seconds, not minutes — bounded by tests/test_pallas_engine
        TestSnapshotRestore.test_restore_1m_rows_is_fast."""
        if len(arrays["key"]) == 0:
            return 0
        keys, words, counts = self._prepared_rows(arrays["key"], arrays)
        if keys.size == 0:
            return 0  # all dropped: no host copy / re-upload for a no-op
        host = np.asarray(self.state).copy()
        uidx, group_id = self._grouped_bucket_view(keys)
        buckets = host[uidx]
        khi, klo = _split_np(keys)
        placed = _place_into_buckets(buckets, group_id, klo, khi, words)
        self.dropped_rows += int(counts[~placed].sum())  # bucket full
        if not placed.any():
            return 0  # saturated buckets: skip the no-op re-upload
        host[uidx] = buckets
        self.state = jax.device_put(jnp.asarray(host),
                                    self._rows_sharding)
        return int(counts[placed].sum())


class XlaFusedEngine(FusedServingMixin, ShardedEngine):
    """The fused serving engine's off-TPU flavor (GUBER_ENGINE=pallas
    on a CPU backend): the SAME one-launch-per-wave fused program —
    decisions + device tap + optional mesh-GLOBAL replica decide and
    accumulator scatter — with core/step.py's COMPILED XLA step as the
    embedded decision kernel instead of the Mosaic bucket kernel.

    This is the "compiled — not interpret — small-shape kernels"
    opt-in: interpret-mode Pallas on CPU measures nothing (orders
    slower by construction), so the CPU flavor serves from compiled
    XLA kernels at small wave shapes (default wave buckets 256/2048 —
    fast compiles, the 1-core host's coalescing sweet spot) while
    keeping decisions bit-identical to ``ShardedEngine`` by
    construction (same decide_batch_impl, same SoA table, so the full
    engine protocol — grow, sweep, snapshot — is inherited unchanged).
    """

    _flavor = "xla"

    #: small-shape default wave buckets (GUBER_WAVE_BUCKETS overrides):
    #: top bucket 1024 matches the classic engine's FIRST bucket, so a
    #: 1000-row wire batch rides the same wave width both ways — the
    #: A/B compares fusion, not wave quantization
    SMALL_WAVE_BUCKETS = (256, 1024)

    def __init__(self, mesh=None, capacity_per_shard: int = 1 << 16,
                 batch_per_shard: int = 1024, auto_grow_limit: int = 0,
                 wave_buckets=None):
        import os as _os

        if wave_buckets is None \
                and not _os.environ.get("GUBER_WAVE_BUCKETS", ""):
            wave_buckets = self.SMALL_WAVE_BUCKETS
        super().__init__(mesh, capacity_per_shard, batch_per_shard,
                         auto_grow_limit=auto_grow_limit,
                         wave_buckets=wave_buckets)

    def _init_table_and_step(self) -> None:
        import os as _os

        from .mesh import shard_table

        self.state = shard_table(self.mesh, self.cap_local)
        # same donation default/opt-out as the classic engine (the
        # bucket-kernel flavor always donates: the kernel owns its
        # scatters in place)
        self._step = make_fused_step_packed(
            self.mesh, flavor="xla",
            donate=_os.environ.get("GUBER_STEP_DONATE", "1") == "1")
        self._fused_setup()


def resolve_engine_kind(selector: str, step_impl: str,
                        backend: str) -> str:
    """GUBER_ENGINE / Config.engine → concrete engine kind.

    - ``auto`` (or unset): the fused Pallas engine on TPU, the classic
      XLA sharded engine elsewhere (the pre-ISSUE-8 default);
    - ``pallas``: fused serving everywhere — the Mosaic bucket kernel
      on TPU, the compiled XLA fused flavor off-TPU;
    - ``xla`` / ``sharded``: the classic engine, explicitly.

    The legacy ``GUBER_STEP_IMPL=pallas`` knob keeps meaning "the
    bucket-kernel engine, even off-TPU (interpret)" — the kernel-parity
    mode tests and the probe drive; GUBER_ENGINE wins when both are
    set.  Unknown values raise: a typo must not silently serve a mode
    whose domain restrictions the operator believes are live.
    """
    sel = (selector or "").strip().lower()
    if sel not in ("", "auto", "pallas", "xla", "sharded"):
        raise ValueError(
            f"unknown GUBER_ENGINE {selector!r} (want auto, pallas, "
            "xla or sharded)")
    if sel in ("", "auto"):
        if step_impl == "pallas":
            return "pallas-kernel"
        return "pallas-fused" if backend == "tpu" else "xla-classic"
    if sel == "pallas":
        return "pallas-fused" if backend == "tpu" else "xla-fused"
    return "xla-classic"

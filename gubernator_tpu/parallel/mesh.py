"""Mesh construction and table shardings.

The key universe is ranged-sharded across the ``shard`` mesh axis by the
top bits of the key hash (hashing.shard_of) — the TPU-native equivalent
of the reference's consistent-hash key ownership (hash.go ›
ConsistantHash / replicated_hash.go — reconstructed).  Each device owns
one contiguous hash range; its table shard lives in its HBM.
"""
from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.table import TableState, init_table

SHARD_AXIS = "shard"

#: Process-wide gate around synchronous XLA executions.  This image's
#: XLA:CPU wedges indefinitely when SEVERAL engines (the in-process
#: multi-daemon test clusters) execute jitted programs concurrently
#: from different threads — observed as every daemon's handler stuck
#: inside the step call (tests/test_soak_wire.py, faulthandler dump).
#: Per-instance engine locks can't prevent that cross-engine overlap;
#: this mutex does.  Single-engine processes (the production topology)
#: already serialize device work on their own engine lock, so the gate
#: is uncontended there.
import threading as _threading

XLA_EXEC_MU = _threading.Lock()

try:  # jax >= 0.5 exports shard_map at top level (check_vma kwarg)
    from jax import shard_map as _shard_map_impl

    _VMA_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _VMA_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable ``shard_map``: the repo targets the public
    ``jax.shard_map`` API (``check_vma``); on jax 0.4.x images the same
    call routes to ``jax.experimental.shard_map`` (whose equivalent
    kwarg is ``check_rep``)."""
    if check_vma is None and _VMA_KW == "check_rep":
        # 0.4.x replication checking has no rule for lax.while_loop
        # (the decision step's per-position fallback); the upstream
        # workaround is check_rep=False — purely a static checker, so
        # disabling it changes no computed values
        check_vma = False
    kw = {} if check_vma is None else {_VMA_KW: check_vma}
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)


def make_mesh(devices: Sequence[jax.Device] | None = None,
              n: int | None = None) -> Mesh:
    """1-D mesh over ``n`` devices (default: all local devices)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n is not None:
        devs = devs[:n]
    return Mesh(np.array(devs), (SHARD_AXIS,))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded across the mesh: row block d of the global table is
    device d's hash range."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def shard_table(mesh: Mesh, capacity_per_shard: int) -> TableState:
    """Build a global table of n_shards × capacity_per_shard rows,
    sharded one block per device."""
    n = mesh.shape[SHARD_AXIS]
    global_tab = init_table(n * capacity_per_shard)
    sh = table_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), global_tab)

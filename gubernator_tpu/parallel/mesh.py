"""Mesh construction and table shardings.

The key universe is ranged-sharded across the ``shard`` mesh axis by the
top bits of the key hash (hashing.shard_of) — the TPU-native equivalent
of the reference's consistent-hash key ownership (hash.go ›
ConsistantHash / replicated_hash.go — reconstructed).  Each device owns
one contiguous hash range; its table shard lives in its HBM.
"""
from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.table import TableState, init_table

SHARD_AXIS = "shard"


def make_mesh(devices: Sequence[jax.Device] | None = None,
              n: int | None = None) -> Mesh:
    """1-D mesh over ``n`` devices (default: all local devices)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n is not None:
        devs = devs[:n]
    return Mesh(np.array(devs), (SHARD_AXIS,))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded across the mesh: row block d of the global table is
    device d's hash range."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def shard_table(mesh: Mesh, capacity_per_shard: int) -> TableState:
    """Build a global table of n_shards × capacity_per_shard rows,
    sharded one block per device."""
    n = mesh.shape[SHARD_AXIS]
    global_tab = init_table(n * capacity_per_shard)
    sh = table_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), global_tab)

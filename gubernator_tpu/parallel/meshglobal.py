"""Mesh-resident GLOBAL tier: collective hit reconciliation (ISSUE 7).

The SNIPPETS.md north star, made the GLOBAL serving mode: "the
`globalManager` async-hits broadcast is replaced by an ICI all-reduce
over the counter tensor so a TPU pod acts as a single coherent
rate-limit region without gRPC peer fan-out".

Layout: every shard holds a full replica of a bounded GLOBAL counter
table ([n, C] with leading device axis, like the hot set), and — new
here — a pair of per-shard **hit accumulators** living on device right
next to it.  Requests route to their key's HOME shard (the same
hash-range ownership `hashing.shard_of` gives the sharded table), so
the home replica sees every hit and its row is always EXACT — decisions
are bit-identical to the owner-sharded path.  The serving step is one
fused program per wave: decide on the home replica AND scatter-add the
applied hits into that shard's active accumulator (no collectives on
the request path).

The reconcile tick then replaces the reference's hit-queue flush +
owner broadcast round trip with ONE collective program:

- every value column adopts its home shard's row via a psum of
  home-masked columns (the all-reduce over the counter tensor — the
  broadcast replacement; "Revisiting the Time Cost Model of AllReduce"
  is the schedule XLA lowers this to on a real pod ring),
- the retired accumulator buffer psums into per-slot hit totals — the
  conservation ledger (`sum of shard counters == injected hits` is the
  oracle tests assert),
- the retired buffer comes back zeroed for its next active term.

Double buffering (TokenWeave-style overlap): accumulators swap between
two buffers at the tick, so the fold reads a RETIRED buffer while new
hits land in the fresh one, and the fold launch is asynchronous — the
host never blocks on the collective; its results drain lazily on the
next tick (serving waves order after it device-side through the state
threading).  Staleness is therefore bounded by the reconcile interval
and measured per fold (`gubernator_mesh_global_staleness_seconds`).

Scope mirrors the hot set's: TOKEN/LEAKY keys without
RESET/DRAIN/Gregorian flags; everything else (and every key once the
tier stands down — see V1Instance's degraded fallback) takes the
owner-sharded path, which is coherent by construction.  Cross-pod /
multi-region traffic keeps the gRPC lanes (`global_manager.py`).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.batch import RequestBatch, clamp_config, empty_batch, pack_requests
from ..core.step import _lookup, _probe_slots, decide_batch_impl
from ..core.table import TableState, init_table
from ..hashing import shard_of
from ..types import EFF_MAX, RateLimitRequest, RateLimitResponse, Status
from .mesh import SHARD_AXIS, XLA_EXEC_MU, shard_map
from .sharded import pack_wave_host

#: TableState value columns (all but `key`) — the fold adopts the home
#: shard's copy of each of these per slot; keys never fold (pins write
#: the key column identically on every replica, and rows never move).
_VALUE_COLS = tuple(f for f in TableState._fields if f != "key")


def _rep(mesh):
    return NamedSharding(mesh, P(SHARD_AXIS))


def _cfg_of(req: RateLimitRequest) -> tuple:
    """(alg, limit, duration, burst) exactly as pack_requests clamps
    them (the hot set's pinned-config contract, same reason)."""
    return clamp_config(req.algorithm, req.limit, req.duration,
                        req.burst, req.behavior)


def make_mesh_global_step(mesh, cap: int):
    """Fused serving step over the packed wire layout: decide on each
    shard's replica AND accumulate the wave's applied hits into the
    shard's active accumulator — the "hit accumulators next to the
    bucket table" half of the design.  No collectives here."""

    def _step(state, acc, a64, a32, now):
        st = jax.tree.map(lambda x: x[0], state)
        a = acc[0]
        bt = RequestBatch(
            key=lax.bitcast_convert_type(a64[0], jnp.uint64),
            hits=a64[1], limit=a64[2], duration=a64[3], eff_ms=a64[4],
            greg_end=a64[5], burst=a64[6], now=a64[7],
            behavior=a32[0], algorithm=a32[1], valid=a32[2] != 0)
        st, out = decide_batch_impl(st, bt, now)
        # per-slot accumulation: re-probe the (post-step) key column so
        # each applied request's hits land on its row's accumulator
        # slot.  Erred rows (probe window exhausted) never mutated
        # state, so they don't accumulate either.
        slots = _probe_slots(bt.key, cap)
        row, _ = _lookup(st.key, slots, bt.key)
        ok = bt.valid & (row >= 0) & (~out.err)
        wrow = jnp.where(ok, row, cap)
        a = a.at[wrow].add(jnp.where(ok, jnp.maximum(bt.hits, 0), 0),
                           mode="drop")
        packed = jnp.stack([
            out.status.astype(jnp.int64), out.remaining, out.reset_time,
            out.limit, out.err.astype(jnp.int64)])
        return (jax.tree.map(lambda x: x[None], st), a[None], packed)

    return jax.jit(shard_map(
        _step, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(None, SHARD_AXIS),
                  P(None, SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(None, SHARD_AXIS))))


def make_mesh_global_fold(mesh):
    """The reconcile collective: every replica adopts its home shard's
    row (psum of home-masked columns — the all-reduce that replaces
    the owner broadcast), the retired accumulator psums into per-slot
    hit totals (the conservation ledger), and comes back zeroed."""
    S = SHARD_AXIS
    n = mesh.shape[S]
    # singleton meshes elide the collectives (identity fold) — same
    # AOT-compile guard as the hot set's sync program
    psum = (lambda x: lax.psum(x, S)) if n > 1 else (lambda x: x)

    def _fold(state, acc):
        st = jax.tree.map(lambda x: x[0], state)
        a = acc[0]
        my = lax.axis_index(S) if n > 1 else jnp.int32(0)
        # home shard from the key column itself (hashing.shard_of):
        # ((h >> 32) * n) >> 32 — the exact host formula, on device
        home = (((st.key >> jnp.uint64(32)) * jnp.uint64(n))
                >> jnp.uint64(32)).astype(jnp.int32)
        mine = (home == my) & (st.key != 0)
        new = {"key": st.key}  # identical on every replica by pinning
        for f in _VALUE_COLS:
            col = getattr(st, f)
            new[f] = psum(jnp.where(mine, col, jnp.zeros_like(col)))
        slot_tot = psum(a)
        folded = TableState(**new)
        return (jax.tree.map(lambda x: x[None], folded),
                jnp.zeros_like(a)[None], slot_tot)

    return jax.jit(shard_map(
        _fold, mesh=mesh,
        in_specs=(P(S), P(S)),
        out_specs=(P(S), P(S), P())))


class MeshGlobalEngine:
    """Host manager of the mesh-resident GLOBAL tier.

    Pins keys to fixed probe-path slots (deterministic across replicas,
    exactly the hot set's discipline), routes each request to its HOME
    shard's sub-batch, and runs the reconcile collective on the
    GlobalSyncWait tick (driven by GlobalManager's mesh backend).
    """

    def __init__(self, mesh, capacity: int = 4096,
                 batch_per_chip: int = 512):
        self.mesh = mesh
        self.n = mesh.shape[SHARD_AXIS]
        self.capacity = capacity
        self.B = batch_per_chip
        #: serializes pin/unpin mutations of the slot maps (reads of
        #: the dicts are GIL-atomic snapshots, the hot set's contract)
        self._mu = threading.Lock()
        self.slots: Dict[int, int] = {}
        #: key_hash → (alg, limit, duration, burst)
        self.pinned_cfg: Dict[int, tuple] = {}
        #: demoted keys keep their slot + device row (the hot set's
        #: retire rule: clearing the key would let an in-flight request
        #: insert a phantom fresh bucket)
        self._retired: Dict[int, int] = {}
        self._occupied: set = set()
        #: serializes every state/accumulator read-modify-write
        #: (request steps, the fold, pins)
        self._state_mu = threading.Lock()
        base = init_table(capacity)
        rep = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n,) + x.shape),
            base)
        sh = _rep(mesh)
        self.state: TableState = jax.tree.map(
            lambda x: jax.device_put(x, sh), rep)
        #: double-buffered per-shard hit accumulators: serving writes
        #: the ACTIVE buffer; the fold reads the retired one
        self._acc = [
            jax.device_put(jnp.zeros((self.n, capacity), jnp.int64), sh),
            jax.device_put(jnp.zeros((self.n, capacity), jnp.int64), sh)]
        self._active = 0  # guarded-by: self._state_mu
        self._step = make_mesh_global_step(mesh, capacity)
        self._fold = make_mesh_global_fold(mesh)
        #: reconcile bookkeeping (host side)
        self.generation = 0  # guarded-by: self._state_mu
        self.folded_hits = 0  # guarded-by: self._state_mu
        self.injected_hits = 0  # guarded-by: self._state_mu
        self.last_staleness_s = 0.0  # guarded-by: self._state_mu
        self._first_unfolded_t: Optional[float] = None  # guarded-by: self._state_mu
        #: pending async fold results: (slot_totals array, launch time)
        self._pending: List[tuple] = []  # guarded-by: self._state_mu

    @property
    def fold_nbytes(self) -> int:
        """Per-replica bytes the reconcile collective moves: every
        TableState value column (int64) plus the retired accumulator
        — the cost model's (bytes, ndev) feature for global_fold."""
        return (len(_VALUE_COLS) + 1) * self.capacity * 8

    # ---- host slot management (hot-set discipline) ---------------------

    def _probe_slots_host(self, key_hash: int) -> List[int]:
        from ..core.step import PROBES

        k = np.uint64(key_hash)
        stride = int((k >> np.uint64(17)) | np.uint64(1))
        return [int((int(k) + p * stride) & (self.capacity - 1))
                for p in range(PROBES)]

    def is_pinned(self, key_hash: int) -> bool:
        return key_hash in self.slots

    def matches_pinned(self, key_hash: int, req: RateLimitRequest) -> bool:
        return self.pinned_cfg.get(key_hash) == _cfg_of(req)

    def probe_occupants(self, key_hash: int) -> List[int]:
        """Pinned keys whose slots occupy ``key_hash``'s probe window —
        the overflow-admission read: a cap overflow demotes the coldest
        of these (by sketch rank) instead of silently declining."""
        with self._mu:
            window = set(self._probe_slots_host(key_hash))
            return [k for k, s in self.slots.items() if s in window]

    def pin_many(self, entries: Sequence[tuple], now_ms: int) -> List[bool]:
        """Pin several keys in ONE device upload set.  ``entries``:
        (req, key_hash, seed-or-None) — seed carries the key's sharded
        row so pre-tier consumption survives promotion into the mesh.
        Returns per-entry success (False: probe window full — the
        request stays on the sharded path, which is always correct)."""
        ok = [False] * len(entries)
        placed: List[tuple] = []  # (slot, host row dict)
        with self._mu:
            for j, (req, kh, seed) in enumerate(entries):
                if kh in self.slots:
                    ok[j] = True
                    continue
                if kh in self._retired:
                    slot = self._retired.pop(kh)
                else:
                    probes = self._probe_slots_host(kh)
                    slot = next((s for s in probes
                                 if s not in self._occupied), None)
                    if slot is None:
                        retired_by_slot = {s: k for k, s in
                                           self._retired.items()}
                        slot = next((s for s in probes
                                     if s in retired_by_slot), None)
                        if slot is None:
                            continue  # window full → sharded path
                        del self._retired[retired_by_slot[slot]]
                    else:
                        self._occupied.add(slot)
                self.slots[kh] = slot
                self.pinned_cfg[kh] = _cfg_of(req)
                placed.append((slot, self._fresh_row(req, kh, now_ms,
                                                     seed)))
                ok[j] = True
        if not placed:
            return ok
        with self._state_mu:
            new_cols = {}
            for f in TableState._fields:
                col = np.asarray(getattr(self.state, f)).copy()
                for slot, host in placed:
                    col[:, slot] = host[f]
                new_cols[f] = jax.device_put(col, _rep(self.mesh))
            self.state = TableState(**new_cols)
        return ok

    def pin(self, req: RateLimitRequest, key_hash: int, now_ms: int,
            seed: Optional[dict] = None) -> bool:
        return self.pin_many([(req, key_hash, seed)], now_ms)[0]

    @staticmethod
    def _fresh_row(req: RateLimitRequest, key_hash: int, now_ms: int,
                   seed: Optional[dict]) -> dict:
        """Initial replica row — the packer-exact eff/burst math the
        hot set's pin uses (core/batch.py clamps)."""
        alg, limit, dur, burst = _cfg_of(req)
        eff = max(int(dur), 1)
        if alg:
            eff = min(eff, EFF_MAX)
        rem0 = burst * eff if alg else limit
        host = {
            "key": np.uint64(key_hash), "meta": np.int32(alg),
            "limit": np.int64(limit), "duration": np.int64(dur),
            "eff_ms": np.int64(eff), "burst": np.int64(burst),
            "remaining": np.int64(rem0), "t_ms": np.int64(now_ms),
            "expire_at": np.int64(now_ms + eff),
        }
        if seed is not None:
            for f in ("remaining", "t_ms", "expire_at", "meta"):
                host[f] = host[f].dtype.type(seed[f])
        return host

    def unpin(self, key_hash: int) -> None:
        with self._mu:
            slot = self.slots.pop(key_hash, None)
            self.pinned_cfg.pop(key_hash, None)
            if slot is not None:
                self._retired[key_hash] = slot

    def pinned_keys(self) -> List[int]:
        with self._mu:
            return list(self.slots)

    def row_state(self, key_hash: int) -> Optional[dict]:
        """The key's HOME replica row — exact without any collective
        (home routing means only the home shard's copy ever moves), so
        demotion/stand-down migrate state even when the fold is the
        thing that is broken."""
        slot = self.slots.get(key_hash)
        if slot is None:
            return None
        home = int(shard_of(int(key_hash), self.n))
        with self._state_mu:
            return {f: np.asarray(getattr(self.state, f))[home, slot]
                    for f in TableState._fields if f != "key"}

    # ---- request path ---------------------------------------------------

    def warmup(self, now_ms: int = 1) -> None:
        """Pre-compile the serving step AND the fold (all-invalid wave,
        zero accumulators: no state change).  Without this the
        first-touch compile lands inside a caller's GLOBAL request —
        on CPU long enough that a short-duration bucket idle-expires
        between the first and second call (observed: a 5 s bucket
        reset by the compile stall).  V1Instance warms the tier at
        construction, the same contract as the sharded engine's
        daemon-startup warmup."""
        self._run_wave(empty_batch(self.n * self.B), now_ms)
        self.fold(self.swap_accum())

    def check_columns(self, batch: RequestBatch, khash: np.ndarray,
                      now_ms: int) -> tuple:
        """Serve pinned GLOBAL requests, HOME-shard routed: numpy
        RequestBatch columns in, (status, remaining, reset_time, limit,
        row_lost) columns out.  The home replica sees every hit for its
        keys, so decisions are exact — bit-identical to the
        owner-sharded path on the same traffic."""
        n_req = len(khash)
        status = np.zeros(n_req, np.int64)
        rem = np.zeros(n_req, np.int64)
        rst = np.zeros(n_req, np.int64)
        lim = np.zeros(n_req, np.int64)
        lost = np.zeros(n_req, bool)
        home = shard_of(np.asarray(khash, np.uint64), self.n)
        by_time = np.argsort(np.asarray(batch.now), kind="stable")
        pending = by_time.tolist()
        inj = int(np.maximum(
            np.asarray(batch.hits)[np.asarray(batch.valid)], 0).sum())
        while pending:
            fill = [0] * self.n
            wave, rest, positions = [], [], []
            for i in pending:
                h = int(home[i])
                if fill[h] < self.B:
                    positions.append(h * self.B + fill[h])
                    fill[h] += 1
                    wave.append(i)
                else:
                    rest.append(i)
            idx = np.asarray(wave, np.int64)
            pos = np.asarray(positions, np.int64)
            glob = empty_batch(self.n * self.B)
            for f in range(len(glob)):
                np.asarray(glob[f])[pos] = np.asarray(batch[f])[idx]
            o_st, o_rem, o_rst, o_lim, o_err = self._run_wave(glob,
                                                              now_ms)
            status[idx] = o_st[pos]
            rem[idx] = o_rem[pos]
            rst[idx] = o_rst[pos]
            lim[idx] = o_lim[pos]
            lost[idx] = o_err[pos]
            pending = rest
        with self._state_mu:
            self.injected_hits += inj
            if inj and self._first_unfolded_t is None:
                self._first_unfolded_t = time.monotonic()
        return status, rem, rst, lim, lost

    def check_batch(self, reqs: Sequence[RateLimitRequest],
                    key_hashes: Sequence[int], now_ms: int
                    ) -> List[RateLimitResponse]:
        """Object-lane wrapper over ``check_columns``."""
        khash = np.asarray(list(key_hashes), np.uint64)
        batch, _ = pack_requests(list(reqs), now_ms, size=len(reqs),
                                 key_hashes=khash)
        st, rem, rst, lim, lost = self.check_columns(batch, khash,
                                                     now_ms)
        return [RateLimitResponse(
            status=Status(int(st[i])), limit=int(lim[i]),
            remaining=int(rem[i]), reset_time=int(rst[i]),
            error="mesh-global row lost" if lost[i] else "")
            for i in range(len(reqs))]

    def _run_wave(self, glob: RequestBatch, now_ms: int):
        a64, a32 = pack_wave_host(glob)
        sh = NamedSharding(self.mesh, P(None, SHARD_AXIS))
        d64 = jax.device_put(a64, sh)
        d32 = jax.device_put(a32, sh)
        with self._state_mu:
            acc = self._acc[self._active]
            with XLA_EXEC_MU:
                self.state, self._acc[self._active], packed = \
                    self._step(self.state, acc, d64, d32,
                               jnp.asarray(now_ms, jnp.int64))
        out = np.asarray(packed)
        return out[0], out[1], out[2], out[3], out[4] != 0

    # ---- fused-engine hooks (ISSUE 8) ----------------------------------

    def run_fused(self, fn):
        """One fused serving launch under the tier's state lock: the
        fused engine (parallel/pallas_engine.py › FusedServingMixin)
        folds this tier's home-replica decide AND the accumulator
        scatter-add into ITS wave program, deleting the separate
        serving dispatch this class's ``check_columns`` costs.
        ``fn(state, active_acc)`` must return (new_state, new_acc,
        result); both store back atomically w.r.t. the fold/pins —
        the double-buffer discipline holds because the launch writes
        only the ACTIVE buffer (the fold reads retired ones)."""
        with self._state_mu:
            st, acc, result = fn(self.state, self._acc[self._active])
            self.state = st
            self._acc[self._active] = acc
            return result

    def note_injected(self, hits: int) -> None:
        """Conservation-ledger feed for fused waves: the fused step
        counts applied mesh hits on device (the exact amount its
        scatter added to the active accumulator), so the
        folded == injected oracle stays exact across both serving
        paths."""
        if hits <= 0:
            return
        with self._state_mu:
            self.injected_hits += hits
            if self._first_unfolded_t is None:
                self._first_unfolded_t = time.monotonic()

    # ---- the reconcile collective --------------------------------------

    def swap_accum(self) -> int:
        """Retire the active accumulator buffer (new hits land in the
        fresh one) and return its index for ``fold``.  The caller (the
        instance's reconcile tick) fires the ``global_accum_swap``
        faultpoint BEFORE calling this, so an injected error leaves the
        buffers untouched — nothing is ever mid-swap."""
        with self._state_mu:
            retired = self._active
            self._active ^= 1
        return retired

    def swap_back(self) -> None:
        """Undo ``swap_accum`` after a failed fold: the retired buffer
        (still holding its unfolded hits) becomes active again, so no
        accumulated hit is ever stranded.  Exact because the tick holds
        the reconcile path single-threaded (GlobalManager's loop)."""
        with self._state_mu:
            self._active ^= 1

    def fold(self, retired: int) -> None:
        """Launch the reconcile collective over the retired buffer —
        asynchronously: the host does not block on the psum (TokenWeave
        overlap); results drain on the next tick or stats read."""
        t0 = time.monotonic()
        with self._state_mu:
            with XLA_EXEC_MU:
                self.state, self._acc[retired], slot_tot = self._fold(
                    self.state, self._acc[retired])
            self.generation += 1
            stale = (t0 - self._first_unfolded_t
                     if self._first_unfolded_t is not None else 0.0)
            self.last_staleness_s = max(stale, 0.0)
            self._first_unfolded_t = None
            self._pending.append(slot_tot)

    def drain(self) -> None:
        """Materialize pending fold totals into ``folded_hits`` (blocks
        on any fold still in flight — call off the serving path)."""
        with self._state_mu:
            pending, self._pending = self._pending, []
            for slot_tot in pending:
                self.folded_hits += int(np.asarray(slot_tot).sum())

    def stats(self) -> dict:
        self.drain()
        with self._state_mu:
            return {
                "generation": self.generation,
                "pinned_keys": len(self.slots),
                "capacity": self.capacity,
                "n_shards": self.n,
                "injected_hits": self.injected_hits,
                "folded_hits": self.folded_hits,
                "last_staleness_s": round(self.last_staleness_s, 6),
            }

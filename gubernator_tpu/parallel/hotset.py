"""Replicated hot-set engine: GLOBAL rate limits as one psum per tick.

This is the TPU-native replacement for the reference's entire GLOBAL
replication machinery (global.go › runAsyncHits + runBroadcasts +
UpdatePeerGlobals — reconstructed; SURVEY.md §2.3/§3.3): instead of
non-owners queueing hits over gRPC to an owner which broadcasts merged
state back, every chip holds a full replica of a small "hot set" table
and serves GLOBAL decisions locally; consumption deltas are folded
across the mesh with a single ``lax.psum`` on the sync tick.  Traffic
per tick is O(hot-set size), independent of request rate — the pod acts
as one coherent rate-limit region with read-local latency.

Scope (enforced by the host router): TOKEN_BUCKET or LEAKY_BUCKET keys
with stable (algorithm, limit, duration, burst) and no
RESET/DRAIN/Gregorian flags — the shape of real-world hot global
limits.  Everything else takes the owner-sharded path
(parallel/sharded.py), which is already coherent.

Merge semantics (per slot, between syncs; replicas start identical at
``base``):

TOKEN_BUCKET:

- a replica that saw ``now ≥ expire`` re-created the bucket fresh
  (detected as ``t_i != base.t``); ``any_refresh`` adopts the latest
  re-creation via pmax of timestamps,
- per-replica consumption ``d_i = (limit if refreshed_i else base.rem)
  - rem_i``  (≥ 0),
- merged ``rem = clamp((limit if any_refresh else base.rem) - Σ d_i,
  0, limit)``.

LEAKY_BUCKET (``remaining`` is token-duration fixed point, replenished
``limit`` per ``eff_ms`` up to ``burst × eff_ms`` — core/table.py): a
replica's timestamp moves on *every* touch, so refresh detection is
meaningless; instead consumption is measured against the base
replenished to the replica's own clock:

- ``rep(t) = min(base.rem + clamp(t - base.t) × limit, burst × eff)``,
- per-replica consumption ``d_i = max(rep(t_i) - rem_i, 0)``,
- merged at ``T = pmax(t_i)``: ``rem = clamp(rep(T) - Σ d_i, 0,
  burst × eff)``.

A replica whose row expired (idle > duration) re-creates it at
``burst × eff``; ``rep(t_i)`` saturates at the same ceiling by then, so
the merge needs no special refresh case.  (If ``burst > limit`` and the
bucket was deeply drained, a refresh can forgive un-replenished debt —
bounded by one bucket, inside GLOBAL's eventual-consistency contract.)

Within one sync window total admissions across the mesh can exceed the
limit by at most (n_chips - 1) × per-window consumption — the same
eventual-consistency window the reference's GLOBAL behavior documents;
tests assert convergence and post-sync conservation.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.batch import (RequestBatch, clamp_config,
                          empty_batch, pack_requests)
from ..core.step import decide_batch_impl
from ..core.table import TableState, init_table
from ..types import EFF_MAX, RateLimitRequest, RateLimitResponse, Status
from .mesh import SHARD_AXIS, shard_map


def _rep(mesh):
    return NamedSharding(mesh, P(SHARD_AXIS))


def _cfg_of(req: RateLimitRequest) -> tuple:
    """(alg, limit, duration, burst) exactly as pack_requests clamps them
    — the pinned row must agree with every packed request that hits it,
    else the device step would see a config change and reset the row."""
    return clamp_config(req.algorithm, req.limit, req.duration, req.burst,
                        req.behavior)


def make_hot_step(mesh):
    """Per-chip replica apply over the packed wire layout
    (sharded.py › PACK64/PACK32: 2 uploads + 1 download per wave):
    state has leading [n] device axis; each chip runs the full decision
    program on its own replica and its own sub-batch.  No collectives
    on the request path."""

    def _step(state, a64, a32, now):
        st = jax.tree.map(lambda x: x[0], state)
        bt = RequestBatch(
            key=lax.bitcast_convert_type(a64[0], jnp.uint64),
            hits=a64[1], limit=a64[2], duration=a64[3], eff_ms=a64[4],
            greg_end=a64[5], burst=a64[6], now=a64[7],
            behavior=a32[0], algorithm=a32[1], valid=a32[2] != 0)
        st, out = decide_batch_impl(st, bt, now)
        st = jax.tree.map(lambda x: x[None], st)
        packed = jnp.stack([
            out.status.astype(jnp.int64), out.remaining, out.reset_time,
            out.limit, out.err.astype(jnp.int64)])
        return st, packed

    return jax.jit(shard_map(
        _step, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS),
                  P(None, SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(None, SHARD_AXIS))))


def make_hot_sync(mesh):
    """The psum fold: merge per-replica consumption into a new common
    base — the entire global.go subsystem as one collective."""
    S = SHARD_AXIS
    n = mesh.shape[S]
    # On a singleton mesh the collectives are identities; eliding them
    # matters beyond speed: axon's chipless AOT compile helper crashes
    # on this program's psum/pmax at topology v5e:1x1x1 (observed
    # 2026-07-30), and a 1-chip hot set must still work there.
    psum = (lambda x: lax.psum(x, S)) if n > 1 else (lambda x: x)
    pmax = (lambda x: lax.pmax(x, S)) if n > 1 else (lambda x: x)

    def _sync(state, base_rem, base_t):
        st = jax.tree.map(lambda x: x[0], state)
        brem, bt = base_rem[0], base_t[0]
        limit = st.limit
        is_leaky = (st.meta & 1) == 1
        # --- token: refresh detection + consumption vs (refreshed) base
        refreshed = (~is_leaky) & (st.t_ms != bt)
        any_refresh = pmax(refreshed.astype(jnp.int32)) > 0
        start = jnp.where(refreshed, limit, brem)
        d_tok = jnp.maximum(start - st.remaining, 0)
        # --- leaky: consumption vs base replenished to the replica's t.
        # elapsed is clamped so elapsed × limit cannot wrap int64: leaky
        # burst ≤ TD_BOUND // eff per the packer clamps, so cap_td ≤ 2^61
        # and the clamped product ≤ cap_td + limit < 2^62.  eff is masked
        # to 1 on token rows (stored token eff can reach DURATION_MAX =
        # 2^53; an unmasked product would wrap even though d_leaky is
        # discarded by the is_leaky select).
        eff = jnp.maximum(jnp.where(is_leaky, st.eff_ms, 1), 1)
        cap_td = st.burst * eff
        el_max = cap_td // jnp.maximum(limit, 1) + 1

        def rep_at(t):
            el = jnp.clip(t - bt, 0, el_max)
            return jnp.minimum(brem + el * limit, cap_td)

        d_leaky = jnp.maximum(rep_at(st.t_ms) - st.remaining, 0)
        d = jnp.where(is_leaky, d_leaky, d_tok)
        total = psum(d)
        new_t = pmax(st.t_ms)
        merged_base = jnp.where(any_refresh, limit, brem)
        new_rem_tok = jnp.clip(merged_base - total, 0, limit)
        new_rem_leaky = jnp.clip(rep_at(new_t) - total, 0, cap_td)
        new_rem = jnp.where(is_leaky, new_rem_leaky, new_rem_tok)
        new_exp = pmax(st.expire_at)
        st = st._replace(remaining=new_rem, t_ms=new_t, expire_at=new_exp)
        out_state = jax.tree.map(lambda x: x[None], st)
        return out_state, new_rem[None], new_t[None]

    return jax.jit(shard_map(
        _sync, mesh=mesh,
        in_specs=(P(S), P(S), P(S)),
        out_specs=(P(S), P(S), P(S))))


class HotSetEngine:
    """Host-managed replicated hot-set over a mesh.

    The host pins keys to fixed slots (deterministic across replicas —
    the property open addressing can't give divergent replicas), routes
    qualifying GLOBAL requests here round-robin across chips, and calls
    ``sync()`` on the GlobalSyncWait tick.
    """

    def __init__(self, mesh, capacity: int = 1024, batch_per_chip: int = 512):
        self.mesh = mesh
        self.n = mesh.shape[SHARD_AXIS]
        self.capacity = capacity
        self.B = batch_per_chip
        self.slots: Dict[int, int] = {}  # key_hash → slot
        #: key_hash → (alg, limit, duration, burst) — see _cfg_of
        self.pinned_cfg: Dict[int, tuple] = {}
        #: Demoted keys keep their slot reserved (and their device row in
        #: place): clearing the key column would let an in-flight hot
        #: request re-insert a phantom fresh bucket, and re-pinning at a
        #: different probe slot would be shadowed by the stale row.
        self._retired: Dict[int, int] = {}
        self._occupied: set = set()
        self._mu = threading.Lock()
        #: Serializes every state read-modify-write (request steps, the
        #: sync tick, pins): a sync computed from pre-step state would
        #: otherwise overwrite a concurrent step's consumption.
        self._state_mu = threading.Lock()
        # state with leading device axis [n, cap]: one replica per chip
        base = init_table(capacity)
        rep = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n,) + x.shape), base)
        sh = _rep(mesh)
        self.state: TableState = jax.tree.map(
            lambda x: jax.device_put(x, sh), rep)
        self.base_rem = jax.device_put(
            jnp.zeros((self.n, capacity), jnp.int64), sh)
        self.base_t = jax.device_put(
            jnp.zeros((self.n, capacity), jnp.int64), sh)
        self._step = make_hot_step(mesh)
        self._sync = make_hot_sync(mesh)
        self._rr = 0  # round-robin cursor across chips
        self.sync_count = 0

    # ---- host slot management ------------------------------------------

    def _probe_slots_host(self, key_hash: int) -> List[int]:
        """The key's probe sequence — MUST match core/step.py ›
        _probe_slots, since the device kernel looks keys up by probing;
        a pinned key outside its probe window would be invisible."""
        from ..core.step import PROBES

        k = np.uint64(key_hash)
        stride = int((k >> np.uint64(17)) | np.uint64(1))
        return [int((int(k) + p * stride) & (self.capacity - 1))
                for p in range(PROBES)]

    def pin(self, req: RateLimitRequest, key_hash: int, now_ms: int,
            seed: Optional[dict] = None) -> bool:
        """Assign an on-probe-path slot and initialize the bucket on
        every replica.  ``seed`` carries the key's current row state
        from the owner-sharded table (promotion must NOT forget hits
        already consumed); without it the bucket starts fresh.  Returns
        False when the key's probe window is fully occupied (hot sets
        are sized sparse, so this is rare)."""
        with self._mu:
            if key_hash in self.slots:
                return True
            if key_hash in self._retired:
                slot = self._retired.pop(key_hash)  # reuse: row is there
            else:
                probes = self._probe_slots_host(key_hash)
                slot = next((s for s in probes
                             if s not in self._occupied), None)
                if slot is None:
                    # reclaim a retired slot in the window: its old key
                    # was demoted (state already migrated out), so the
                    # stale row may be overwritten.  Without this,
                    # promote/demote churn would exhaust capacity.
                    retired_by_slot = {s: k for k, s in
                                       self._retired.items()}
                    slot = next((s for s in probes
                                 if s in retired_by_slot), None)
                    if slot is None:
                        return False
                    del self._retired[retired_by_slot[slot]]
                else:
                    self._occupied.add(slot)
            self.slots[key_hash] = slot
            self.pinned_cfg[key_hash] = _cfg_of(req)
        alg, limit, dur, burst = _cfg_of(req)
        # Effective denominator exactly as the packers compute it
        # (core/batch.py): floor at 1; leaky additionally clamps to
        # EFF_MAX (the td-bound contract).  Gregorian is _HOT_EXCLUDED,
        # so the non-calendar branch is the only one.  Seeding eff from
        # the raw duration would disagree with every packed request
        # (spurious per-step "eff change") and burst × dur could wrap
        # int64 at calendar-scale durations.
        eff = max(int(dur), 1)
        if alg:
            eff = min(eff, EFF_MAX)
        # fresh leaky buckets start at burst × eff token-duration fixed
        # point; token buckets at limit (core/step.py › rem_fresh)
        rem0 = burst * eff if alg else limit
        host = {
            "key": np.uint64(key_hash), "meta": np.int32(alg),
            "limit": np.int64(limit), "duration": np.int64(dur),
            "eff_ms": np.int64(eff), "burst": np.int64(burst),
            "remaining": np.int64(rem0), "t_ms": np.int64(now_ms),
            "expire_at": np.int64(now_ms + eff),
        }
        if seed is not None:
            for f in ("remaining", "t_ms", "expire_at", "meta"):
                host[f] = host[f].dtype.type(seed[f])
        # one tiny device_put per column: pin is rare (promotion only)
        with self._state_mu:
            new_cols = {}
            for f in TableState._fields:
                col = np.asarray(getattr(self.state, f)).copy()
                col[:, slot] = host[f]
                new_cols[f] = jax.device_put(col, _rep(self.mesh))
            self.state = TableState(**new_cols)
            br = np.asarray(self.base_rem).copy()
            br[:, slot] = host["remaining"]
            self.base_rem = jax.device_put(br, _rep(self.mesh))
            bt = np.asarray(self.base_t).copy()
            bt[:, slot] = host["t_ms"]
            self.base_t = jax.device_put(bt, _rep(self.mesh))
        return True

    def is_pinned(self, key_hash: int) -> bool:
        return key_hash in self.slots

    def matches_pinned(self, key_hash: int, req: RateLimitRequest) -> bool:
        return self.pinned_cfg.get(key_hash) == _cfg_of(req)

    def row_state(self, key_hash: int) -> Optional[dict]:
        """Merged row values for a pinned key (call ``sync()`` first —
        post-sync all replicas agree; replica 0 is read).  Used to
        migrate state back to the sharded table on demotion."""
        slot = self.slots.get(key_hash)
        if slot is None:
            return None
        with self._state_mu:
            return {f: np.asarray(getattr(self.state, f))[0, slot]
                    for f in TableState._fields if f != "key"}

    def unpin(self, key_hash: int) -> None:
        """Stop hot-routing a key.  The slot stays reserved and the
        device row stays in place (see ``_retired``); hits from requests
        already in flight land on the retired row and are lost — a
        bounded, demotion-only window consistent with GLOBAL's
        eventual-consistency contract."""
        with self._mu:
            slot = self.slots.pop(key_hash, None)
            self.pinned_cfg.pop(key_hash, None)
            if slot is not None:
                self._retired[key_hash] = slot

    def unpin_all(self) -> None:
        with self._mu:
            self.slots.clear()
            self.pinned_cfg.clear()
            self._retired.clear()
            self._occupied.clear()

    # ---- request path ---------------------------------------------------

    def _run_hot_wave(self, glob: RequestBatch, now_ms: int):
        """One replica-step launch over the packed layout: 2 uploads +
        1 download.  ``glob`` holds [n·B] numpy columns in block order;
        returns (status, remaining, reset_time, limit, lost) arrays."""
        from .sharded import pack_wave_host

        a64, a32 = pack_wave_host(glob)
        sh = NamedSharding(self.mesh, P(None, SHARD_AXIS))
        d64 = jax.device_put(a64, sh)
        d32 = jax.device_put(a32, sh)
        with self._state_mu:
            self.state, packed = self._step(
                self.state, d64, d32, jnp.asarray(now_ms, jnp.int64))
        out = np.asarray(packed)
        return out[0], out[1], out[2], out[3], out[4] != 0

    def check_batch(self, reqs: Sequence[RateLimitRequest],
                    key_hashes: Sequence[int], now_ms: int
                    ) -> List[RateLimitResponse]:
        """Serve pinned GLOBAL requests: spread across chips round-robin
        (any replica answers), one device launch, no collectives."""
        n_req = len(reqs)
        responses: List[Optional[RateLimitResponse]] = [None] * n_req
        pending = list(range(n_req))
        while pending:
            wave, rest = pending[: self.n * self.B], pending[self.n * self.B:]
            # pack the whole wave once, then place with one fancy index
            packed, _ = pack_requests(
                [reqs[i] for i in wave], now_ms, size=len(wave),
                key_hashes=np.asarray([key_hashes[i] for i in wave],
                                      np.uint64))
            positions = np.empty(len(wave), np.int64)
            fill = [0] * self.n
            for j, i in enumerate(wave):
                c = self._rr % self.n
                self._rr += 1
                # find a chip with room (wave is bounded so one exists)
                for _ in range(self.n):
                    if fill[c] < self.B:
                        break
                    c = (c + 1) % self.n
                positions[j] = c * self.B + fill[c]
                fill[c] += 1
            glob = empty_batch(self.n * self.B)
            for f in range(len(glob)):
                np.asarray(glob[f])[positions] = packed[f][:len(wave)]
            slot_of = list(zip(wave, positions.tolist()))
            status, rem, rst, lim, err = self._run_hot_wave(glob, now_ms)
            for i, pos in slot_of:
                responses[i] = RateLimitResponse(
                    status=Status(int(status[pos])), limit=int(lim[pos]),
                    remaining=int(rem[pos]), reset_time=int(rst[pos]),
                    error="hot-set row lost" if err[pos] else "")
            pending = rest
        return responses  # type: ignore[return-value]

    def check_columns(self, batch: RequestBatch, khash: np.ndarray,
                      now_ms: int) -> tuple:
        """Columnar twin of ``check_batch`` (the wire lane's GLOBAL
        path): numpy RequestBatch columns in, response columns out —
        (status, remaining, reset_time, limit, row_lost) arrays.  Any
        replica answers; placement round-robins across chips."""
        n_req = len(khash)
        status = np.zeros(n_req, np.int64)
        rem = np.zeros(n_req, np.int64)
        rst = np.zeros(n_req, np.int64)
        lim = np.zeros(n_req, np.int64)
        lost = np.zeros(n_req, bool)
        W = self.n * self.B
        # earliest requests take the earliest waves (same rule as
        # check_packed): merged batches spanning instants keep per-key
        # time monotone across internal waves too
        by_time = np.argsort(np.asarray(batch.now), kind="stable")
        done = 0
        while done < n_req:
            m = min(W, n_req - done)
            idx = by_time[done:done + m]  # original indices, time order
            p = np.arange(m)
            chip = (self._rr + p) % self.n
            self._rr += m
            # fill order per chip → block positions [chip·B + row]
            order = np.argsort(chip, kind="stable")
            cs = chip[order]
            starts = np.searchsorted(cs, np.arange(self.n))
            rowin = np.empty(m, np.int64)
            rowin[order] = np.arange(m) - starts[cs]
            positions = chip * self.B + rowin
            glob = empty_batch(W)
            for f in range(len(glob)):
                np.asarray(glob[f])[positions] = np.asarray(batch[f])[idx]
            o_st, o_rem, o_rst, o_lim, o_err = self._run_hot_wave(
                glob, now_ms)
            status[idx] = o_st[positions]
            rem[idx] = o_rem[positions]
            rst[idx] = o_rst[positions]
            lim[idx] = o_lim[positions]
            lost[idx] = o_err[positions]
            done += m
        return status, rem, rst, lim, lost

    # ---- the tick -------------------------------------------------------

    def sync(self) -> None:
        """Fold all replicas' consumption: ONE psum replaces the
        reference's hit-queue flush + owner broadcast round-trip."""
        with self._state_mu:
            self.state, self.base_rem, self.base_t = self._sync(
                self.state, self.base_rem, self.base_t)
        self.sync_count += 1

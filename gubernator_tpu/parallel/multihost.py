"""Multi-host mesh bootstrap: jax.distributed over DCN.

SURVEY.md §5.8 — the reference's distributed comm backend is gRPC
between daemons.  Here the traffic classes map to:

- intra-pod: ICI collectives under shard_map (sharded.py / hotset.py),
- multi-pod / multi-region: daemon-level peering over the reference
  wire protocol (peer_client.py, global_manager.py, multiregion.py),
- multi-HOST pods (one logical engine spanning hosts, e.g. a v5e-256):
  this module — `jax.distributed` process bootstrap + a global mesh
  whose collectives ride ICI within a host/pod slice and DCN across,
  exactly where XLA places them.

The single-host engines compose with this unchanged: a shard_map
program over `global_mesh()` runs SPMD on every participating process,
psum/pmax folds cross host boundaries transparently.  What stays
host-local is request ingest — each daemon feeds its addressable
shards (`process_local_batch`), which is the same "every daemon owns
its slice of the key space" contract the reference has, with the
collectives replacing its gRPC fan-out.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from .mesh import SHARD_AXIS


def initialize(coordinator_address: str, num_processes: int,
               process_id: int,
               local_device_count: Optional[int] = None) -> None:
    """Join (or form) a multi-process JAX cluster.

    ``coordinator_address`` is ``host:port`` of process 0 — the analog
    of the reference's peer-discovery seed.  For CPU-based tests, set
    ``local_device_count`` to force that many virtual devices per
    process (must happen before the backend initializes).
    """
    import os

    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{local_device_count}").strip()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def global_mesh(axis: str = SHARD_AXIS) -> jax.sharding.Mesh:
    """1-D mesh over every device in the cluster (all hosts)."""
    return jax.sharding.Mesh(np.asarray(jax.devices()), (axis,))


def process_local_batch(mesh: jax.sharding.Mesh, host_cols, shape,
                        spec=None):
    """Assemble a globally-sharded array from THIS process's slice
    (jax.make_array_from_process_local_data) — the multi-host analog of
    the single-host ``device_put(batch, NamedSharding(...))``: every
    daemon contributes the sub-batch for the shards it hosts.
    ``spec`` overrides the default first-axis sharding (packed wire
    lanes are [cols, B] — sharded on axis 1, P(None, shard)).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, spec if spec is not None
                             else P(SHARD_AXIS))
    return jax.make_array_from_process_local_data(sharding, host_cols,
                                                  shape)

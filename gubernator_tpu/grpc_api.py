"""gRPC service registration and client stubs.

The reference ships protoc-generated gRPC bindings (*.pb.go /
python pb2_grpc — SURVEY.md §2.1 "Wire protocol"); grpc_tools isn't
available in this image, so the equivalent wiring is written by hand on
grpc's generic-handler API.  Wire format and method paths are identical
to generated code: /pb.gubernator.V1/... and /pb.gubernator.PeersV1/...
"""
from __future__ import annotations

from typing import Optional

import grpc

from .proto import gubernator_pb2 as pb
from .proto import peers_pb2 as peers_pb

V1_SERVICE = "pb.gubernator.V1"
PEERS_SERVICE = "pb.gubernator.PeersV1"


def add_v1_servicer(server: grpc.Server, servicer) -> None:
    """servicer: object with GetRateLimits(req, ctx) / HealthCheck(req, ctx)
    taking and returning pb2 messages."""
    handlers = {
        "GetRateLimits": grpc.unary_unary_rpc_method_handler(
            servicer.GetRateLimits,
            request_deserializer=pb.GetRateLimitsReq.FromString,
            response_serializer=pb.GetRateLimitsResp.SerializeToString),
        "HealthCheck": grpc.unary_unary_rpc_method_handler(
            servicer.HealthCheck,
            request_deserializer=pb.HealthCheckReq.FromString,
            response_serializer=pb.HealthCheckResp.SerializeToString),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(V1_SERVICE, handlers),))


def add_v1_servicer_raw(server: grpc.Server, servicer) -> None:
    """Like add_v1_servicer, but GetRateLimits passes request/response as
    raw serialized bytes (servicer.GetRateLimitsWire(data, ctx) → bytes)
    so the C++ wire-ingest lane can skip pb2 entirely.  Wire format is
    unchanged — clients can't tell the difference."""
    handlers = {
        "GetRateLimits": grpc.unary_unary_rpc_method_handler(
            servicer.GetRateLimitsWire,
            request_deserializer=None,
            response_serializer=None),
        "HealthCheck": grpc.unary_unary_rpc_method_handler(
            servicer.HealthCheck,
            request_deserializer=pb.HealthCheckReq.FromString,
            response_serializer=pb.HealthCheckResp.SerializeToString),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(V1_SERVICE, handlers),))


def add_peers_servicer(server: grpc.Server, servicer) -> None:
    """servicer: object with GetPeerRateLimits / UpdatePeerGlobals."""
    handlers = {
        "GetPeerRateLimits": grpc.unary_unary_rpc_method_handler(
            servicer.GetPeerRateLimits,
            request_deserializer=peers_pb.GetPeerRateLimitsReq.FromString,
            response_serializer=peers_pb.GetPeerRateLimitsResp.SerializeToString),
        "UpdatePeerGlobals": grpc.unary_unary_rpc_method_handler(
            servicer.UpdatePeerGlobals,
            request_deserializer=peers_pb.UpdatePeerGlobalsReq.FromString,
            response_serializer=peers_pb.UpdatePeerGlobalsResp.SerializeToString),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(PEERS_SERVICE, handlers),))


def add_peers_servicer_raw(server: grpc.Server, servicer) -> None:
    """Like add_peers_servicer, but GetPeerRateLimits passes raw bytes
    (servicer.GetPeerRateLimitsWire(data, ctx) → bytes) for the C++ wire
    lane.  UpdatePeerGlobals keeps pb2 (cold path)."""
    handlers = {
        "GetPeerRateLimits": grpc.unary_unary_rpc_method_handler(
            servicer.GetPeerRateLimitsWire,
            request_deserializer=None,
            response_serializer=None),
        "UpdatePeerGlobals": grpc.unary_unary_rpc_method_handler(
            servicer.UpdatePeerGlobals,
            request_deserializer=peers_pb.UpdatePeerGlobalsReq.FromString,
            response_serializer=peers_pb.UpdatePeerGlobalsResp.SerializeToString),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(PEERS_SERVICE, handlers),))


def add_health_servicer(server: grpc.Server, instance) -> None:
    """Standard ``grpc.health.v1.Health/Check`` — what Kubernetes gRPC
    probes and ``grpc_health_probe`` speak (the reference daemon
    registers the stock health server alongside its own HealthCheck
    RPC).  Hand-rolled wire, matching the rest of this module: the
    request (field 1: service name) is accepted for any service and
    answered with the daemon's overall health; the response is field 1
    varint ServingStatus (1 = SERVING, 2 = NOT_SERVING).

    ``Watch`` (server-streaming) emits the current status immediately,
    then a new message on every status CHANGE — the stock health
    server's contract, ended by client cancel/teardown.  A sync gRPC
    server necessarily parks one worker thread per open stream (the
    stock sync grpcio health servicer does too), so concurrent watchers
    are CAPPED at 4 — beyond that Watch aborts RESOURCE_EXHAUSTED
    (probes should poll Check) — and all watchers share ONE 1 Hz status
    poller that reads the cheap ``instance.health_status()`` (async-
    manager error state only; no device work, no metrics writes) and
    broadcasts changes over a condition."""
    import threading

    def _status() -> bytes:
        if hasattr(instance, "health_status"):
            ok = instance.health_status() == "healthy"
        else:  # pragma: no cover - legacy instance objects
            ok = instance.health_check().status == "healthy"
        return bytes([0x08, 0x01 if ok else 0x02])

    def check(request: bytes, context):
        return _safe_status()

    cond = threading.Condition()
    #: "poller" holds the Thread object of the ONE live poller (or
    #: None): ownership is identity-checked under the lock, so a
    #: dying poller can never clear a replacement's claim
    state = {"cur": None, "watchers": 0, "poller": None}
    MAX_WATCHERS = 4
    NOT_SERVING = bytes([0x08, 0x02])

    def _safe_status() -> bytes:
        try:
            return _status()
        except Exception:  # noqa: BLE001 - a failing status source IS
            # the unhealthy signal; both the poller and watch() must
            # outlive it or watchers go deaf / leak their slot
            return NOT_SERVING

    def _poller():
        import time as _time

        me = threading.current_thread()
        try:
            while True:
                with cond:
                    if state["watchers"] == 0 or state["poller"] is not me:
                        # release the claim HERE, atomically with the
                        # exit decision: a watcher arriving after this
                        # lock drops must see no live claim and start a
                        # replacement (the finally alone would race it)
                        if state["poller"] is me:
                            state["poller"] = None
                        return  # last watcher left (or we were replaced)
                cur = _safe_status()
                with cond:
                    if cur != state["cur"]:
                        state["cur"] = cur
                        cond.notify_all()
                _time.sleep(1.0)
        finally:
            # clear ONLY our own claim, atomically — a later watcher
            # can then start a replacement; never stomp a successor's
            with cond:
                if state["poller"] is me:
                    state["poller"] = None

    def watch(request: bytes, context):
        with cond:
            if state["watchers"] >= MAX_WATCHERS:
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              "too many health watchers; poll Check")
            state["watchers"] += 1
            try:
                if state["cur"] is None:
                    state["cur"] = _safe_status()
                alive = state["poller"]
                if alive is None or not alive.is_alive():
                    t = threading.Thread(target=_poller, daemon=True,
                                         name="health-watch-poller")
                    state["poller"] = t
                    t.start()  # on failure: claim stays on a never-
                    # started thread; is_alive() is False so the next
                    # watcher restarts it
            except BaseException:
                # the decrementing finally below doesn't exist yet —
                # give the slot back or it leaks toward the cap
                state["watchers"] -= 1
                raise
        last = None
        try:
            while context.is_active():
                with cond:
                    if state["cur"] != last:
                        last = out = state["cur"]
                    else:
                        cond.wait(timeout=5.0)
                        continue
                yield out
        finally:
            with cond:
                state["watchers"] -= 1

    handlers = {
        "Check": grpc.unary_unary_rpc_method_handler(
            check, request_deserializer=None, response_serializer=None),
        "Watch": grpc.unary_stream_rpc_method_handler(
            watch, request_deserializer=None, response_serializer=None),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler("grpc.health.v1.Health",
                                              handlers),))


class V1Stub:
    """Client stub for the V1 service (generated-code equivalent)."""

    def __init__(self, channel: grpc.Channel):
        self.GetRateLimits = channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetRateLimitsResp.FromString)
        self.HealthCheck = channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=pb.HealthCheckReq.SerializeToString,
            response_deserializer=pb.HealthCheckResp.FromString)


class PeersV1Stub:
    """Client stub for the PeersV1 service."""

    def __init__(self, channel: grpc.Channel):
        self.GetPeerRateLimits = channel.unary_unary(
            f"/{PEERS_SERVICE}/GetPeerRateLimits",
            request_serializer=peers_pb.GetPeerRateLimitsReq.SerializeToString,
            response_deserializer=peers_pb.GetPeerRateLimitsResp.FromString)
        self.UpdatePeerGlobals = channel.unary_unary(
            f"/{PEERS_SERVICE}/UpdatePeerGlobals",
            request_serializer=peers_pb.UpdatePeerGlobalsReq.SerializeToString,
            response_deserializer=peers_pb.UpdatePeerGlobalsResp.FromString)


def raw_unary(channel: grpc.Channel, method: str):
    """bytes-in/bytes-out unary call handle on the peers service
    (identity serializers).  The columnar send lanes (peer_client.py ›
    _SendLane) ship concatenated TLV slices through these — wire format
    is identical to the typed stubs, just with zero pb2 objects on this
    side."""
    return channel.unary_unary(f"/{PEERS_SERVICE}/{method}")


def dial_peer(address: str, tls_creds: Optional[grpc.ChannelCredentials] = None
              ) -> grpc.Channel:
    """Open a channel to a peer (peer_client.go › dialPeer analog)."""
    opts = [("grpc.enable_retries", 1)]
    if tls_creds is not None:
        return grpc.secure_channel(address, tls_creds, options=opts)
    return grpc.insecure_channel(address, options=opts)
